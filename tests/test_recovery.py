"""Crash-safe control plane (ISSUE-20): orphan parking, the adopt
protocol, failover park-adoption, client resume, and journal-driven
restart recovery.

The house rule holds through a gateway crash: every recovered stream
is pinned BYTE-IDENTICAL to a no-crash control — an adopted parked
session resumes mid-stream with zero re-prefill and no attempt
charged, a re-run is charged exactly one attempt and regenerates the
same bytes (deterministic decode), and a request that finished into
the void comes back as its buffered result. The protocol half pins the
agent-side machinery: gateway silence freezes in-flight slots into
parked snapshots, the park TTL reaps them, the epoch fence makes
double-adoption impossible (409, never a second copy), and
``GET /v1/stream/<id>?offset=`` serves the absolute token sequence on
both edges.

In-process agents speak REAL HTTP over localhost (same trick as
test_remote); ``Gateway.kill()`` dies the way SIGKILL would — no
drain, no journal compaction, no epoch bumps. The subprocess flavor
(actual ``kill -9`` on a CLI gateway) runs in ``make recovery-smoke``.
Engines are throttled with a wedge fault (30 ms per dispatch,
token-exact preserved) so mid-stream windows exist on a tiny model.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway import journal as jr
from tony_tpu.gateway.core import Gateway, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import Request, Server
from tony_tpu.serve.faults import FaultPlan

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompt(seed=5, n=11):
    return np.random.default_rng(seed).integers(1, 64, size=n).tolist()


def _slow():
    # 30 ms per dispatch: a 40-token stream stays in flight ~1.2 s,
    # wide enough to crash/park/adopt mid-stream deterministically
    return FaultPlan.wedge_at(1, 0.03, times=-1)


def _mk(tiny, **kw):
    model, params = tiny
    kw.setdefault("prefix_cache_mb", 0)
    kw.setdefault("batch_size", 2)
    kw.setdefault("min_bucket", 8)
    # one token per dispatch (the wedge meters REAL wall time per
    # token) and paged KV (wire snapshots gather page content)
    kw.setdefault("chunk_steps", 1)
    kw.setdefault("paged", True)
    kw.setdefault("kv_page_size", 8)
    return Server(model, params, eos_id=-1, **kw)


def _control(tiny, prompt, budget):
    srv = _mk(tiny)
    srv.submit(Request(list(prompt), budget, id="c"))
    return list(list(srv.run())[0].tokens)


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _start_agent(tiny, **agent_kw):
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    server_kw = agent_kw.pop("server_kw", {})
    server_kw.setdefault("fault_plan", _slow())
    return AgentHTTP(ReplicaAgent(_mk(tiny, **server_kw), **agent_kw),
                     port=0).start()


def _stub(address, **kw):
    from tony_tpu.gateway.remote import RemoteServer

    kw.setdefault("heartbeat_interval_s", 0.1)
    kw.setdefault("lease_misses", 3)
    kw.setdefault("read_timeout_s", 2.0)
    kw.setdefault("boot_timeout_s", 20.0)
    return RemoteServer(address, **kw)


# ---------------------------------------------- park/adopt protocol


class TestParkAdopt:
    def test_gateway_silence_parks_then_adopt_token_exact(self, tiny):
        """The watchdog story end to end: gateway contact goes silent
        past the grace -> the live slot freezes into a parked wire
        snapshot with real progress; a fresh engine adopting that
        snapshot finishes the stream byte-identical to an uninterrupted
        control. The epoch fence then guarantees single ownership: a
        second adopter on a stale epoch gets 409, and the handed-out
        session is gone even for the current epoch."""
        from tony_tpu.serve.agent import ReplicaAgent, _StaleEpoch

        prompt, budget = _prompt(), 40
        expect = _control(tiny, prompt, budget)
        agent = ReplicaAgent(_mk(tiny, fault_plan=_slow()),
                             gateway_grace_s=0.3, park_ttl_s=60).start()
        try:
            agent.submit({"prompt": prompt, "max_new_tokens": budget,
                          "id": "p1", "rid": "rid-1", "epoch": 0})
            _wait(lambda: any(not r["finished"] and r["rid"] == "rid-1"
                              for r in agent.parked()["parked"]),
                  msg="watchdog parking the orphaned slot")
            row = [r for r in agent.parked()["parked"]
                   if r["rid"] == "rid-1"][0]
            assert row["offset"] > 0  # froze MID-stream, not at admit
            resp = agent.adopt({"id": "rid-1", "epoch": agent.epoch + 1})
            assert resp["found"] and not resp.get("finished")
            snap = resp["snapshot"]
            assert resp["offset"] == len(snap["generated"]) > 0
            # stale second adopter: fenced, never a second copy
            with pytest.raises(_StaleEpoch):
                agent.adopt({"id": "rid-1", "epoch": agent.epoch - 1})
            # current epoch, but the session was already handed out
            assert not agent.adopt({"id": "rid-1",
                                    "epoch": agent.epoch})["found"]
            adopter = _mk(tiny)
            adopter.submit(Request(list(prompt), budget, id="p1",
                                   migrate=snap))
            res = list(adopter.run())[0]
            assert list(res.tokens) == expect
        finally:
            agent.stop()

    def test_adopt_freezes_still_live_slot_on_the_spot(self, tiny):
        """A recovering gateway must not wait out the watchdog grace:
        /v1/adopt on a rid still in a live decode slot freezes it
        right there and hands back the snapshot."""
        from tony_tpu.serve.agent import ReplicaAgent

        prompt, budget = _prompt(seed=7), 40
        expect = _control(tiny, prompt, budget)
        agent = ReplicaAgent(_mk(tiny, fault_plan=_slow())).start()
        try:
            agent.submit({"prompt": prompt, "max_new_tokens": budget,
                          "id": "p2", "rid": "rid-2", "epoch": 0})
            _wait(lambda: agent.server.n_active > 0, msg="slot active")
            assert agent.healthz()["n_parked"] == 0  # no watchdog ran
            resp = agent.adopt({"id": "rid-2", "epoch": 1})
            assert resp["found"] and resp.get("snapshot") is not None
            adopter = _mk(tiny)
            adopter.submit(Request(list(prompt), budget, id="p2",
                                   migrate=resp["snapshot"]))
            assert list(list(adopter.run())[0].tokens) == expect
        finally:
            agent.stop()

    def test_finished_undelivered_result_adoptable_once(self, tiny):
        """A request that finishes with nobody listening parks as its
        result; adoption returns the full buffered stream exactly
        once."""
        from tony_tpu.serve.agent import ReplicaAgent

        prompt, budget = _prompt(seed=9), 8
        expect = _control(tiny, prompt, budget)
        agent = ReplicaAgent(_mk(tiny)).start()
        try:
            agent.submit({"prompt": prompt, "max_new_tokens": budget,
                          "id": "p3", "rid": "rid-3", "epoch": 0})
            _wait(lambda: any(r["finished"] and r["rid"] == "rid-3"
                              for r in agent.parked()["parked"]),
                  msg="finished result parked")
            resp = agent.adopt({"id": "rid-3", "epoch": 1})
            assert resp["found"] and resp["finished"]
            assert list(resp["result"]["tokens"]) == expect
            assert not agent.adopt({"id": "rid-3",
                                    "epoch": agent.epoch})["found"]
        finally:
            agent.stop()

    def test_stale_incarnation_id_collision_readmits(self, tiny):
        """A restarted gateway's engine-id counter starts over, so its
        id 1 can collide with the DEAD incarnation's finished ticket
        (retained for the reconnect grace). The submit idempotence
        guard is epoch-scoped: the colliding newer-epoch submit must
        evict the stale record and run the new request — not echo
        `duplicate` and stream the old gateway's result."""
        from tony_tpu.serve.agent import ReplicaAgent

        prompt, budget = _prompt(seed=3), 40
        expect = _control(tiny, prompt, budget)
        agent = ReplicaAgent(_mk(tiny)).start()
        try:
            # incarnation 1 (epoch 0): id 1 runs to completion and its
            # finished ticket lingers within park_ttl_s
            agent.submit({"prompt": [7, 7], "max_new_tokens": 2,
                          "id": 1, "rid": "old-warm", "epoch": 0})
            _wait(lambda: agent._tickets[1].result is not None,
                  msg="incarnation-1 result buffered")
            # a same-epoch retry IS a duplicate (stub retry semantics)
            assert agent.submit({"prompt": [7, 7], "max_new_tokens": 2,
                                 "id": 1, "rid": "old-warm",
                                 "epoch": 0})["duplicate"]
            # incarnation 2 (epoch 1): same id, different request
            resp = agent.submit({"prompt": prompt,
                                 "max_new_tokens": budget,
                                 "id": 1, "rid": "new-r1", "epoch": 1})
            assert "duplicate" not in resp
            _wait(lambda: agent._tickets[1].result is not None,
                  msg="incarnation-2 result")
            got = agent._tickets[1]
            assert got.rid == "new-r1"
            assert list(got.result["tokens"]) == expect
        finally:
            agent.stop()

    def test_channel_never_serves_stale_epoch_ticket(self, tiny):
        """A reconnecting channel's resume map names engine ids the
        NEW gateway incarnation assigned, but the agent may still hold
        a DEAD incarnation's finished ticket under a colliding id
        until the in-flight submit evicts it. The channel must skip
        the stale record while it waits — streaming its tokens or
        done-result would land ANOTHER request's output on the fresh
        stream (the recovery-smoke truncation bug: a resumed stream
        went terminal with the dead gateway's warmup metrics)."""
        from tony_tpu.serve.agent import ReplicaAgent

        prompt, budget = _prompt(seed=9), 24
        expect = _control(tiny, prompt, budget)
        agent = ReplicaAgent(_mk(tiny), keepalive_s=0.05).start()
        try:
            # incarnation 1 (epoch 0): id 1 finished, undelivered
            agent.submit({"prompt": [7, 7], "max_new_tokens": 2,
                          "id": 1, "rid": "old-warm", "epoch": 0})
            _wait(lambda: agent._tickets[1].result is not None,
                  msg="stale finished ticket")
            # the restarted gateway fences to epoch 1, and its channel
            # reconnect names id 1 BEFORE the evicting submit lands
            agent.check_epoch(1)
            gen = agent.channel_events({1: 0}, epoch=1)
            assert next(gen)["channel"]
            early = [next(gen) for _ in range(3)]
            assert all(f.get("keepalive") for f in early), early
            # the evicting submit lands: the SAME channel now streams
            # the fresh request from offset 0 — never the warm result
            agent.submit({"prompt": prompt, "max_new_tokens": budget,
                          "id": 1, "rid": "new-r1", "epoch": 1})
            toks, done = [], None
            deadline = time.monotonic() + 30
            while done is None and time.monotonic() < deadline:
                f = next(gen)
                if f.get("keepalive"):
                    continue
                if f.get("done"):
                    done = f
                    break
                assert f.get("rid") == 1 and "token_ids" in f, f
                assert f["off"] == len(toks)
                toks.extend(f["token_ids"])
            assert done is not None and done["rid"] == 1
            assert toks == expect
            assert list(done["result"]["tokens"]) == expect
        finally:
            agent.stop()

    def test_park_ttl_reaps(self, tiny):
        """Nobody came back: a parked snapshot past the TTL is reaped
        (the pages were gathered to host memory at freeze time, so the
        reap is a dict delete) and a late adopter gets found=false —
        the 404 that tells a recovering gateway to re-run from the
        prompt."""
        from tony_tpu.serve.agent import ReplicaAgent

        agent = ReplicaAgent(_mk(tiny, fault_plan=_slow()),
                             gateway_grace_s=0.2,
                             park_ttl_s=0.5).start()
        try:
            agent.submit({"prompt": _prompt(), "max_new_tokens": 40,
                          "id": "p4", "rid": "rid-4", "epoch": 0})
            # NB: poll parked(), not healthz() — healthz IS gateway
            # contact and would keep resetting the silence clock
            _wait(lambda: len(agent._parked) >= 1, msg="parking")
            _wait(lambda: len(agent._parked) == 0, msg="TTL reap")
            assert not agent.adopt({"id": "rid-4",
                                    "epoch": 1})["found"]
        finally:
            agent.stop()


# ------------------------------------- failover park-adoption (R4)


def test_failover_adopts_parked_session_token_exact(tiny):
    """The ROADMAP-4 residue: a lease that expires because the
    GATEWAY-SIDE heartbeat flapped (not because the agent died) leaves
    the agent holding a perfectly good live session. The failover must
    check the park lease FIRST and adopt it — pins: ONE attempt
    charged, the stream byte-identical to a no-failure control, zero
    5xx, and the adoption visible in routing stats (the zero-re-prefill
    witness: the session crossed as a snapshot, not a prompt)."""
    prompt, budget = _prompt(seed=11), 40
    expect = _control(tiny, prompt, budget)
    agents = [_start_agent(tiny), _start_agent(tiny)]
    stubs = [_stub(a.address) for a in agents]
    gw = Gateway(stubs, stall_timeout_s=10.0, breaker_base_s=0.05,
                 breaker_max_s=0.25).start()
    try:
        ticket = gw.submit(GenRequest(list(prompt),
                                      max_new_tokens=budget, id="fo"))
        _wait(lambda: ticket._n_emitted >= 3, msg="mid-stream")
        src = ticket.replica
        assert src is not None
        # sever ONLY the lease ping: heartbeats still reach the agent
        # (its watchdog never fires) but the monitor starves and
        # declares the replica dead — the transport-flap shape
        stubs[src]._monitor.register = lambda *a, **kw: None
        res = ticket.result(timeout=120)
        assert list(res.tokens) == expect
        assert ticket.metrics["attempts"] == 1  # exactly one charged
        snap = gw.snapshot()
        assert snap["shed"] == {}  # zero 5xx
        assert snap["routing"]["park_adoptions"] >= 1
        assert snap["routing"]["migrations"] >= 1
    finally:
        gw.drain(timeout=60)
        for a in agents:
            a.stop()


# -------------------------------------------- client resume (edges)


@pytest.fixture(params=["event", "threaded"])
def resume_edge(tiny, request):
    from tony_tpu.gateway import GatewayEdge, GatewayHTTP

    gw = Gateway([_mk(tiny, fault_plan=_slow())], max_queue=8).start()
    edge = (GatewayEdge(gw) if request.param == "event"
            else GatewayHTTP(gw)).start()
    yield gw, f"http://{edge.host}:{edge.port}"
    gw.drain(timeout=60)
    edge.stop()


def _resume_lines(url, rid, offset=0, timeout=120):
    resp = urllib.request.urlopen(
        f"{url}/v1/stream/{rid}?offset={offset}", timeout=timeout)
    assert resp.status == 200
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    return [json.loads(ln) for ln in resp.read().decode().splitlines()
            if not json.loads(ln).get("keepalive")]


def test_resume_stream_absolute_offsets_both_edges(tiny, resume_edge):
    """GET /v1/stream/<id>?offset=N on both edges: a watcher joining
    mid-flight gets the absolute suffix from ITS OWN cursor plus the
    terminal line; N watchers of one request see the same bytes; the
    original consumer's event queue is never consumed. Unknown rids
    404, junk offsets 400."""
    gw, url = resume_edge
    prompt, budget = _prompt(seed=13), 24
    expect = _control(tiny, prompt, budget)
    ticket = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                  id="rs"))
    _wait(lambda: ticket._n_emitted >= 3, msg="mid-stream")
    got = {}

    def watch(offset):
        got[offset] = _resume_lines(url, "rs", offset)

    threads = [threading.Thread(target=watch, args=(off,))
               for off in (0, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for off in (0, 2):
        lines = got[off]
        assert lines[-1]["done"] and "metrics" in lines[-1]
        toks = [t for ln in lines[:-1] for t in ln["token_ids"]]
        assert toks == expect[off:]
        assert lines[0]["offset"] == off
    # the original consumer still gets its full stream: resume taps
    # the buffer, never the single-consumer queue
    assert list(ticket.result(timeout=120).tokens) == expect
    # a client who comes back AFTER the finish gets suffix + terminal
    late = _resume_lines(url, "rs", 5)
    assert [t for ln in late[:-1] for t in ln["token_ids"]] == expect[5:]
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/v1/stream/nope", timeout=30)
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/v1/stream/rs?offset=junk",
                               timeout=30)
    assert e.value.code == 400


# ------------------------------------------- journal-driven restart


def test_crash_recover_rerun_local_token_exact(tiny, tmp_path):
    """Local replicas died with the process — --recover re-runs every
    live journaled request from its prompt under the ORIGINAL id,
    charged exactly one attempt, byte-identical to a no-crash
    control."""
    prompts = [_prompt(seed=s) for s in (21, 22)]
    budget = 40
    expect = [_control(tiny, p, budget) for p in prompts]
    j1 = jr.TicketJournal(str(tmp_path / "j1.ndjson"))
    gw1 = Gateway([_mk(tiny, fault_plan=_slow())], journal=j1).start()
    tickets = [gw1.submit(GenRequest(list(p), max_new_tokens=budget,
                                     id=f"rr{i}"))
               for i, p in enumerate(prompts)]
    _wait(lambda: all(t._n_emitted >= 3 for t in tickets),
          msg="both mid-stream")
    gw1.kill()  # SIGKILL-shaped: no drain, no compaction
    entries = jr.replay(j1.path)
    assert sorted(rid for rid, e in entries.items() if e.live) \
        == ["rr0", "rr1"]
    j2 = jr.TicketJournal(str(tmp_path / "j2.ndjson"))
    gw2 = Gateway([_mk(tiny, fault_plan=_slow())], journal=j2).start()
    try:
        report = gw2.recover_from_journal(entries)
        assert report["rerun"] == 2 and report["adopted"] == 0
        assert report["shed"] == 0
        for i, exp in enumerate(expect):
            t = gw2.resume_ticket(f"rr{i}")
            assert t is not None
            res = t.result(timeout=120)
            assert list(res.tokens) == exp
            assert t.metrics["attempts"] == 1
        snap = gw2.snapshot()
        assert snap["shed"] == {}
        assert snap["recovery"]["recoveries"] == 1
        assert snap["recovery"]["sessions_rerun"] == 2
    finally:
        gw2.drain(timeout=60)
    # clean drain compacted THIS boot's journal down to nothing
    assert jr.replay(j2.path) == {}


def test_crash_recover_adopts_parked_and_finished(tiny, tmp_path):
    """THE in-process recovery anchor: gateway crashes mid-stream over
    two live agents; one request finishes into the void (parks as its
    result), one gets frozen by the agent watchdog (parks as a
    snapshot). The restarted gateway replays the WAL and adopts BOTH —
    the in-flight session resumes token-exact with zero re-prefill and
    no attempt charged, the finished one materializes terminal with
    its exact bytes, and a resuming client pulls byte-identical
    streams through the registry. Zero 5xx anywhere."""
    short_p, long_p = _prompt(seed=31), _prompt(seed=32)
    expect_short = _control(tiny, short_p, 8)
    expect_long = _control(tiny, long_p, 40)
    # grace wide enough for the short request's tail (~0.15s of wedged
    # decode) to FINISH into the void, narrow enough that the long one
    # (~1.1s left) parks as a snapshot — deterministic either side
    agents = [_start_agent(tiny, gateway_grace_s=0.5, park_ttl_s=60)
              for _ in range(2)]
    j1 = jr.TicketJournal(str(tmp_path / "j1.ndjson"))
    gw1 = Gateway([_stub(a.address) for a in agents],
                  journal=j1, park_ttl_s=60).start()
    ts = gw1.submit(GenRequest(list(short_p), max_new_tokens=8,
                               id="fin"))
    tl = gw1.submit(GenRequest(list(long_p), max_new_tokens=40,
                               id="mid"))
    _wait(lambda: ts._n_emitted >= 3 and tl._n_emitted >= 3,
          msg="both mid-stream")
    gw1.kill()
    entries = jr.replay(j1.path)
    assert entries["fin"].live and entries["mid"].live
    assert entries["mid"].offset >= 3  # emit rows made it to the WAL

    def rows():
        return [r for a in agents for r in a.agent.parked()["parked"]]

    # the short one FINISHES into the void; the long one is frozen by
    # the agent watchdog once the gateway goes silent past the grace
    _wait(lambda: any(r["finished"] and r["rid"] == "fin"
                      for r in rows())
          and any(not r["finished"] and r["rid"] == "mid"
                  for r in rows()),
          msg="agents parking the orphans")
    j2 = jr.TicketJournal(str(tmp_path / "j2.ndjson"))
    gw2 = Gateway([_stub(a.address) for a in agents],
                  journal=j2, park_ttl_s=60).start()
    try:
        report = gw2.recover_from_journal(entries)
        assert report["adopted"] == 1, report
        assert report["finished"] == 1, report
        assert report["rerun"] == 0 and report["shed"] == 0
        # the finished request: immediately terminal, exact bytes,
        # metrics flagged recovered with no attempt charged
        tf = gw2.resume_ticket("fin")
        assert list(tf.result(timeout=30).tokens) == expect_short
        assert tf.metrics["recovered"] and tf.metrics["attempts"] == 0
        # the adopted session: resumes mid-stream token-exact — and a
        # client resuming at its own (journal-lagged) offset gets the
        # exact suffix through resume_events
        tm = gw2.resume_ticket("mid")
        assert list(tm.result(timeout=120).tokens) == expect_long
        assert tm.attempts == 0  # adopted, never re-run
        toks = []
        for doc in gw2.resume_events("mid", offset=2):
            if doc.get("done"):
                break
            toks.extend(doc.get("token_ids", []))
        assert toks == expect_long[2:]
        snap = gw2.snapshot()
        assert snap["shed"] == {}  # zero 5xx
        assert snap["recovery"]["recoveries"] == 1
        assert snap["recovery"]["sessions_adopted"] == 1
        assert snap["recovery"]["recovered_finished"] == 1
        # zero re-prefill: the adopting ENGINE admitted the session as
        # a migrate-in (page install + sampler restore), not a prompt
        assert sum(a.agent.server.migrations_in for a in agents) >= 1
        # the recovery alert fired and carries the signal
        sig = gw2.alert_signals()
        assert sig["recovered_ago_s"] is not None
    finally:
        gw2.drain(timeout=60)
        for a in agents:
            a.stop()


def test_recover_unknown_host_reruns_and_shed_is_terminal(tiny,
                                                          tmp_path):
    """A journal whose host is gone (agent reaped the park, or never
    came back) re-runs from the prompt — the adopt 404 funnels into
    the rerun path, never an error. And a journaled terminal shed
    stays dead: replay must not resurrect it."""
    prompt, budget = _prompt(seed=41), 24
    expect = _control(tiny, prompt, budget)
    j1 = jr.TicketJournal(str(tmp_path / "j1.ndjson"))
    j1.admit("ghost", {"prompt": prompt, "max_new_tokens": budget,
                       "temperature": 0.0, "top_k": 0, "seed": 0},
             time.time())
    j1.route("ghost", 0, "127.0.0.1:1")  # a host nobody answers at
    j1.admit("dead", {"prompt": prompt, "max_new_tokens": 4},
             time.time())
    j1.shed("dead", 503)
    j1.close()
    entries = jr.replay(j1.path)
    gw2 = Gateway([_mk(tiny)]).start()
    try:
        report = gw2.recover_from_journal(entries)
        assert report["live"] == 1  # the shed entry never replays
        assert report["rerun"] == 1
        t = gw2.resume_ticket("ghost")
        assert list(t.result(timeout=120).tokens) == expect
        assert gw2.resume_ticket("dead") is None
    finally:
        gw2.drain(timeout=60)
