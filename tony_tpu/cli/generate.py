"""``tony-tpu generate`` — batch inference on a local HF checkpoint.

No reference analog (TonY orchestrates training jobs only); this is the
serving face of the framework's model stack: import a GPT-2/Llama/Mistral/
Qwen2 checkpoint directory (``models/hf.py``), run the jitted KV-cache
decode loop (``models/generate.py``), print completions. Fully offline —
the checkpoint and tokenizer are read from disk, nothing is downloaded.

    python -m tony_tpu.cli.generate --model ./my-llama \
        --prompt "Once upon a time" --max-new-tokens 64 \
        --temperature 0.8 --top-p 0.95

Raw-token mode (no tokenizer needed): ``--token-ids 1,2,3``.

Serving mode (``--serve``): the gateway core (``tony_tpu.gateway``
over ``tony_tpu.serve`` replicas) driven as a JSONL loop — one JSON
request per stdin line, one JSON response per finished request,
printed the moment it finishes while stdin is still being read.
Drivable without a TPU (JAX_PLATFORMS=cpu) and without a tokenizer
(token_ids requests). The network front door over the same core is
``python -m tony_tpu.cli.gateway``:

    printf '%s\n' '{"id": "a", "token_ids": [1, 2, 3]}' \
                  '{"id": "b", "prompt": "Hello", "max_new_tokens": 8}' \
        | python -m tony_tpu.cli.generate --model ./my-llama --serve

Request fields: ``token_ids`` or ``prompt``; optional ``id``,
``max_new_tokens``, ``temperature``, ``top_k``, ``seed`` (defaulting to
the CLI flags). Responses stream in FINISH order (short requests do not
wait on long ones — that is the point): ``{"id", "token_ids",
"finish_reason", "text"?}``.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu generate",
        description="Generate from a local HF checkpoint on TPU",
    )
    p.add_argument("--model", required=True,
                   help="local checkpoint directory (HF format)")
    p.add_argument("--prompt", action="append", default=[],
                   help="text prompt (repeatable; needs a tokenizer in the "
                        "model dir)")
    p.add_argument("--token-ids", action="append", default=[],
                   help="raw prompt as comma-separated ids (repeatable, "
                        "no tokenizer needed)")
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--num-beams", type=int, default=1,
                   help=">1 uses beam search (overrides sampling knobs)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--repetition-penalty", type=float, default=1.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--eos-id", type=int, default=-1,
                   help="stop token (default: model config's eos_token_id)")
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache: quantize-on-write with "
                        "per-(position, head) scales — halves the decode "
                        "cache HBM traffic (the dominant decode bytes at "
                        "long context). Recommended below ~2k live "
                        "cache tokens per sequence (1.27x e2e measured); "
                        "above that the in-scan VPU lowering favors the "
                        "bf16 cache (docs/PERF.md r5 context rule)")
    p.add_argument("--flash-decode", action="store_true",
                   help="use the pallas flash-decode kernel for "
                        "single-token decode steps (fused online-softmax "
                        "over the KV cache; int8-aware). Measured ~par "
                        "with the default einsum e2e (1.06x at cache "
                        "512, 0.95x with int8 at 3584 — docs/PERF.md "
                        "r5); the clear win case is VMEM-spill regimes "
                        "(very long caches x batch x heads). "
                        "Interpreted — slow — off TPU")
    p.add_argument("--int8", action="store_true",
                   help="serve with int8 weight-only quantization "
                        "(pallas dequant-matmul; half the weight bytes "
                        "per decode step)")
    p.add_argument("--dtype", choices=("fp32", "bf16"), default="fp32",
                   help="parameter storage dtype. Default fp32 keeps "
                        "bit-exact greedy parity with the torch "
                        "reference; pass bf16 for serving — decode is "
                        "bandwidth-bound on parameter bytes, so bf16 "
                        "storage halves per-token traffic (the standard "
                        "accelerator serving precision)")
    p.add_argument("--serve", action="store_true",
                   help="continuous-batching serving loop: JSONL "
                        "requests on stdin -> JSONL responses on stdout "
                        "(see module docstring). Requests multiplex onto "
                        "one resident KV cache; finished slots are "
                        "refilled the same iteration, so mixed-length "
                        "traffic never idles behind the longest sequence")
    p.add_argument("--serve-batch", type=int, default=4,
                   help="cache slots (resident batch size) in --serve "
                        "mode; bounds the KV-cache footprint")
    p.add_argument("--serve-replicas", type=int, default=1,
                   help="data-parallel engine replicas in --serve mode "
                        "(the gateway core drives one scheduler thread "
                        "per replica; the HTTP front door is "
                        "``tony-tpu gateway``)")
    p.add_argument("--prefix-cache-mb", type=float, default=64.0,
                   help="--serve mode: per-replica byte budget for the "
                        "prefix KV-cache store (shared prompt prefixes "
                        "skip the matched part of prefill; exact "
                        "repeats skip it entirely). 0 disables")
    p.add_argument("--speculate-k", type=int, default=0,
                   help="--serve mode: speculative decoding — up to K "
                        "prompt-lookup draft tokens verified per "
                        "batched dispatch (greedy outputs unchanged; "
                        "sampled requests decode normally). 0 disables")
    p.add_argument("--kv-page-size", type=int, default=0,
                   help="--serve mode: tokens per KV-cache page "
                        "(block-paged cache; 0 auto-sizes from "
                        "max_seq_len)")
    p.add_argument("--kv-pages", type=int, default=0,
                   help="--serve mode: KV page-pool size per replica "
                        "(0 auto-sizes: the unpaged-equivalent "
                        "footprint, grown into free HBM on TPU)")
    p.add_argument("--no-paged-kv", action="store_true",
                   help="--serve mode: fixed-shape per-slot cache rows "
                        "instead of the paged pool (A/B escape hatch; "
                        "sliding-window models downgrade automatically)")
    p.add_argument("--compile-cache",
                   default=os.path.join(os.path.expanduser("~"), ".cache",
                                        "tony_tpu", "compile-cache"),
                   help="persistent XLA compile-cache dir; decode programs "
                        "compile once per (model, length) ever, not once "
                        "per process ('' disables)")
    return p


def load_model(model_dir: str):
    """(Transformer, params, hf_config) from a local checkpoint dir."""
    import transformers

    from tony_tpu.models import (
        from_hf_gemma,
        from_hf_gpt2,
        from_hf_llama,
        from_hf_mixtral,
        from_hf_neox,
        from_hf_phi,
    )

    config = transformers.AutoConfig.from_pretrained(model_dir)
    hf = transformers.AutoModelForCausalLM.from_pretrained(model_dir)
    if config.model_type == "gpt2":
        model, params = from_hf_gpt2(hf)
    elif config.model_type in ("llama", "mistral", "qwen2"):
        model, params = from_hf_llama(hf)
    elif config.model_type == "gemma":
        model, params = from_hf_gemma(hf)
    elif config.model_type == "mixtral":
        model, params = from_hf_mixtral(hf)
    elif config.model_type == "gpt_neox":
        model, params = from_hf_neox(hf)
    elif config.model_type == "phi":
        model, params = from_hf_phi(hf)
    else:
        raise SystemExit(
            f"unsupported model_type {config.model_type!r} "
            "(supported: gpt2, llama, mistral, qwen2, gemma, mixtral, "
            "gpt_neox, phi)")
    return model, params, config


def resolve_paged_kv(args, model, batch_size: int,
                     n_replicas: int = 1) -> dict:
    """``Server(paged=..., kv_page_size=..., kv_pages=...)`` kwargs from
    CLI args — shared with ``cli.gateway``, mirroring the
    ``resolve_prefix_cache_mb`` precedent: the feature defaults ON, so
    the CLIs degrade (stderr note) instead of crashing on model configs
    the engine refuses (sliding-window attention), and ``--kv-pages 0``
    auto-sizes the per-replica pool: the unpaged-equivalent footprint
    (``batch x ceil(max_seq_len / page_size)`` — capacity parity) as
    the floor, grown toward half the free HBM TpuDiscoverer reports
    SPLIT ACROSS the ``n_replicas`` pools that will coexist (capped at
    4x the floor) when a TPU is present — the freed fixed-shape waste
    is exactly what bigger batches grow into."""
    if getattr(args, "no_paged_kv", False):
        return {"paged": False}
    if model.cfg.sliding_window:
        print("note: paged KV cache disabled (untested over "
              "sliding-window attention)", file=sys.stderr)
        return {"paged": False}
    from tony_tpu.serve.slots import default_page_size, kv_page_nbytes

    cfg = model.cfg
    ps = int(getattr(args, "kv_page_size", 0) or 0) \
        or default_page_size(cfg)
    ps = max(1, min(ps, cfg.max_seq_len))
    pages = int(getattr(args, "kv_pages", 0) or 0)
    if pages <= 0:
        base = batch_size * (-(-cfg.max_seq_len // ps))
        pages = base
        try:
            from tony_tpu.utils.tpu_info import TpuDiscoverer

            info = TpuDiscoverer().get_device_information()
            free = sum(c.hbm_total_bytes - c.hbm_used_bytes
                       for c in info.chips)
            if free > 0:
                hbm_pages = int(free * 0.5 / max(1, n_replicas)) \
                    // kv_page_nbytes(cfg, ps)
                pages = max(base, min(4 * base, hbm_pages))
        except Exception:  # noqa: BLE001 — no TPU / no tpu-info binary:
            pass           # the capacity-parity floor is always safe
    return {"paged": True, "kv_page_size": ps, "kv_pages": pages}


def resolve_prefix_cache_mb(args, model) -> float:
    """``--prefix-cache-mb``, downgraded to 0 (with a stderr note) for
    model configs the prefix store refuses — the flag defaults ON, so
    the CLIs must degrade instead of crashing on e.g. Mistral's
    sliding-window attention. Shared with ``cli.gateway``."""
    mb = getattr(args, "prefix_cache_mb", 0.0)
    if mb > 0 and model.cfg.sliding_window:
        print("note: prefix cache disabled (untested over "
              "sliding-window attention)", file=sys.stderr)
        return 0.0
    return mb


def _serve_loop(model, params, args, eos) -> int:
    """``--serve``: read JSONL requests from stdin until EOF, stream one
    JSONL response per finished request (finish order, not submit
    order). Token-id requests need no tokenizer; the first ``prompt``
    request lazy-loads one from the model dir.

    Runs over the gateway core (``tony_tpu.gateway``): requests decode
    on ``--serve-replicas`` worker threads WHILE stdin is still being
    read, responses print the moment they finish, and a full admission
    queue blocks the stdin reader (natural pipe backpressure) instead
    of growing without bound."""
    import json
    import threading
    import time

    from tony_tpu.gateway import Gateway, GatewayQueueFull, GenRequest
    from tony_tpu.serve import FaultPlan, Server

    n_replicas = max(1, getattr(args, "serve_replicas", 1))
    prefix_mb = resolve_prefix_cache_mb(args, model)
    paged_kw = resolve_paged_kv(args, model, args.serve_batch,
                                n_replicas=n_replicas)
    # same chaos hook as the gateway CLI: TONY_SERVE_FAULTS arms
    # deterministic per-replica fault injection (serve/faults.py)
    servers = [Server(model, params["params"],
                      batch_size=args.serve_batch, eos_id=eos,
                      prefix_cache_mb=prefix_mb,
                      speculate_k=args.speculate_k,
                      fault_plan=FaultPlan.from_env(replica=i),
                      **paged_kw)
               for i in range(n_replicas)]
    armed = [i for i, s in enumerate(servers) if s.fault_plan is not None]
    if armed:
        # loud, like the gateway CLI: a TONY_SERVE_FAULTS leftover from
        # a chaos run must not silently sabotage a real serve loop
        print(f"fault injection ARMED on replica(s) {armed} via "
              "TONY_SERVE_FAULTS", file=sys.stderr)
    gateway = Gateway(servers,
                      max_queue=max(64, 32 * n_replicas)).start()
    tokenizer = None
    n_bad = 0
    n_shed = 0
    out_lock = threading.Lock()

    def on_event(ticket, event):
        nonlocal n_shed
        if event[0] == "done":
            res = event[1]
            new_ids = res.tokens
            stops = [i for i, t in enumerate(new_ids) if t in eos]
            if stops:  # mirror the batch CLI: trim at the first stop
                new_ids = new_ids[:stops[0]]
            out = {"id": res.id, "token_ids": list(res.prompt) + new_ids,
                   "finish_reason": res.finish_reason}
            if tokenizer is not None:
                out["text"] = tokenizer.decode(out["token_ids"])
            with out_lock:
                print(json.dumps(out), flush=True)
        elif event[0] == "shed":
            n_shed += 1
            print(f"request {ticket.request.id} shed: {event[2]}",
                  file=sys.stderr)

    for lineno, raw in enumerate(sys.stdin, 1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            d = json.loads(raw)
            if not isinstance(d, dict):
                raise ValueError("request must be a JSON object")
            if "token_ids" in d:
                ids = [int(x) for x in d["token_ids"]]
            elif "prompt" in d:
                if tokenizer is None:
                    import transformers

                    tokenizer = transformers.AutoTokenizer.from_pretrained(
                        args.model)
                ids = tokenizer.encode(d["prompt"])
            else:
                raise ValueError("request needs token_ids or prompt")
            req = GenRequest(
                ids,
                int(d.get("max_new_tokens", args.max_new_tokens)),
                temperature=float(d.get("temperature", args.temperature)),
                top_k=int(d.get("top_k", args.top_k)),
                seed=int(d.get("seed", args.seed)),
                id=d.get("id"))
            while True:
                try:
                    gateway.submit(req, on_event=on_event)
                    break
                except GatewayQueueFull:
                    time.sleep(0.01)  # pipe backpressure, not rejection
        except Exception as e:  # noqa: BLE001 — a malformed
            # line (bad JSON, wrong shapes, a prompt with no tokenizer
            # in the model dir, an oversized prompt) must not kill the
            # stream and strand every queued request: report, skip
            print(f"request line {lineno} rejected: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            n_bad += 1
    gateway.drain()
    return 0 if n_bad == 0 and n_shed == 0 else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.serve and not args.prompt and not args.token_ids:
        print("need --prompt or --token-ids", file=sys.stderr)
        return 2

    if args.compile_cache:
        from tony_tpu.utils import compilecache

        compilecache.enable(args.compile_cache)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import generate

    model, params, config = load_model(args.model)
    if args.dtype == "bf16" and args.int8:
        print("note: --int8 supplies its own storage format; "
              "--dtype bf16 is ignored", file=sys.stderr)
    if args.dtype == "bf16" and not args.int8:
        # cast ONCE at load: flax would otherwise re-read fp32 kernels
        # from HBM every decode step and cast per-use. Inspect x.dtype
        # directly — np.asarray would pull every leaf to host first.
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)
    if args.int8:
        from tony_tpu.models.quantize import quantize_cli

        model, params = quantize_cli(model, params)
    if args.kv_int8 or args.flash_decode:
        import dataclasses

        from tony_tpu.models import Transformer

        model = Transformer(dataclasses.replace(
            model.cfg,
            kv_cache_quant=args.kv_int8,
            decode_attention="flash" if args.flash_decode
            else model.cfg.decode_attention))

    tokenizer = None
    if args.prompt:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(args.model)
    prompts = [tokenizer.encode(t) for t in args.prompt]
    prompts += [[int(i) for i in ids.split(",")] for ids in args.token_ids]

    from tony_tpu.models.generate import normalize_eos_ids

    # HF configs may ship a LIST of eos ids (Llama-3 instruct:
    # [128001, 128009]); the decode loops stop on ANY of them
    eos = normalize_eos_ids(args.eos_id) or \
        normalize_eos_ids(getattr(config, "eos_token_id", None))

    if args.serve:
        if args.int8:
            print("--serve does not support --int8 weight quantization "
                  "yet", file=sys.stderr)
            return 2
        if args.top_p < 1.0:
            print("warning: --top-p is not applied in --serve mode "
                  "(per-slot sampling supports temperature/top-k); "
                  "ignoring", file=sys.stderr)
        return _serve_loop(model, params, args, eos)

    from tony_tpu.models import beam_search

    if args.num_beams > 1 and args.repetition_penalty != 1.0:
        print("warning: --repetition-penalty is not applied under "
              "beam search; ignoring", file=sys.stderr)
    # GREEDY same-length prompts decode as one batch (no padding, so
    # absolute positions agree and greedy rows are independent) — one
    # compiled program and one KV-cache pass serve up to 32 prompts;
    # distinct lengths still compile once each. Sampled decode stays
    # per-prompt so a (prompt, --seed) pair reproduces the same text
    # regardless of what else is in the invocation; beam search's batch
    # dim is the beam.
    outputs: dict[int, list[int]] = {}
    if args.num_beams > 1:  # beam search's batch dim IS the beam
        for pos, ids in enumerate(prompts):
            out = beam_search(model, params["params"],
                              jnp.asarray([ids], jnp.int32),
                              max_new_tokens=args.max_new_tokens,
                              num_beams=args.num_beams, eos_id=eos)
            outputs[pos] = np.asarray(out)[0].tolist()
    else:
        batchable = args.temperature == 0.0
        by_len: dict[int, list[int]] = {}
        for pos, ids in enumerate(prompts):
            by_len.setdefault(len(ids) if batchable else pos, []).append(pos)
        max_group = 32  # bounds the batched KV-cache footprint
        for whole in by_len.values():
            for start in range(0, len(whole), max_group):
                group = whole[start:start + max_group]
                prompt_arr = jnp.asarray(
                    [prompts[pos] for pos in group], jnp.int32)
                out = generate(model, params["params"], prompt_arr,
                               max_new_tokens=args.max_new_tokens,
                               temperature=args.temperature,
                               top_k=args.top_k,
                               top_p=args.top_p, eos_id=eos,
                               repetition_penalty=args.repetition_penalty,
                               rng=jax.random.PRNGKey(args.seed))
                for row, pos in enumerate(group):
                    outputs[pos] = np.asarray(out)[row].tolist()
    for pos, ids in enumerate(prompts):  # print in input order
        new_ids = outputs[pos]
        stops = [i for i, t in enumerate(new_ids) if t in eos]
        if stops:
            new_ids = new_ids[:stops[0]]
        if tokenizer is not None:
            print(tokenizer.decode(ids + new_ids))
        else:
            print(",".join(str(i) for i in ids + new_ids))
    return 0


if __name__ == "__main__":
    sys.exit(main())
