"""Durable ticket journal (ISSUE-20): the gateway's write-ahead log.

Pure-python unit coverage for the WAL primitives restart recovery
rests on: replay is idempotent (same file, same map, twice), a torn
final line — the append a SIGKILL cut mid-write — is tolerated and
costs at most that one row, and compaction on clean drain drops every
terminated request so a cleanly-drained gateway leaves an empty
journal behind. The end-to-end story (SIGKILL the gateway, replay,
adopt) lives in test_gateway.py and the recovery smoke round.
"""

import json
import os

import pytest

from tony_tpu.gateway import journal as jr

REQ = {"prompt": [1, 2, 3], "max_new_tokens": 8, "temperature": 0.0,
       "top_k": 0, "seed": 0, "stream": True}


def _journal(tmp_path, fsync="batch"):
    return jr.TicketJournal(str(tmp_path / "journal.ndjson"),
                            fsync=fsync)


def test_fsync_policy_validated(tmp_path):
    with pytest.raises(ValueError, match="fsync policy"):
        jr.TicketJournal(str(tmp_path / "j.ndjson"), fsync="sometimes")


def test_roundtrip_live_entry(tmp_path):
    j = _journal(tmp_path)
    j.admit("r1", REQ, 123.0)
    j.route("r1", 2, "127.0.0.1:9999")
    j.emit("r1", 3)
    j.emit("r1", 7)
    j.close()
    entries = jr.replay(j.path)
    e = entries["r1"]
    assert e.live
    assert e.request["prompt"] == [1, 2, 3]
    assert e.request["max_new_tokens"] == 8
    assert e.replica == 2 and e.host == "127.0.0.1:9999"
    assert e.offset == 7          # max of the emit rows
    assert e.t_admit == 123.0


def test_terminal_rows_mark_dead(tmp_path):
    j = _journal(tmp_path)
    j.admit("done", REQ, 1.0)
    j.done("done")
    j.admit("shed", REQ, 2.0)
    j.shed("shed", 503)
    j.admit("live", REQ, 3.0)
    j.close()
    entries = jr.replay(j.path)
    assert not entries["done"].live
    assert not entries["shed"].live
    assert entries["live"].live


def test_replay_idempotent(tmp_path):
    j = _journal(tmp_path)
    j.admit("a", REQ, 1.0)
    j.route("a", 0, None)
    j.emit("a", 5)
    j.close()

    def shape(entries):
        return {rid: (e.live, e.offset, e.replica, e.host)
                for rid, e in entries.items()}

    first = shape(jr.replay(j.path))
    second = shape(jr.replay(j.path))
    assert first == second == {"a": (True, 5, 0, None)}


def test_torn_tail_tolerated(tmp_path):
    j = _journal(tmp_path)
    j.admit("a", REQ, 1.0)
    j.emit("a", 4)
    j.close()
    # SIGKILL mid-append: the final line is cut in half
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"ev": "emit", "rid": "a", "of')
    entries = jr.replay(j.path)
    assert entries["a"].live
    assert entries["a"].offset == 4   # the torn row is simply absent


def test_missing_file_is_empty(tmp_path):
    assert jr.replay(str(tmp_path / "nope.ndjson")) == {}


def test_compact_drops_terminated(tmp_path):
    j = _journal(tmp_path)
    for rid in ("a", "b", "c"):
        j.admit(rid, REQ, 1.0)
        j.route(rid, 1, "h:1")
    j.emit("a", 6)
    j.done("b")
    j.shed("c", 503)
    kept = j.compact()
    assert kept == 1
    entries = jr.replay(j.path)
    assert set(entries) == {"a"}
    assert entries["a"].offset == 6
    assert entries["a"].host == "h:1"
    # the journal keeps accepting appends after a compact
    j.done("a")
    assert j.compact() == 0
    j.close()
    assert jr.replay(j.path) == {}


def test_clean_drain_leaves_empty_file(tmp_path):
    j = _journal(tmp_path)
    j.admit("a", REQ, 1.0)
    j.done("a")
    j.close(compact=True)
    assert os.path.getsize(j.path) == 0
    assert jr.replay(j.path) == {}


def test_compact_is_atomic_rewrite(tmp_path):
    j = _journal(tmp_path)
    j.admit("a", REQ, 1.0)
    j.compact()
    assert not os.path.exists(j.path + ".tmp")
    j.close()


def test_fsync_off_still_durable_after_close(tmp_path):
    j = _journal(tmp_path, fsync="off")
    j.admit("a", REQ, 1.0)
    j.close()
    assert jr.replay(j.path)["a"].live


def test_find_latest_picks_newest(tmp_path):
    root = tmp_path / "history"
    for app, t in (("application_1", 100.0), ("application_2", 200.0)):
        d = root / "intermediate" / app
        d.mkdir(parents=True)
        p = d / "journal.ndjson"
        p.write_text(json.dumps({"ev": "admit", "rid": app}) + "\n")
        os.utime(p, (t, t))
    assert jr.find_latest(str(root)).endswith(
        os.path.join("application_2", "journal.ndjson"))
    assert jr.find_latest(str(tmp_path / "none")) is None
