"""Device-mesh construction for DP/FSDP/TP/PP/SP/EP parallelism.

New territory relative to the reference (SURVEY.md section 2.4: TonY has no
tensor/pipeline/sequence parallelism — it only orchestrates processes).
Here parallelism is expressed the TPU way: a named ``jax.sharding.Mesh``
over the slice, PartitionSpec annotations, and XLA-inserted collectives
riding ICI (scaling-book recipe: pick a mesh, annotate, let XLA insert
collectives).

Canonical axis names used across the framework:

  data    - data parallelism (batch sharding; gradient psum)
  fsdp    - fully-sharded data parallelism (param/optimizer sharding)
  tensor  - tensor/model parallelism (head & mlp sharding)
  pipe    - pipeline stages
  seq     - sequence/context parallelism (ring attention)
  expert  - expert parallelism (MoE all-to-all)
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

DATA, FSDP, TENSOR, PIPE, SEQ, EXPERT = "data", "fsdp", "tensor", "pipe", "seq", "expert"
ALL_AXES = (DATA, FSDP, TENSOR, PIPE, SEQ, EXPERT)


@dataclass
class MeshSpec:
    """Sizes per logical axis; -1 on exactly one axis means "absorb the
    remaining devices" (like a reshape wildcard)."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            DATA: self.data,
            FSDP: self.fsdp,
            TENSOR: self.tensor,
            PIPE: self.pipe,
            SEQ: self.seq,
            EXPERT: self.expert,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = self.sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one wildcard axis, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


def make_mesh(spec: MeshSpec | None = None, devices=None,
              drop_trivial: bool = False) -> Mesh:
    """Build the named mesh. Axis order is (data, fsdp, tensor, pipe, seq,
    expert) — outer axes map to DCN/slower links, inner axes to ICI, which
    is the layout that keeps tensor/seq collectives on the fastest rings.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    names = [a for a in ALL_AXES if not (drop_trivial and sizes[a] == 1)]
    shape = [sizes[a] for a in names]
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (DATA,))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


# ---------------------------------------------------------------------------
# Multi-slice (DCN x ICI) meshes. A multi-slice TPU job has fast ICI only
# *within* each slice; slices talk over DCN. The standard recipe (scaling
# book; reference analog is TonY's multi-cluster spec construction,
# SURVEY.md section 7.9c) is: put pure data parallelism on the DCN axis,
# keep model axes (fsdp/tensor/seq/expert) inside a slice on ICI.
# ---------------------------------------------------------------------------


def num_slices(devices=None) -> int:
    """Number of TPU slices in this job (1 on single-slice / CPU)."""
    devices = list(devices if devices is not None else jax.devices())
    ids = {getattr(d, "slice_index", 0) for d in devices}
    return max(len(ids), 1)


def multislice_mesh(spec: MeshSpec | None = None, *, devices=None,
                    dcn_axis: str = DATA, n_slices: int | None = None) -> Mesh:
    """Mesh whose ``dcn_axis`` additionally spans slices while every other
    axis stays within-slice (ICI). ``spec`` is resolved against the
    per-slice device count (a wildcard absorbs the per-slice remainder),
    then the ``dcn_axis`` is multiplied by the slice count — e.g. 2 slices
    of 16 chips with MeshSpec(data=-1, tensor=4) gives data=8 (4 per slice
    x 2 slices over DCN) x tensor=4 (ICI).

    Single-slice (or CPU test) degenerates to ``make_mesh`` — the same
    code runs everywhere. ``n_slices`` forces a slice count when the
    devices carry no ``slice_index`` (virtual CPU devices in tests and the
    driver's multichip dryrun): consecutive device groups then stand in
    for slices, stacked along ``dcn_axis``.
    """
    devices = list(devices if devices is not None else jax.devices())
    detected = num_slices(devices)
    n = n_slices or detected
    spec = spec or MeshSpec()
    if n == 1:
        return make_mesh(spec, devices=devices)
    if len(devices) % n:
        raise ValueError(f"{len(devices)} devices not divisible into {n} slices")
    per_slice = len(devices) // n
    ici_sizes = spec.resolve(per_slice)
    if detected == n:
        from jax.experimental import mesh_utils

        dcn_sizes = {a: (n if a == dcn_axis else 1) for a in ALL_AXES}
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=[ici_sizes[a] for a in ALL_AXES],
            dcn_mesh_shape=[dcn_sizes[a] for a in ALL_AXES],
            devices=devices,
        )
        return Mesh(arr, ALL_AXES)
    # virtual slices: per-slice sub-meshes concatenated along the dcn axis
    # (exercises the same shardings/collective structure minus the real
    # slice topology, which CPU devices cannot express)
    axis_idx = ALL_AXES.index(dcn_axis)
    shape = [ici_sizes[a] for a in ALL_AXES]
    subs = [np.array(devices[i * per_slice:(i + 1) * per_slice])
            .reshape(shape) for i in range(n)]
    return Mesh(np.concatenate(subs, axis=axis_idx), ALL_AXES)
