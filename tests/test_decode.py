"""Flash-decode kernel + int8 KV cache (docs/PERF.md decode roofline
"next lever"; VERDICT r3 next #2). CPU runs the pallas interpreter, so
these pin exactness, not speed."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.models.generate import generate
from tony_tpu.ops.decode import dequantize_kv, flash_decode, quantize_kv


def _ref_decode(q, k, v, length, window=0):
    """numpy reference: full softmax over valid cache positions."""
    b, h, d = q.shape
    s, kvh = k.shape[1], k.shape[2]
    g = h // kvh
    kr = np.repeat(np.asarray(k, np.float32), g, axis=2)
    vr = np.repeat(np.asarray(v, np.float32), g, axis=2)
    scores = np.einsum("bhd,bshd->bhs", np.asarray(q, np.float32),
                       kr) / np.sqrt(d)
    pos = np.arange(s)[None, None, :]
    ln = np.asarray(length).reshape(-1, 1, 1)
    vis = pos < ln
    if window > 0:
        vis = vis & (pos >= np.maximum(ln - window, 0))
    scores = np.where(vis, scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhs,bshd->bhd", p, vr)


@pytest.fixture(scope="module")
def qkv():
    b, s, h, kvh, d = 2, 64, 8, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kvh, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kvh, d))
    return q, k, v


@pytest.mark.parametrize("window", [0, 10])
def test_flash_decode_matches_reference(qkv, window):
    q, k, v = qkv
    length = jnp.asarray([37, 64], jnp.int32)
    out = flash_decode(q, k, v, length, window=window, block_k=16)
    ref = _ref_decode(q, k, v, length, window=window)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_flash_decode_scalar_length_and_full_mha(qkv):
    q, k, v = qkv
    # scalar length broadcasts; MHA path (kvh == h) via repeat
    kf = jnp.repeat(k, 4, axis=2)
    vf = jnp.repeat(v, 4, axis=2)
    out = flash_decode(q, kf, vf, 40, block_k=16)
    ref = _ref_decode(q, kf, vf, np.asarray([40, 40]))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_flash_decode_int8_cache(qkv):
    q, k, v = qkv
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    length = jnp.asarray([37, 64], jnp.int32)
    out = flash_decode(q, kq, vq, length, block_k=16, k_scale=ks,
                       v_scale=vs)
    # exact vs the dequantized reference (the kernel's math), close to fp
    ref_q = _ref_decode(q, dequantize_kv(kq, ks).astype(jnp.float32),
                        dequantize_kv(vq, vs).astype(jnp.float32), length)
    np.testing.assert_allclose(np.asarray(out), ref_q, atol=2e-5, rtol=2e-5)
    ref_fp = _ref_decode(q, k, v, length)
    np.testing.assert_allclose(np.asarray(out), ref_fp, atol=0.05, rtol=0.05)


def test_flash_decode_zero_length_slot_rows(qkv):
    """Per-slot lengths (serve/): a length-0 row — an EMPTY continuous-
    batching slot — must emit EXACT zeros (never NaN, never a uniform
    average of junk V tiles) while live rows stay exact. Covers the GQA
    kernel's never-ran accumulator and the MHA kernel's `valid` mask
    (decode.py _finalize)."""
    q, k, v = qkv
    length = jnp.asarray([0, 37], jnp.int32)
    out = np.asarray(flash_decode(q, k, v, length, block_k=16))
    assert (out[0] == 0).all()
    ref = _ref_decode(q, k, v, np.asarray([37, 37]))
    np.testing.assert_allclose(out[1], ref[1], atol=2e-5, rtol=2e-5)
    # MHA batched-rows kernel (bh_blk path needs (b*kvh) % 8 == 0)
    kf = jnp.repeat(k, 4, axis=2)
    vf = jnp.repeat(v, 4, axis=2)
    out = np.asarray(flash_decode(q, kf, vf, length, block_k=16))
    assert (out[0] == 0).all()
    reff = _ref_decode(q, kf, vf, np.asarray([37, 37]))
    np.testing.assert_allclose(out[1], reff[1], atol=2e-5, rtol=2e-5)


def test_quantize_kv_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 4, 32))
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 8, 4)
    err = np.abs(np.asarray(dequantize_kv(q, s)) - np.asarray(x))
    # symmetric absmax: per-(b, pos, head) error <= scale/2
    bound = np.asarray(s)[..., None] * 0.5 + 1e-7
    assert (err <= bound).all()


def test_flash_decode_rejects_bad_shapes(qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="divisible"):
        flash_decode(q[:, :5], k, v, 8)  # 5 q heads vs 2 kv heads
    kq, ks = quantize_kv(k)
    vq, _ = quantize_kv(v)
    with pytest.raises(ValueError, match="k_scale"):
        flash_decode(q, kq, vq, 8)


@pytest.fixture(scope="module")
def tiny_lm():
    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=2, d_ff=64,
                            max_seq_len=48, dtype=jnp.float32)
    model = Transformer(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 128)
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    return cfg, params, prompt


def test_generate_flash_decode_greedy_exact(tiny_lm):
    cfg, params, prompt = tiny_lm
    ref = generate(Transformer(cfg), params, prompt, max_new_tokens=10,
                   temperature=0.0)
    out = generate(Transformer(dataclasses.replace(
        cfg, decode_attention="flash")), params, prompt,
        max_new_tokens=10, temperature=0.0)
    assert np.array_equal(np.asarray(ref), np.asarray(out))


def test_generate_int8_kv_cache_flash_matches_einsum(tiny_lm):
    """int8 cache: the flash kernel and the dequant einsum path must
    agree exactly (same quantized numbers either way)."""
    cfg, params, prompt = tiny_lm
    out_e = generate(Transformer(dataclasses.replace(
        cfg, kv_cache_quant=True)), params, prompt,
        max_new_tokens=10, temperature=0.0)
    out_f = generate(Transformer(dataclasses.replace(
        cfg, kv_cache_quant=True, decode_attention="flash")), params,
        prompt, max_new_tokens=10, temperature=0.0)
    assert np.array_equal(np.asarray(out_e), np.asarray(out_f))


def test_generate_windowed_flash_decode(tiny_lm):
    cfg, params, prompt = tiny_lm
    cfg_w = dataclasses.replace(cfg, sliding_window=16)
    ref = generate(Transformer(cfg_w), params, prompt, max_new_tokens=10,
                   temperature=0.0)
    out = generate(Transformer(dataclasses.replace(
        cfg_w, decode_attention="flash")), params, prompt,
        max_new_tokens=10, temperature=0.0)
    assert np.array_equal(np.asarray(ref), np.asarray(out))


def test_int8_cache_vars_allocated(tiny_lm):
    cfg, params, prompt = tiny_lm
    model = Transformer(dataclasses.replace(cfg, kv_cache_quant=True))
    variables = model.init(jax.random.PRNGKey(0), prompt, decode=True)
    cache = variables["cache"]
    flat = {"/".join(str(getattr(k_, "key", k_)) for k_ in path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]}
    keys = [k_ for k_ in flat if "cached_key" in k_ and "scale" not in k_]
    scales = [k_ for k_ in flat if "cached_key_scale" in k_]
    assert keys and scales
    assert all(flat[k_].dtype == jnp.int8 for k_ in keys)
    assert all(flat[k_].dtype == jnp.float32 for k_ in scales)


@pytest.fixture(scope="module")
def qkv_mha():
    # h == kvh and b*kvh % 8 == 0 -> the batched-rows MHA kernel
    b, s, h, d = 2, 64, 8, 16
    q = jax.random.normal(jax.random.PRNGKey(7), (b, h, d))
    k = jax.random.normal(jax.random.PRNGKey(8), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(9), (b, s, h, d))
    return q, k, v


@pytest.mark.parametrize("window", [0, 10])
def test_flash_decode_mha_mixed_lengths(qkv_mha, window):
    """The batched-rows MHA kernel assembles per-row lengths from SMEM
    (rows of one 8-row block span batches with DIFFERENT lengths) and
    gates blocks on the max/min over rows — exactness against the numpy
    reference across mixed lengths and a sliding window."""
    q, k, v = qkv_mha
    length = jnp.asarray([37, 64], jnp.int32)
    out = flash_decode(q, k, v, length, window=window, block_k=16)
    ref = _ref_decode(q, k, v, length, window=window)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_flash_decode_mha_int8_cache(qkv_mha):
    """int8 cache through the MHA kernel's scale-tile dequant path."""
    q, k, v = qkv_mha
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    length = jnp.asarray([29, 55], jnp.int32)
    out = flash_decode(q, kq, vq, length, block_k=16, k_scale=ks,
                       v_scale=vs)
    ref_q = _ref_decode(q, dequantize_kv(kq, ks).astype(jnp.float32),
                        dequantize_kv(vq, vs).astype(jnp.float32), length)
    np.testing.assert_allclose(np.asarray(out), ref_q, atol=2e-5, rtol=2e-5)


def test_flash_decode_mha_windowed_int8(qkv_mha):
    """Window + int8 + mixed lengths together on the MHA kernel (the
    conservative in_range gate must not skip a block any row's window
    still reaches)."""
    q, k, v = qkv_mha
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    length = jnp.asarray([18, 62], jnp.int32)
    out = flash_decode(q, kq, vq, length, window=12, block_k=16,
                       k_scale=ks, v_scale=vs)
    ref_q = _ref_decode(q, dequantize_kv(kq, ks).astype(jnp.float32),
                        dequantize_kv(vq, vs).astype(jnp.float32),
                        length, window=12)
    np.testing.assert_allclose(np.asarray(out), ref_q, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("h", [12, 16])
def test_flash_decode_mha_head_count_branches(h):
    """The tile-legality rule (r14): 16 MHA heads take the
    head-blocked kernel with hb=8 (a sublane multiple); 12 heads have
    no legal head block (12 % 8 != 0) and fall back to the GQA
    kernel. Both paths must match the reference, int8 included (the
    MHA path folds the transposed scale tiles onto scores/probs)."""
    b, s, d = 2, 64, 16
    q = jax.random.normal(jax.random.PRNGKey(20), (b, h, d))
    k = jax.random.normal(jax.random.PRNGKey(21), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(22), (b, s, h, d))
    length = jnp.asarray([33, 64], jnp.int32)
    out = flash_decode(q, k, v, length, block_k=16)
    ref = _ref_decode(q, k, v, length)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5,
                               rtol=2e-5)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    out_q = flash_decode(q, kq, vq, length, block_k=16, k_scale=ks,
                         v_scale=vs)
    ref_q = _ref_decode(q, dequantize_kv(kq, ks).astype(jnp.float32),
                        dequantize_kv(vq, vs).astype(jnp.float32),
                        length)
    np.testing.assert_allclose(np.asarray(out_q), ref_q, atol=2e-5,
                               rtol=2e-5)


def test_flash_decode_mha_zero_length_row():
    """A zero-length row sharing an 8-row MHA block with live rows (an
    empty continuous-batching slot) must emit 0, exactly like the GQA
    kernel whose per-row gate never runs such rows."""
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), s) for i, s in
               enumerate([(2, 8, 16), (2, 64, 8, 16), (2, 64, 8, 16)]))
    length = jnp.asarray([0, 40], jnp.int32)
    out = np.asarray(flash_decode(q, k, v, length, block_k=16))
    np.testing.assert_array_equal(out[0], np.zeros_like(out[0]))
    ref = _ref_decode(q, k, v, np.asarray([64, 40]))  # row1 vs its ref
    np.testing.assert_allclose(out[1], ref[1], atol=2e-5, rtol=2e-5)
