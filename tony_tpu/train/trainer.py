"""Training-step builders: pjit'd SPMD train loops over a named mesh.

The compute-side counterpart of the control plane: where the reference
delegates "training" entirely to the user script + NCCL/Gloo
(SURVEY.md section 2.5), tony-tpu ships an in-tree trainer whose gradient
exchange is XLA collectives inserted by pjit from sharding annotations.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel.sharding import batch_sharding, shard_params_by_size


@dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(*c),
)


def cross_entropy_loss(logits, labels):
    """logits: [..., V], labels: [...] int."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@dataclass
class Trainer:
    """Builds a jitted SPMD train step.

    apply_fn(params, batch) -> loss (scalar). Shardings: params via the
    FSDP-by-size heuristic (or replicated), batch sharded on (data, fsdp).
    """

    mesh: Mesh
    apply_fn: Callable[[Any, Any], jnp.ndarray]
    optimizer: optax.GradientTransformation
    fsdp: bool = False
    donate: bool = True

    def init_state(self, params) -> TrainState:
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.optimizer.init(params),
        )

    def state_shardings(self, state: TrainState):
        if self.fsdp:
            p_sh = shard_params_by_size(self.mesh, state.params)
        else:
            p_sh = jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), state.params)
        o_sh = _opt_shardings_like(self.mesh, state.opt_state, p_sh,
                                   state.params)
        return TrainState(
            step=NamedSharding(self.mesh, P()),
            params=p_sh,
            opt_state=o_sh,
        )

    def compile_step(self, shardings):
        """The jitted step for a given TrainState sharding tree (shardings
        may come from a real or an abstract — jax.eval_shape — state)."""
        b_sh = batch_sharding(self.mesh)

        def step_fn(state: TrainState, batch):
            loss, grads = jax.value_and_grad(self.apply_fn)(state.params, batch)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            gnorm = optax.global_norm(grads)
            new_state = TrainState(step=state.step + 1, params=params,
                                   opt_state=opt_state)
            return new_state, {"loss": loss, "grad_norm": gnorm}

        metric_sh = {"loss": NamedSharding(self.mesh, P()),
                     "grad_norm": NamedSharding(self.mesh, P())}
        # b_sh is a pytree prefix: one sharding broadcast over the batch tree
        return jax.jit(
            step_fn,
            in_shardings=(shardings, b_sh),
            out_shardings=(shardings, metric_sh),
            donate_argnums=(0,) if self.donate else (),
        )

    def build_step(self, state: TrainState):
        """Returns (step_fn, placed_state). step_fn(state, batch) ->
        (state, metrics)."""
        shardings = self.state_shardings(state)
        return self.compile_step(shardings), jax.device_put(state, shardings)


def build_train_step(mesh: Mesh, apply_fn, optimizer, params, fsdp=False):
    """One-call convenience: returns (step_fn, state)."""
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn, optimizer=optimizer,
                      fsdp=fsdp)
    state = trainer.init_state(params)
    return trainer.build_step(state)


def _opt_shardings_like(mesh, opt_state, param_shardings, params):
    """Optimizer-state shardings: leaves shaped like a param get that
    param's sharding (momentum/adam moments); everything else replicated."""
    flat_params, _ = jax.tree_util.tree_flatten(params)
    flat_shard, _ = jax.tree_util.tree_flatten(param_shardings)
    by_shape = {}
    for p, s in zip(flat_params, flat_shard):
        by_shape.setdefault((p.shape, p.dtype), s)

    def pick(leaf):
        if hasattr(leaf, "shape"):
            s = by_shape.get((leaf.shape, leaf.dtype))
            if s is not None:
                return s
        return NamedSharding(mesh, P())

    return jax.tree.map(pick, opt_state)
