"""Elastic autoscaling: close the loop between the gateway and the
TonY control plane.

TonY's defining move is a control plane that ACQUIRES AND RELEASES
resources to match the job (the AM asks YARN for containers as roles
need them, returns them when tasks finish). The serving gateway had
every sensor that loop needs — queue depth and oldest-wait age on the
new ``/stats`` queue block, shed rates, TTFT SLO burn in the lifetime
histograms, ``kv_pages`` pressure — and both actuation primitives
(``Gateway.add_replica`` rides the circuit breaker's probe admission,
``Gateway.remove_replica`` rides the zero-loss drain — which, since
ISSUE-18, MIGRATES the victim's in-flight sessions to the survivors
mid-stream instead of decoding them to completion, so a scale-down is
also a defragmentation: the fleet's live work packs onto the replicas
that remain, token-exact, and the victim's drain time is bounded by
freeze cost rather than its longest remaining generation), but
nothing connected them. ``AutoScaler`` is that connection:

- a control loop samples ``Gateway.scale_signals()`` every
  ``interval_s`` and classifies the fleet as PRESSURED (queue depth
  per routable replica, oldest queued wait, capacity sheds since the
  last tick, TTFT SLO burn, KV-page exhaustion), IDLE (empty queues,
  near-empty slots, no recent enqueues), or neither;
- hysteresis: a scale-up needs ``up_stable`` consecutive pressured
  ticks, a scale-down ``down_stable`` consecutive idle ticks, and
  each action arms its own cooldown — the loop structurally cannot
  flap (the up condition is pressure, the down condition is complete
  idleness; no signal satisfies both);
- min/max bounds; scale-up capacity comes from a ``backend``:
  ``ThreadBackend`` builds another in-process ``serve.Server`` (the
  tests/CPU/dev story — replicas are threads sharing weights), and
  ``ProvisionerBackend`` acquires a real TPU slice through
  ``coordinator/provisioner.py`` first (the production shape: one
  ``Provisioner`` per dynamic replica, deprovisioned at scale-down).

Every decision — action, reason, the signals it read — lands in the
in-memory ring behind ``/stats``'s ``scaler`` block and, with history
on, in ``metrics/scaling.jsonl`` next to ``requests.jsonl`` (the
portal renders both), so "why did the fleet grow at 14:02" is
answerable from the job record.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

log = logging.getLogger(__name__)


class ScaleError(RuntimeError):
    """A backend failed to produce or release capacity."""


class ThreadBackend:
    """In-process replica capacity: ``create()`` builds another
    ``serve.Server`` via the factory (weights shared — a new replica
    costs one KV cache, not a checkpoint load). The tests/CPU/dev
    backend; also the right one for a single TPU host with spare
    chips. ``destroy`` drops the reference — the engine was already
    released by ``remove_replica``."""

    def __init__(self, server_factory, label: str = "thread"):
        self._factory = server_factory
        self._label = label  # /stats backend name ("remote-agent"
        #                      when the factory launches agent stubs)
        self.created = 0
        self.destroyed = 0

    def create(self):
        server = self._factory()
        self.created += 1
        return server

    def destroy(self, server) -> None:
        # remote stubs: a destroyed replica's agent must not outlive
        # it (remove_replica already closed a RETIRING one; a server
        # that never joined — failed probe admission — is closed here)
        from tony_tpu.gateway.remote import close_server

        close_server(server, "thread-backend destroy")
        self.destroyed += 1

    def describe(self) -> str:
        return self._label


class ProvisionerBackend:
    """Slice-backed replica capacity: each ``create()`` acquires a TPU
    slice through a fresh ``coordinator.provisioner.Provisioner``
    (``provisioner_factory(slot)`` — e.g. a ``TpuVmProvisioner`` named
    per slot) and hands its host list to ``server_factory(hosts)``;
    ``destroy()`` deprovisions the slice. Failures surface as
    ``ScaleError`` (a failed acquisition must cost a logged decision
    and a cooldown, never a crashed control loop); a provision that
    succeeded but whose server construction failed is deprovisioned
    on the spot — no leaked slices.

    The REMOTE mode (the closed TonY loop): pass
    ``cli.gateway.remote_server_factory(args)`` as the server factory
    (``lambda hosts: rmake(index, hosts=hosts)``) and the acquired
    slice's hosts get a replica AGENT (``cli/replica.py``) with a
    ``RemoteServer`` stub returned — the engine runs on the slice,
    and ``destroy()`` closing the stub then deprovisioning the slice
    is exactly "the dead host's capacity goes back" with nothing
    leaked."""

    def __init__(self, provisioner_factory, server_factory):
        self._provisioner_factory = provisioner_factory
        self._server_factory = server_factory
        self._slices: dict[int, object] = {}  # id(server) -> Provisioner
        self._slot = 0

    def create(self):
        slot = self._slot
        self._slot += 1
        prov = self._provisioner_factory(slot)
        try:
            hosts = prov.provision()
        except Exception as e:
            raise ScaleError(f"slice provision failed: {e}") from e
        try:
            server = self._server_factory(hosts)
        except Exception as e:
            try:
                prov.deprovision()
            except Exception:  # noqa: BLE001 — best-effort teardown,
                log.exception("deprovision after failed server build")
            raise ScaleError(f"server build on {hosts} failed: {e}") from e
        self._slices[id(server)] = prov
        return server

    def destroy(self, server) -> None:
        # remote stubs first: stop heartbeating (and reap a launched
        # agent) BEFORE the slice under it is deleted — the lease
        # machinery must not spend a deprovision window counting
        # connect errors against a host that is going away on purpose
        from tony_tpu.gateway.remote import close_server

        close_server(server, "provisioner-backend destroy")
        prov = self._slices.pop(id(server), None)
        if prov is not None:
            try:
                prov.deprovision()
            except Exception as e:  # noqa: BLE001 — teardown trouble is
                # a logged decision, not a dead control loop
                raise ScaleError(f"slice deprovision failed: {e}") from e

    def describe(self) -> str:
        return "provisioner"


class AutoScaler:
    """The gateway's elasticity control loop. Construct with a started
    ``Gateway`` and a backend, then ``start()``; ``stop()`` is
    idempotent and also called by ``Gateway.drain()``.

    Knobs (all per-loop-tick unless noted):

    - ``min_replicas`` / ``max_replicas``: hard fleet bounds (live
      replicas, i.e. not retired/retiring).
    - ``interval_s``: tick period.
    - ``up_queue_depth``: queued tickets per ROUTABLE replica that
      count as pressure.
    - ``up_wait_s``: oldest queued ticket age that counts as pressure.
    - ``ttft_slo_s`` + ``slo_burn``: pressure when more than
      ``slo_burn`` of the requests completed since the last tick
      exceeded the TTFT SLO (computed from deltas of the lifetime
      histogram — needs ``min_slo_sample`` completions per tick to
      vote, so a trickle can't trigger on one slow request).
    - ``kv_used_frac``: pressure when the paged-KV pool is fuller
      than this fleet-wide (0 disables; unpaged fleets never vote).
    - ``up_stable`` / ``down_stable``: consecutive pressured / idle
      ticks (hysteresis) before acting.
    - ``cooldown_up_s`` / ``cooldown_down_s``: lockout after each
      action (shared: any action resets both directions' streaks).
    - ``idle_slot_frac``: the fleet counts as idle only when active
      slots are at or below this fraction (and queues are empty and
      nothing was enqueued within the tick).
    """

    def __init__(self, gateway, backend, *, min_replicas: int = 1,
                 max_replicas: int = 4, interval_s: float = 1.0,
                 up_queue_depth: float = 4.0, up_wait_s: float = 1.0,
                 ttft_slo_s: float = 0.0, slo_burn: float = 0.1,
                 min_slo_sample: int = 5, kv_used_frac: float = 0.95,
                 up_stable: int = 2, down_stable: int = 5,
                 cooldown_up_s: float = 5.0, cooldown_down_s: float = 15.0,
                 idle_slot_frac: float = 0.25,
                 drain_timeout_s: float = 120.0,
                 decisions_kept: int = 64):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})")
        self.gateway = gateway
        self.backend = backend
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval_s = max(0.01, interval_s)
        self.up_queue_depth = up_queue_depth
        self.up_wait_s = up_wait_s
        self.ttft_slo_s = ttft_slo_s
        self.slo_burn = slo_burn
        self.min_slo_sample = max(1, min_slo_sample)
        self.kv_used_frac = kv_used_frac
        self.up_stable = max(1, up_stable)
        self.down_stable = max(1, down_stable)
        self.cooldown_up_s = cooldown_up_s
        self.cooldown_down_s = cooldown_down_s
        self.idle_slot_frac = idle_slot_frac
        self.drain_timeout_s = drain_timeout_s
        # decision state
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown_until = 0.0
        self._last_shed = 0
        self._last_ttft = (0, 0)  # (count, over-slo) cumulative
        self._last_enq: dict[int, int] = {}  # replica -> enqueued seen
        self.scale_ups = 0
        self.scale_downs = 0
        self.errors = 0
        self.ticks = 0
        self.decisions: deque[dict] = deque(maxlen=max(1, decisions_kept))
        self._servers: dict[int, object] = {}  # replica idx -> server
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()  # guards status vs the loop
        gateway.scaler = self  # surface on /stats; stopped by drain()

    # -------------------------------------------------------- lifecycle

    def start(self) -> "AutoScaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="gateway-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float | None = None) -> None:
        """Idempotent; joins the loop thread. A scale action in flight
        (a slice provision, a drain) finishes first — the loop checks
        the stop flag between ticks, not inside an action."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout if timeout is not None
                   else self.drain_timeout_s + 10 * self.interval_s + 30)

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the control loop must
                # survive anything: a broken tick is a logged error
                # plus a missed beat, never a dead autoscaler
                self.errors += 1
                log.exception("autoscaler tick failed")

    # --------------------------------------------------------- decisions

    def tick(self) -> str | None:
        """One control iteration (public for tests: drive the loop by
        hand with a fake clock-free cadence). Returns the action taken
        ("up"/"down") or None."""
        sig = self.gateway.scale_signals()
        now = sig["now"]
        self.ticks += 1
        action, reasons = self.decide(sig, now)
        if action == "up":
            self._scale_up(sig, reasons)
        elif action == "down":
            self._scale_down(sig, reasons)
        return action

    def decide(self, sig: dict, now: float) -> tuple[str | None, list]:
        """Pure decision half (unit-testable): classify the tick,
        advance the hysteresis streaks, and return the action once a
        streak crosses its threshold outside the cooldown."""
        pressure = self._pressure_reasons(sig)
        idle = not pressure and self._is_idle(sig)
        if pressure:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # neither pressured nor fully idle: decay both streaks —
            # hysteresis counts CONSECUTIVE ticks only
            self._up_streak = 0
            self._down_streak = 0
        live = sig["replicas_live"]
        if now < self._cooldown_until:
            return None, pressure
        if live < self.min_replicas:
            # below the configured floor (boot under-provisioned, or a
            # prior scale-up failed): grow regardless of pressure —
            # paced by the cooldown so a broken backend isn't
            # hot-looped
            return "up", [f"below floor ({live} < min "
                          f"{self.min_replicas})"]
        if pressure and self._up_streak >= self.up_stable \
                and live < self.max_replicas:
            return "up", pressure
        if idle and self._down_streak >= self.down_stable \
                and live > self.min_replicas:
            return "down", ["idle"]
        return None, pressure

    def _pressure_reasons(self, sig: dict) -> list[str]:
        reasons = []
        routable = max(1, sig["replicas_routable"])
        per_rep = sig["depth"] / routable
        if per_rep >= self.up_queue_depth:
            reasons.append(f"queue_depth {sig['depth']} "
                           f"({per_rep:.1f}/replica)")
        if sig["oldest_wait_s"] >= self.up_wait_s:
            reasons.append(f"oldest_wait {sig['oldest_wait_s']:.2f}s")
        shed = sig["shed_capacity_total"]
        if shed > self._last_shed:
            reasons.append(f"sheds +{shed - self._last_shed}")
        self._last_shed = shed
        burn = self._ttft_burn(sig)
        if burn is not None and burn > self.slo_burn:
            reasons.append(f"ttft_slo_burn {burn:.2f}")
        if self.kv_used_frac > 0 and sig["kv_pages_total"] > 0:
            used = 1.0 - sig["kv_pages_free"] / sig["kv_pages_total"]
            if used >= self.kv_used_frac:
                reasons.append(f"kv_pages {used:.0%} used")
        return reasons

    def _ttft_burn(self, sig: dict) -> float | None:
        """Fraction of requests completed SINCE THE LAST TICK whose
        TTFT exceeded the SLO, from deltas of the lifetime histogram
        (bucket edges, so the SLO is effectively rounded up to the
        next edge). None = disabled or too small a sample to vote."""
        if self.ttft_slo_s <= 0:
            return None
        from tony_tpu.obs.prom import hist_over_edge

        # SLO-rounds-up-to-the-next-edge semantics live in ONE place
        # (obs/prom.hist_over_edge), shared with the alert bus's
        # ttft_slo_burn rule — the two surfaces must never disagree
        over, total = hist_over_edge(sig["ttft_hist"], self.ttft_slo_s)
        d_total = total - self._last_ttft[0]
        d_over = over - self._last_ttft[1]
        self._last_ttft = (total, over)
        if d_total < self.min_slo_sample:
            return None
        return d_over / d_total

    def _is_idle(self, sig: dict) -> bool:
        if sig["depth"] > 0 or sig["oldest_wait_s"] > 0:
            return False
        slots = sig["slots"]
        if slots and sig["active_slots"] > self.idle_slot_frac * slots:
            return False
        # no enqueues since the last tick: compare per-replica
        # lifetime enqueue counters (rate windows are too coarse for
        # sub-window intervals)
        idle = True
        for r in self.gateway.live_replicas:
            if r.enqueued > self._last_enq.get(r.index, 0):
                idle = False
            self._last_enq[r.index] = r.enqueued
        return idle

    # ----------------------------------------------------------- actions

    def _scale_up(self, sig: dict, reasons: list) -> None:
        t0 = time.monotonic()
        try:
            server = self.backend.create()
        except Exception as e:  # noqa: BLE001 — a failed acquisition
            # is a recorded decision + cooldown (do NOT hot-loop a
            # broken backend), never a dead control loop
            self.errors += 1
            log.exception("scale-up create failed")
            self._record("up_failed", sig, reasons, error=str(e))
            self._after_action(up=True)
            return
        try:
            index = self.gateway.add_replica(server, probe=True)
        except Exception as e:  # noqa: BLE001 — e.g. the gateway
            # closed while a slow slice provision was in flight: the
            # capacity we just acquired MUST go back (a billed TPU
            # slice must never outlive the failed join)
            self.errors += 1
            log.exception("scale-up join failed; releasing capacity")
            try:
                self.backend.destroy(server)
            except Exception:  # noqa: BLE001 — best-effort teardown
                log.exception("release after failed join also failed")
            self._record("up_failed", sig, reasons, error=str(e))
            self._after_action(up=True)
            return
        with self._lock:
            self._servers[index] = server
        self.scale_ups += 1
        self._record("up", sig, reasons, replica=index,
                     took_s=round(time.monotonic() - t0, 3))
        self._after_action(up=True)
        log.warning("autoscaler: scale-up -> replica %d (probe pending; "
                    "reasons: %s)", index, "; ".join(reasons))

    def _scale_down(self, sig: dict, reasons: list) -> None:
        victim = self._pick_victim()
        if victim is None:
            return
        t0 = time.monotonic()
        try:
            ok = self.gateway.remove_replica(victim.index,
                                             timeout=self.drain_timeout_s)
        except ValueError as e:  # last-live race: bounds moved under us
            self._record("down_failed", sig, reasons, error=str(e))
            self._after_action(up=False)
            return
        if not ok:
            # still draining past the deadline: it is out of routing
            # and will finish; pick it up again on a later tick
            self.errors += 1
            self._record("down_timeout", sig, reasons,
                         replica=victim.index)
            self._after_action(up=False)
            return
        with self._lock:
            server = self._servers.pop(victim.index, None)
        try:
            self.backend.destroy(server)
        except Exception as e:  # noqa: BLE001 — the replica is gone
            # either way; a teardown hiccup is a logged decision
            self.errors += 1
            log.exception("scale-down backend destroy failed")
            self._record("destroy_failed", sig, reasons, error=str(e))
        self.scale_downs += 1
        self._record("down", sig, ["idle"], replica=victim.index,
                     took_s=round(time.monotonic() - t0, 3))
        self._after_action(up=False)
        log.warning("autoscaler: scale-down retired replica %d "
                    "(zero-loss drain)", victim.index)

    def _pick_victim(self):
        """Scale-down victim order: a quarantined/broken replica first
        (it serves nothing — retiring it frees real capacity at zero
        traffic cost), then the youngest dynamically-added one, then
        the youngest of all — never below the floor the caller already
        checked."""
        live = self.gateway.live_replicas
        if len(live) <= self.min_replicas:
            return None
        from tony_tpu.gateway.core import HEALTHY

        dead = [r for r in live if r.state != HEALTHY]
        if dead:
            return dead[-1]
        spawned = [r for r in live if r.spawned]
        return (spawned or live)[-1]

    def _after_action(self, up: bool) -> None:
        self._cooldown_until = time.monotonic() + \
            (self.cooldown_up_s if up else self.cooldown_down_s)
        self._up_streak = 0
        self._down_streak = 0

    # ------------------------------------------------------ observability

    def _record(self, action: str, sig: dict, reasons: list,
                **extra) -> None:
        row = {
            "t": round(time.time(), 3),
            "action": action,
            "reasons": list(reasons),
            "replicas_live": sig["replicas_live"],
            "queue_depth": sig["depth"],
            "oldest_wait_s": sig["oldest_wait_s"],
            **extra,
        }
        with self._lock:
            self.decisions.append(row)
        history = getattr(self.gateway, "history", None)
        if history is not None:
            try:
                history.record_scaling(row)
            except Exception:  # noqa: BLE001 — same contract as every
                # other history write: never let a disk hiccup near
                # the serving path
                log.exception("history scaling write failed")

    def status(self) -> dict:
        """The /stats ``scaler`` block."""
        with self._lock:
            decisions = list(self.decisions)[-8:]
        return {
            "enabled": True,
            "backend": self.backend.describe()
            if hasattr(self.backend, "describe") else
            type(self.backend).__name__,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "interval_s": self.interval_s,
            "replicas_live": len(self.gateway.live_replicas),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "errors": self.errors,
            "ticks": self.ticks,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "cooldown_s": round(
                max(0.0, self._cooldown_until - time.monotonic()), 3),
            "last_decisions": decisions,
        }
