from tony_tpu.events.event import (
    Event,
    EventType,
    JobMetadata,
    application_finished,
    application_inited,
    session_resized,
    task_finished,
    task_started,
)
from tony_tpu.events.handler import EventHandler

__all__ = [
    "Event",
    "EventType",
    "EventHandler",
    "JobMetadata",
    "application_finished",
    "application_inited",
    "session_resized",
    "task_finished",
    "task_started",
]
