from tony_tpu.parallel.mesh import (
    ALL_AXES,
    DATA,
    EXPERT,
    FSDP,
    PIPE,
    SEQ,
    TENSOR,
    MeshSpec,
    data_parallel_mesh,
    make_mesh,
    multislice_mesh,
    num_slices,
)
from tony_tpu.parallel.ring_attention import (
    blockwise_attention,
    reference_attention,
    ring_attention,
)
from tony_tpu.parallel.pipeline import (
    interleave_stage_params,
    pipeline_apply,
    stack_stage_params,
)
from tony_tpu.parallel.ulysses import ulysses_attention
from tony_tpu.parallel.moe import (
    MoEConfig,
    init_moe_params,
    moe_layer,
    moe_logical_axes,
    top_k_gating,
)
from tony_tpu.parallel.sharding import (
    RULES,
    batch_sharding,
    replicated,
    shard_params_by_size,
    spec_for,
    tree_shardings,
)

__all__ = [
    "ALL_AXES", "DATA", "EXPERT", "FSDP", "PIPE", "SEQ", "TENSOR",
    "MeshSpec", "MoEConfig", "RULES",
    "batch_sharding", "blockwise_attention", "data_parallel_mesh",
    "init_moe_params", "make_mesh", "moe_layer", "moe_logical_axes",
    "multislice_mesh", "num_slices",
    "interleave_stage_params",
    "pipeline_apply", "reference_attention", "replicated", "ring_attention",
    "shard_params_by_size", "spec_for", "stack_stage_params",
    "top_k_gating", "tree_shardings", "ulysses_attention",
]
