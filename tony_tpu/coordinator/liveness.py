"""Heartbeat liveness monitor.

Reference: Hadoop AbstractLivelinessMonitor wired in
ApplicationMaster.java:202-222 — a task expires after
``heartbeat-interval * max(3, max-missed-heartbeats)`` without a ping;
expiry fires ``onTaskDeemedDead`` (:1225-1232) which fails the app.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)


def _expiry_s(interval_ms: int, max_missed: int) -> float:
    return (interval_ms / 1000) * max(3, max_missed)


def liveness_expiry_s(conf) -> float:
    """The ONE expiry-horizon formula. The coordinator expires a silent
    task after this long; the agent self-terminates after being unable to
    reach the coordinator for this long; the client fences a coordinator
    respawn past this + the checkpoint grace. All three must agree or
    task generations can overlap on the chips — change _expiry_s only."""
    return _expiry_s(conf.get_int("tony.task.heartbeat-interval-ms", 1000),
                     conf.get_int("tony.task.max-missed-heartbeats", 25))


def heartbeat_rpc_timeout_s(conf) -> float:
    """Per-ping RPC timeout on the agent's dedicated heartbeat channel —
    shared with the client's respawn-fence budget (a split copy of this
    formula would silently shorten the fence)."""
    hb_s = conf.get_int("tony.task.heartbeat-interval-ms", 1000) / 1000
    return max(2 * hb_s, 2.0)


class LivenessMonitor:
    def __init__(self, interval_ms: int, max_missed: int,
                 on_expired: Callable[[str], None]):
        self.expiry_s = _expiry_s(interval_ms, max_missed)
        self.check_s = max(interval_ms / 1000, 0.05)
        self.on_expired = on_expired
        self._last: dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, task_id: str) -> None:
        with self._lock:
            self._last[task_id] = time.monotonic()

    def clear(self) -> None:
        """Drop every watched task — session reset/resize must not let a
        previous epoch's entries expire against the new session."""
        with self._lock:
            self._last.clear()

    def unregister(self, task_id: str) -> None:
        """Stop watching a task — called when its result is registered, to
        close the completion-vs-heartbeat race (ref: ApplicationMaster.java
        :928-956 three-way race comment)."""
        with self._lock:
            self._last.pop(task_id, None)

    def ping(self, task_id: str) -> None:
        with self._lock:
            if task_id in self._last:
                self._last[task_id] = time.monotonic()

    def _loop(self) -> None:
        while not self._stop.wait(self.check_s):
            now = time.monotonic()
            expired = []
            with self._lock:
                for task_id, last in list(self._last.items()):
                    if now - last > self.expiry_s:
                        expired.append(task_id)
                        del self._last[task_id]
            for task_id in expired:
                log.error("task %s missed heartbeats for %.1fs; deemed dead",
                          task_id, self.expiry_s)
                try:
                    self.on_expired(task_id)
                except Exception:
                    log.exception("on_expired callback failed for %s", task_id)

    def start(self) -> "LivenessMonitor":
        self._thread = threading.Thread(target=self._loop, name="liveness",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
