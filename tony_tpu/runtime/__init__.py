from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter, TaskContext
from tony_tpu.runtime.registry import (
    get_am_adapter,
    get_runtime,
    get_task_adapter,
    register,
)

__all__ = [
    "AMAdapter",
    "Runtime",
    "TaskAdapter",
    "TaskContext",
    "get_am_adapter",
    "get_runtime",
    "get_task_adapter",
    "register",
]
