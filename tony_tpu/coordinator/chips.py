"""Per-task chip assignment on shared hosts.

Reference: ``tony.<role>.gpus`` becomes an ENFORCED container resource —
YARN hands each container its own GPU set
(HadoopCompatibleAdapter.java:71, util/Utils.java:393-419
``setCapabilityGPU``). On a shared TPU-VM host (LocalProcessLauncher /
DockerLauncher) nothing isolates tasks by default: every process sees all
chips. The ChipAllocator assigns each task a disjoint device-id set from
``tony.<role>.chips`` and the coordinator exports it as
``TPU_VISIBLE_DEVICES`` (libtpu's device-subset contract), so two tasks on
one 4-chip host with 2 chips each see 2 chips apiece. Topology bounds
(TPU_PROCESS_BOUNDS etc.) stay with the runtime adapters — they depend on
the mesh, not the allocation.
"""

from __future__ import annotations

import threading


class ChipAllocator:
    """Disjoint device-id sets for tasks sharing this host's chips."""

    def __init__(self, total: int):
        self.total = max(int(total), 0)
        self._free: list[int] = list(range(self.total))
        self._held: dict[str, list[int]] = {}
        self._lock = threading.Lock()

    def allocate(self, task_id: str, n: int) -> list[int]:
        """Reserve ``n`` chips for ``task_id``. Raises RuntimeError when
        the host cannot satisfy the request (the scheduler treats that as
        an allocation failure, like an unsatisfiable container request)."""
        with self._lock:
            if task_id in self._held:  # relaunch same epoch: reuse
                return list(self._held[task_id])
            if n > len(self._free):
                raise RuntimeError(
                    f"task {task_id} wants {n} chips but only "
                    f"{len(self._free)} of {self.total} are free on this "
                    "host")
            ids, self._free = self._free[:n], self._free[n:]
            self._held[task_id] = ids
            return list(ids)

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def release(self, task_id: str) -> None:
        with self._lock:
            ids = self._held.pop(task_id, None)
            if ids:
                self._free = sorted(self._free + ids)

    def reset(self) -> None:
        """New session epoch: every previous hold is void."""
        with self._lock:
            self._free = list(range(self.total))
            self._held.clear()
