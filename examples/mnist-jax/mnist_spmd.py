"""Distributed MNIST-style training, the tony-tpu flagship example.

Reference analog: tony-examples/mnist-tensorflow/mnist_distributed.py —
which hand-parses TF_CONFIG and runs async PS/worker training. Here the
rendezvous is one call (`tony_tpu.distributed.initialize()`), and training
is synchronous SPMD: every worker holds a shard of the global batch, pjit
inserts the gradient all-reduce over ICI (or gloo on CPU hosts).

Runs standalone (single process) or under a tony-tpu gang:

    python -m tony_tpu.cli.local --conf_file examples/mnist-jax/job.toml
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root, for standalone runs

import jax
import jax.numpy as jnp
import numpy as np
import optax


def make_dataset(n: int, key: np.random.Generator):
    """Synthetic 28x28 'digits': class k = noisy k-banded image. Replace
    with a real MNIST loader in production runs."""
    labels = key.integers(0, 10, size=(n,))
    images = key.normal(0.1, 1.0, size=(n, 28, 28)).astype(np.float32)
    for k in range(10):
        images[labels == k, k * 2:k * 2 + 2, :] += 2.0
    return images.reshape(n, 784), labels.astype(np.int32)


def init_params(key, sizes=(784, 128, 10)):
    params = []
    for din, dout in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        params.append({
            "w": jax.random.normal(sub, (din, dout)) * jnp.sqrt(2.0 / din),
            "b": jnp.zeros((dout,)),
        })
    return params


def apply_fn(params, batch):
    x = batch["x"]
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    logits = x @ params[-1]["w"] + params[-1]["b"]
    onehot = jax.nn.one_hot(batch["y"], 10)
    return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=256)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()

    import tony_tpu.distributed as dist
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import Trainer

    spec = dist.initialize()  # no-op when standalone
    role, index = dist.task_identity()
    nproc = spec["num_processes"] if spec else 1
    mesh = data_parallel_mesh()

    rng = np.random.default_rng(index)
    images, labels = make_dataset(args.global_batch * 4, rng)
    params = init_params(jax.random.PRNGKey(0))

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adamw(args.lr))
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)

    per_proc = args.global_batch // max(nproc, 1)
    b_sh = batch_sharding(mesh)

    def shard(local):
        # each process contributes its own rows of the global batch
        return jax.make_array_from_process_local_data(b_sh, local)

    loss = None
    for step in range(args.steps):
        lo = (step * per_proc) % (images.shape[0] - per_proc)
        batch = {
            "x": shard(images[lo:lo + per_proc]),
            "y": shard(labels[lo:lo + per_proc]),
        }
        placed, metrics = step_fn(placed, batch)
        loss = float(metrics["loss"])
        if dist.is_chief() or spec is None:
            print(f"step {step}: loss={loss:.4f}")

    # training must actually reduce the loss below chance (-ln 1/10), or the
    # job fails — the exit status is the assertion, TestTonyE2E-style
    print(f"worker {role}:{index} final loss {loss:.4f}")
    return 0 if loss is not None and loss < 2.3 else 1


if __name__ == "__main__":
    raise SystemExit(main())
