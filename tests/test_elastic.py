"""Elastic training tests — checkpoint-aware gang restart (the reference
stubs elasticity: horovod_driver.py:28-29 elastic_driver_fn = pass)."""

import glob
import json
import os
import threading
import time

import pytest

from tony_tpu import elastic
from tony_tpu.mini import MiniTonyCluster, script_conf

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_control_file_roundtrip(tmp_path):
    assert not elastic.save_and_exit_requested(str(tmp_path), "worker:0")
    elastic.write_save_and_exit(str(tmp_path), task_id="worker:0")
    assert elastic.save_and_exit_requested(str(tmp_path), "worker:0")
    assert not elastic.save_and_exit_requested(str(tmp_path), "worker:1")


def test_resize_validation():
    import tempfile

    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.application.security.enabled", False)
    with tempfile.TemporaryDirectory() as tmp:
        conf.set("tony.staging-dir", tmp)
        conf.set("tony.history.location", os.path.join(tmp, "hist"))
        coord = Coordinator(conf, "application_rsz", os.path.join(tmp, "job"))
        try:
            assert coord.request_resize("worker", 4) is True
            assert coord.request_resize("worker", 0) is False
            assert coord.request_resize("ghost", 2) is False
            assert coord._take_pending_resize() == {"worker": 4}
            assert coord._take_pending_resize() == {}
        finally:
            coord.rpc.stop()
            coord.metrics_rpc.stop()


def test_elastic_resize_e2e():
    """Submit 2 elastic workers, grow to 3 mid-run: job must SUCCEED, the
    new epoch must see TASK_NUM=3, progress must resume (not restart), and
    the history must record SESSION_RESIZED."""
    with MiniTonyCluster() as c:
        conf = script_conf(c, os.path.join(SCRIPTS, "elastic_worker.py"),
                           {"worker": 2})
        conf.set("tony.elastic.grace-ms", 5000)
        conf.set("tony.application.shell-env", f"TONY_REPO_ROOT={REPO}")
        hist = str(conf.get("tony.history.location"))
        client = c.make_client(conf)

        def resize_soon():
            for _ in range(200):
                if client.rpc is not None:
                    try:
                        infos = client.rpc.call("get_task_infos")
                        if infos and all(i["status"] in ("RUNNING", "READY")
                                         for i in infos):
                            ok = client.rpc.call("resize_role", role="worker",
                                                 instances=3)
                            print("resize ->", ok)
                            return
                    except Exception:
                        pass
                time.sleep(0.1)

        t = threading.Thread(target=resize_soon, daemon=True)
        t.start()
        ok = client.run()
        assert ok, client.final_status
        job_dir = client.job_dir

        # every worker of the final gang saw TASK_NUM=3 in epoch 1
        sizes = {}
        for path in glob.glob(os.path.join(job_dir, "sizes-worker-*.txt")):
            idx = path.rsplit("-", 1)[1].split(".")[0]
            with open(path) as f:
                sizes[idx] = f.read().strip().splitlines()
        assert "2" in sizes, sizes  # the grown worker existed
        assert any(line == "1:3" for line in sizes["2"]), sizes
        # worker 0 lived in both epochs: 0:2 then 1:3
        assert sizes["0"][0] == "0:2" and "1:3" in sizes["0"], sizes

        # progress resumed: worker-0's file shows a resume line in its log
        log0 = os.path.join(job_dir, "logs", "worker-0-user.log")
        with open(log0) as f:
            content = f.read()
        assert "resumed at step" in content, content

        # history has the resize event
        events = []
        for path in glob.glob(os.path.join(hist, "**", "*.jhist.jsonl"),
                              recursive=True):
            with open(path) as f:
                events += [json.loads(line) for line in f if line.strip()]
        assert any(e["type"] == "SESSION_RESIZED" for e in events), \
            [e["type"] for e in events]
