"""Job-history event schema.

Reference: src/main/avro/*.avsc (Event, ApplicationInited, ApplicationFinished,
TaskStarted, TaskFinished + metadata) serialized as an Avro container file.
The rebuild uses JSON-lines with an explicit ``type`` tag — same record
fields, human-greppable, no Avro dependency in the image.
"""

from __future__ import annotations

import enum
import time
from dataclasses import asdict, dataclass, field
from typing import Any


class EventType(enum.Enum):
    APPLICATION_INITED = "APPLICATION_INITED"
    APPLICATION_FINISHED = "APPLICATION_FINISHED"
    TASK_STARTED = "TASK_STARTED"
    TASK_FINISHED = "TASK_FINISHED"
    # rebuild extra: elastic resize epochs (no reference analog)
    SESSION_RESIZED = "SESSION_RESIZED"


@dataclass
class Event:
    type: EventType
    payload: dict[str, Any]
    timestamp_ms: int = field(default_factory=lambda: int(time.time() * 1000))

    def to_dict(self) -> dict:
        return {
            "type": self.type.value,
            "timestamp": self.timestamp_ms,
            "event": self.payload,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Event":
        return cls(
            type=EventType(d["type"]),
            payload=d.get("event", {}),
            timestamp_ms=int(d.get("timestamp", 0)),
        )


def application_inited(app_id: str, num_tasks: int, host: str) -> Event:
    """Ref: ApplicationInited.avsc, emitted at ApplicationMaster.java:397-399."""
    return Event(EventType.APPLICATION_INITED,
                 {"applicationId": app_id, "numTasks": num_tasks, "host": host})


def application_finished(app_id: str, status: str, num_failed_tasks: int,
                         metrics: dict | None = None) -> Event:
    """Ref: ApplicationFinished.avsc, emitted at ApplicationMaster.java:427-430."""
    return Event(EventType.APPLICATION_FINISHED,
                 {"applicationId": app_id, "status": status,
                  "numFailedTasks": num_failed_tasks, "metrics": metrics or {}})


def task_started(role: str, index: int, host: str) -> Event:
    """Ref: TaskStarted.avsc, emitted at ApplicationMaster.java:1216-1221."""
    return Event(EventType.TASK_STARTED,
                 {"taskType": role, "taskIndex": index, "host": host})


def task_finished(role: str, index: int, status: str,
                  metrics: dict | None = None) -> Event:
    """Ref: TaskFinished.avsc, emitted at ApplicationMaster.java:1246-1258
    with TaskMonitor metrics attached."""
    return Event(EventType.TASK_FINISHED,
                 {"taskType": role, "taskIndex": index, "status": status,
                  "metrics": metrics or {}})


@dataclass
class JobMetadata:
    """Ref: models/JobMetadata.java (143 LoC)."""

    id: str
    user: str
    started: int
    completed: int = -1
    status: str = "RUNNING"
    conf_path: str = ""

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "JobMetadata":
        return cls(**{k: d[k] for k in
                      ("id", "user", "started", "completed", "status", "conf_path")
                      if k in d})


def session_resized(app_id: str, new_session_id: int,
                    sizes: dict[str, int]) -> Event:
    """Elastic resize epoch (rebuild extra; reference stubs elasticity)."""
    return Event(EventType.SESSION_RESIZED,
                 {"applicationId": app_id, "sessionId": new_session_id,
                  "sizes": dict(sizes)})
