"""Elastic autoscaling + SLO-aware admission (ISSUE-9).

The acceptance anchors:
- chaos-style scaling: under a synthetic burst the autoscaler adds a
  replica which enters via PROBE admission, scale-down drains with
  zero accepted-request loss, and every output is byte-exact vs a
  solo generate (no 5xx anywhere);
- WFQ no-starvation: a saturating ``batch``-tier flood cannot starve
  ``interactive`` requests (bounded admission rank / queue wait),
  while an idle fleet still gives ``batch`` full throughput;
- deadline anchoring: a failover re-enqueue cannot extend a
  request's ``ttl_s`` deadline (it stays anchored to submit time).

Plus units for the WFQueue scheduler, tenant quota buckets, the
autoscaler's decision logic (hysteresis, cooldowns, bounds), the
backends, and the new observability surfaces (queue block, admission
block, scaler block, per-request tier fields). CPU-only tiny model;
the timing-sensitive p99-vs-fixed-control comparison is slow-marked.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway import (AutoScaler, BadRequest, Gateway, GatewayHTTP,
                              GatewayQueueFull, GenRequest,
                              NoHealthyReplicas, ProvisionerBackend,
                              QuotaExceeded, ScaleError, TenantQuotas,
                              ThreadBackend, Ticket, WFQueue,
                              parse_tier_weights)
from tony_tpu.gateway.core import BROKEN, HEALTHY, RETIRED
from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.serve import Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _server(tiny, **kw):
    model, params = tiny
    kw.setdefault("batch_size", 2)
    kw.setdefault("min_bucket", 8)
    return Server(model, params, **kw)


def _solo(tiny, prompt, n):
    model, params = tiny
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0].tolist()


def _ticket(prompt_len=3, max_new=4, ttl_s=None, tier="standard"):
    t = Ticket(GenRequest([1] * prompt_len, max_new_tokens=max_new,
                          ttl_s=ttl_s), ttl_s)
    t.tier = tier
    return t


# ------------------------------------------------------------- WFQueue


def test_wfq_weighted_interleave_under_contention():
    """Two saturated tiers with weights 2:1 and equal costs admit
    ~2:1; the heavier tier never monopolizes."""
    q = WFQueue({"a": 2.0, "b": 1.0})
    for i in range(12):
        q.push(_ticket(tier="a"))
        q.push(_ticket(tier="b"))
    order = [q.pop().tier for _ in range(18)]
    # in any prefix, a's count tracks ~2x b's (off by at most one round)
    for i in range(1, len(order) + 1):
        a, b = order[:i].count("a"), order[:i].count("b")
        assert a <= 2 * (b + 1) and b <= a, (i, order)


def test_wfq_single_tier_is_work_conserving():
    """Only batch queued: it gets the full admission rate in FIFO
    order — weights shape contention, they never reserve idle
    capacity."""
    q = WFQueue()
    tickets = [_ticket(tier="batch") for _ in range(6)]
    for t in tickets:
        q.push(t)
    assert [q.pop() for _ in range(6)] == tickets
    assert q.pop() is None and len(q) == 0


def test_wfq_idle_tier_catches_up_no_banked_credit():
    """A tier waking from idle is caught up to the busiest floor: it
    gets priority for one round, not unbounded credit for the time it
    sat idle."""
    q = WFQueue({"a": 1.0, "b": 1.0})
    for _ in range(8):
        q.push(_ticket(tier="b"))
    for _ in range(4):
        q.pop()  # b accumulates virtual work while a idles
    q.push(_ticket(tier="a"))
    for _ in range(4):
        q.push(_ticket(tier="a"))
    assert q.pop().tier == "a"  # the wake-up pop
    # equal weights from the caught-up floor: strict alternation, NOT
    # four more a's cashing in idle time
    order = [q.pop().tier for _ in range(6)]
    assert order.count("a") <= 4 and order[:2] != ["a", "a"], order


def test_wfq_deadline_first_within_tier():
    """Within a tier, the ticket closest to its deadline pops first;
    deadline-less tickets keep arrival order behind any deadline."""
    q = WFQueue()
    none1 = _ticket(ttl_s=None)
    late = _ticket(ttl_s=60.0)
    soon = _ticket(ttl_s=0.5)
    none2 = _ticket(ttl_s=None)
    for t in (none1, late, soon, none2):
        q.push(t)
    assert [q.pop() for _ in range(4)] == [soon, late, none1, none2]


def test_wfq_unpop_restores_position_and_charge():
    q = WFQueue()
    first, second = _ticket(ttl_s=1.0), _ticket(ttl_s=2.0)
    q.push(first)
    q.push(second)
    got = q.pop()
    assert got is first
    q.unpop(got)
    assert len(q) == 2
    assert q.pop() is first and q.pop() is second


def test_wfq_steal_all_preserves_tiers_and_empties():
    q = WFQueue()
    tickets = [_ticket(tier=t) for t in
               ("batch", "interactive", "standard", "batch")]
    for t in tickets:
        q.push(t)
    stolen = q.steal_all()
    assert sorted(t.tier for t in stolen) == sorted(t.tier for t in tickets)
    assert len(q) == 0 and not q
    # unknown tier is a programming error (gateway validates earlier)
    with pytest.raises(KeyError):
        q.push(_ticket(tier="nope"))


def test_parse_tier_weights():
    assert parse_tier_weights("") == {"interactive": 8.0, "standard": 4.0,
                                      "batch": 1.0}
    assert parse_tier_weights("gold=2,bronze=0.5") == {"gold": 2.0,
                                                       "bronze": 0.5}
    with pytest.raises(ValueError, match="not a number"):
        parse_tier_weights("gold=shiny")
    with pytest.raises(ValueError, match="starve"):
        parse_tier_weights("gold=0")
    with pytest.raises(ValueError, match="name=weight"):
        parse_tier_weights("gold")


# --------------------------------------------------------- tenant quota


def test_tenant_quota_bucket_refill_and_retry_after():
    q = TenantQuotas(rate_tokens_per_s=10.0, burst_tokens=30.0)
    now = 1000.0
    assert q.admit("acme", 30, now) is None  # full burst admits
    retry = q.admit("acme", 20, now)  # empty bucket refuses
    assert retry == pytest.approx(2.0)  # 20 tokens / 10 per s
    assert q.admit("other", 20, now) is None  # tenants isolated
    assert q.admit("acme", 20, now + 2.0) is None  # refilled
    st = q.stats()
    assert st["tenants"] == 2 and st["enabled"]
    # refund: a charge whose request got zero service goes back
    q2 = TenantQuotas(rate_tokens_per_s=10.0, burst_tokens=30.0)
    assert q2.admit("t", 30, now) is None
    assert q2.admit("t", 5, now) is not None  # empty
    q2.refund("t", 30)
    assert q2.admit("t", 30, now) is None  # whole burst back


def test_tenant_quota_disabled_and_oversize_clamp():
    assert TenantQuotas(0.0).admit("anyone", 10**9) is None  # off
    q = TenantQuotas(rate_tokens_per_s=10.0, burst_tokens=20.0)
    # a request bigger than the burst charges one full burst — huge
    # requests stay admittable instead of refusing forever
    assert q.admit("t", 10**6, now=0.0) is None
    assert q.admit("t", 1, now=0.0) is not None  # bucket emptied
    # anonymous traffic shares one bucket under quotas
    assert q.admit(None, 20, now=0.0) is None
    assert q.admit(None, 20, now=0.0) is not None


# -------------------------------------------------- gateway admission


def test_gateway_quota_429_and_unknown_priority_400(tiny):
    gw = Gateway([_server(tiny)], max_queue=16,
                 tenant_quota_rate=10.0, tenant_quota_burst=30.0)
    with pytest.raises(BadRequest, match="unknown priority"):
        gw.submit(GenRequest([1, 2], max_new_tokens=2, priority="vip"))
    gw.submit(GenRequest([1] * 10, max_new_tokens=20, tenant="acme"))
    with pytest.raises(QuotaExceeded) as e:
        gw.submit(GenRequest([1] * 10, max_new_tokens=20, tenant="acme"))
    assert e.value.http_status == 429 and e.value.retry_after_s > 0
    # quota sheds are counted separately from capacity sheds (the
    # autoscaler must not grow the fleet to chase a tenant's limit)
    snap = gw.snapshot()
    assert snap["shed"] == {400: 1, 429: 1}  # the vip 400 + quota 429
    assert snap["admission"]["quota"]["rejections"] == 1
    assert snap["admission"]["quota"]["enabled"]
    assert gw.scale_signals()["shed_capacity_total"] == 0


def test_quota_not_charged_when_request_never_queues(tiny):
    """A request refused by the queue bound (checked BEFORE the quota
    gate) or by fleet health (refunded after) must not drain the
    tenant's bucket — zero service means zero tokens spent."""
    gw = Gateway([_server(tiny)], max_queue=1,
                 tenant_quota_rate=1.0, tenant_quota_burst=20.0)
    gw.submit(GenRequest([1] * 5, max_new_tokens=5, tenant="t"))  # 10
    with pytest.raises(GatewayQueueFull):
        gw.submit(GenRequest([1] * 5, max_new_tokens=5, tenant="t"))
    # the bound 429 fired BEFORE the quota gate: bucket untouched
    assert gw.quotas._buckets["t"][0] == pytest.approx(10.0, abs=0.5)
    # fleet-health refusal happens AFTER the charge: it refunds
    gw2 = Gateway([_server(tiny)], max_queue=8,
                  tenant_quota_rate=1.0, tenant_quota_burst=20.0)
    gw2.submit(GenRequest([1] * 5, max_new_tokens=5, tenant="t"))
    with gw2.replicas[0].cv:
        gw2.replicas[0].state = BROKEN
    with pytest.raises(NoHealthyReplicas):
        gw2.submit(GenRequest([1] * 5, max_new_tokens=5, tenant="t"))
    # the NoHealthyReplicas charge was refunded
    assert gw2.quotas._buckets["t"][0] == pytest.approx(10.0, abs=0.5)


def test_snapshot_queue_block_and_tier_fields(tiny, tmp_path):
    """Satellites 1+2: the queue block (depth / oldest wait / enqueue
    rate) and tenant/priority/queue_pos in window rows + history
    requests.jsonl."""
    from tony_tpu.gateway import GatewayHistory

    hist = GatewayHistory(str(tmp_path), n_replicas=1)
    gw = Gateway([_server(tiny)], max_queue=32, history=hist)
    gw.submit(GenRequest([1, 2, 3], max_new_tokens=3, id="a",
                         tenant="acme", priority="interactive"))
    gw.submit(GenRequest([4, 5], max_new_tokens=3, id="b",
                         priority="batch"))
    time.sleep(0.05)
    snap = gw.snapshot()  # pre-start: the queue is holding both
    q = snap["queue"]
    assert q["depth"] == 2
    assert q["oldest_wait_s"] > 0
    assert q["enqueue_rate_per_s"] > 0
    assert q["by_tier"] == {"interactive": 1, "batch": 1}
    assert q["per_replica"][0]["replica"] == 0
    assert q["per_replica"][0]["depth"] == 2
    adm = snap["admission"]
    assert adm["by_tier"]["interactive"]["queued"] == 1
    assert adm["tiers"]["interactive"] > adm["tiers"]["batch"]
    row = snap["replicas"][0]
    assert row["enqueued"] == 2 and row["oldest_wait_s"] > 0
    gw.start()
    assert gw.drain(timeout=120)
    rows = [json.loads(ln) for ln in open(
        tmp_path / "intermediate" / hist.app_id / "metrics" /
        "requests.jsonl")]
    by_id = {r["id"]: r for r in rows}
    assert by_id["a"]["tenant"] == "acme"
    assert by_id["a"]["priority"] == "interactive"
    assert by_id["b"]["tenant"] is None
    assert by_id["b"]["priority"] == "batch"
    assert all(r["queue_pos"] >= 0 for r in rows)
    snap = gw.snapshot()
    assert snap["admission"]["by_tier"]["batch"]["completed"] == 1
    assert snap["admission"]["by_tier"]["interactive"]["completed"] == 1


def test_wfq_batch_flood_cannot_starve_interactive(tiny):
    """THE WFQ acceptance pin: 16 queued batch requests, then 4
    interactive arrivals — the interactive tier is admitted almost
    immediately (at most a couple of batch admissions ahead of it),
    while an idle fleet (the batch-only phase after interactive
    drains) still gives batch its full throughput."""
    servers = [_server(tiny, batch_size=1, chunk_steps=1)]
    gw = Gateway(servers, max_queue=64)  # NOT started: queue builds up
    batch = [gw.submit(GenRequest([1 + i % 5, 2, 3], max_new_tokens=4,
                                  id=f"b{i}", priority="batch"))
             for i in range(16)]
    inter = [gw.submit(GenRequest([7, 2 + i], max_new_tokens=4,
                                  id=f"i{i}", priority="interactive"))
             for i in range(4)]
    gw.start()
    for t in batch + inter:
        t.result(timeout=240)
    # admission order: every interactive ticket entered a slot before
    # all but (at most) 2 of the 16 batch tickets
    last_inter_admit = max(t.t_admit for t in inter)
    batch_before = sum(1 for t in batch if t.t_admit < last_inter_admit)
    assert batch_before <= 2, (batch_before,
                               sorted(t.t_admit for t in batch),
                               last_inter_admit)
    # bounded queue wait: interactive p99 beats the batch median
    inter_waits = sorted(t.metrics["queue_wait_ms"] for t in inter)
    batch_waits = sorted(t.metrics["queue_wait_ms"] for t in batch)
    assert inter_waits[-1] < batch_waits[len(batch_waits) // 2], (
        inter_waits, batch_waits)
    # full batch throughput once interactive is gone: every batch
    # request completed (nothing starved, nothing shed)
    snap = gw.snapshot()
    assert snap["admission"]["by_tier"]["batch"]["completed"] == 16
    assert snap["admission"]["by_tier"]["interactive"]["completed"] == 4
    assert snap["shed"] == {}
    assert gw.drain(timeout=60)


def test_deadline_anchored_to_submit_across_failover(tiny):
    """Satellite 3: a failover re-enqueue refreshes ``t_queued`` but
    must NOT extend the request's deadline — ``ttl_s`` counts from the
    original submit. The ticket here gets 0.5 s of life, fails over at
    ~0.3 s (deadline under refreshed-at-enqueue semantics would be
    ~0.8 s), and is checked at ~0.7 s: anchored semantics shed it 504."""
    servers = [_server(tiny, batch_size=1) for _ in range(2)]
    gw = Gateway(servers, max_queue=16)  # not started: deterministic
    ticket = gw.submit(GenRequest([1, 2, 3], max_new_tokens=4,
                                  ttl_s=0.5, id="anchored"))
    assert ticket.deadline == pytest.approx(ticket.t_submit + 0.5)
    time.sleep(0.3)
    victim = gw.replicas[ticket.replica]
    gw._fail_replica(victim, victim.epoch, "injected for the test")
    assert ticket.replica != victim.index  # moved, untouched (queued)
    assert ticket.attempts == 0
    # the re-enqueue refreshed t_queued; the deadline must not move
    assert ticket.t_queued > ticket.t_submit
    assert ticket.deadline == pytest.approx(ticket.t_submit + 0.5)
    time.sleep(0.4)  # now past the anchored deadline, inside a
    # hypothetical refreshed one
    gw.start()
    from tony_tpu.gateway import DeadlineExceeded

    with pytest.raises(DeadlineExceeded):
        ticket.result(timeout=120)
    snap = gw.snapshot()
    assert snap["shed"].get(504) == 1
    assert gw.drain(timeout=60)


# --------------------------------------------------- dynamic membership


def test_add_replica_probe_admission_and_remove_zero_loss(tiny):
    """add_replica joins via a real probe generation (state BROKEN
    until the probe lands), serves traffic, and remove_replica drains
    zero-loss and releases the engine."""
    gw = Gateway([_server(tiny)], max_queue=64,
                 breaker_base_s=0.02, breaker_max_s=0.1).start()
    idx = gw.add_replica(_server(tiny), probe=True)
    r = gw.replicas[idx]
    assert r.spawned
    deadline = time.monotonic() + 60
    saw_non_healthy = r.state != HEALTHY
    while r.state != HEALTHY and time.monotonic() < deadline:
        time.sleep(0.01)
    assert saw_non_healthy, "scale-up must not join routing instantly"
    assert r.state == HEALTHY
    assert gw.stats.probes >= 1 and gw.stats.rejoins >= 1
    # both replicas do real work under load
    tickets = [gw.submit(GenRequest([1 + i % 5, 2], max_new_tokens=3,
                                    id=i)) for i in range(12)]
    for t in tickets:
        t.result(timeout=120)
    assert all(rep.completed >= 1 for rep in gw.replicas)
    # scale-down: zero-loss, engine released, out of /stats rows
    inflight = [gw.submit(GenRequest([9, 8, 7], max_new_tokens=3, id="z"))]
    assert gw.remove_replica(idx, timeout=120)
    assert r.retired and r.state == RETIRED and r.server is None
    for t in inflight:
        assert t.result(timeout=120).tokens == _solo(tiny, [9, 8, 7], 3)
    snap = gw.snapshot()
    assert [row["replica"] for row in snap["replicas"]] == [0]
    assert snap["supervision"]["replicas_added"] == 1
    assert snap["supervision"]["replicas_removed"] == 1
    assert snap["supervision"]["retired"] == 1
    with pytest.raises(ValueError, match="last live replica"):
        gw.remove_replica(0)
    assert gw.drain(timeout=60)
    assert snap["shed"] == {}


# ----------------------------------------------------- scaler decisions


class _FakeGateway:
    """Just enough gateway for AutoScaler.decide(): no replicas, no
    engines — signal dicts are handed in directly."""

    def __init__(self):
        self.scaler = None
        self.live_replicas = []
        self.history = None


def _sig(**kw):
    base = dict(now=time.monotonic(), replicas_live=1,
                replicas_routable=1, depth=0, oldest_wait_s=0.0,
                enqueue_rate_per_s=0.0, by_tier={}, per_replica=[],
                active_slots=0, slots=4, shed_capacity_total=0,
                ttft_hist={"count": 0, "sum": 0.0, "buckets": {}},
                kv_pages_total=0, kv_pages_free=0)
    base.update(kw)
    return base


def _scaler(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_stable", 2)
    kw.setdefault("down_stable", 3)
    kw.setdefault("cooldown_up_s", 0.05)
    kw.setdefault("cooldown_down_s", 0.05)
    return AutoScaler(_FakeGateway(), ThreadBackend(lambda: None), **kw)


def test_scaler_decide_hysteresis_streaks_and_bounds():
    sc = _scaler()
    hot = _sig(depth=10, oldest_wait_s=2.0)
    now = time.monotonic()
    assert sc.decide(hot, now) == (None, ["queue_depth 10 (10.0/replica)",
                                          "oldest_wait 2.00s"])
    action, _ = sc.decide(hot, now)  # second consecutive breach
    assert action == "up"
    # at the ceiling the same pressure is a no-op
    sc2 = _scaler(max_replicas=1)
    for _ in range(5):
        action, _ = sc2.decide(_sig(depth=10), now)
    assert action is None
    # idle needs down_stable consecutive ticks AND live > min
    sc3 = _scaler()
    idle = _sig(replicas_live=2)
    assert sc3.decide(idle, now)[0] is None
    assert sc3.decide(idle, now)[0] is None
    assert sc3.decide(idle, now)[0] == "down"
    # at the floor, idleness never scales down
    sc4 = _scaler()
    for _ in range(6):
        action, _ = sc4.decide(_sig(replicas_live=1), now)
    assert action is None


def test_scaler_below_floor_scales_up_without_pressure():
    """An under-provisioned fleet (boot below --autoscale-min, or a
    prior scale-up failed) grows toward the floor regardless of
    pressure, paced by the cooldown."""
    sc = _scaler(min_replicas=2, max_replicas=3)
    now = time.monotonic()
    action, reasons = sc.decide(_sig(replicas_live=1), now)
    assert action == "up" and reasons == ["below floor (1 < min 2)"]
    sc._after_action(up=True)  # cooldown paces the retry
    assert sc.decide(_sig(replicas_live=1), now)[0] is None


def test_scaler_alternating_signals_never_flap():
    """Hysteresis: pressure interleaved with calm ticks never crosses
    a streak threshold — the loop cannot flap."""
    sc = _scaler(up_stable=2, down_stable=2)
    now = time.monotonic()
    busy = _sig(depth=10, replicas_live=2, active_slots=4)
    calm = _sig(replicas_live=2, active_slots=2)  # not idle (slots hot)
    for _ in range(10):
        assert sc.decide(busy, now)[0] is None
        assert sc.decide(calm, now)[0] is None


def test_scaler_cooldown_blocks_actions():
    sc = _scaler(up_stable=1, cooldown_up_s=30.0)
    now = time.monotonic()
    assert sc.decide(_sig(depth=10, replicas_live=1), now)[0] == "up"
    sc._after_action(up=True)  # what _scale_up does
    for _ in range(5):
        assert sc.decide(_sig(depth=10, replicas_live=1),
                         time.monotonic())[0] is None


def test_scaler_slo_burn_from_histogram_deltas():
    sc = _scaler(up_stable=1, ttft_slo_s=0.1, slo_burn=0.25,
                 min_slo_sample=4)
    # seed the cumulative baseline
    sc._ttft_burn(_sig(ttft_hist={"count": 10, "sum": 1.0,
                                  "buckets": {"0.1": 10}}))
    # 6 of the next 8 completions blew the 100 ms SLO
    burn = sc._ttft_burn(_sig(ttft_hist={
        "count": 18, "sum": 9.0, "buckets": {"0.1": 12, "0.5": 6}}))
    assert burn == pytest.approx(0.75)
    # too small a delta to vote
    assert sc._ttft_burn(_sig(ttft_hist={
        "count": 19, "sum": 9.5, "buckets": {"0.1": 12, "0.5": 7}})) is None
    # an SLO BETWEEN bucket edges rounds UP to the next edge: the
    # straddling bucket counts as within-SLO (a fleet at 0.28 s with a
    # 0.3 s SLO must not read as 100% burn)
    sc2 = _scaler(up_stable=1, ttft_slo_s=0.3, slo_burn=0.25,
                  min_slo_sample=4)
    sc2._ttft_burn(_sig(ttft_hist={"count": 0, "sum": 0, "buckets": {}}))
    burn = sc2._ttft_burn(_sig(ttft_hist={
        "count": 10, "sum": 2.8, "buckets": {"0.5": 10}}))
    assert burn == 0.0
    burn = sc2._ttft_burn(_sig(ttft_hist={
        "count": 20, "sum": 22.8, "buckets": {"0.5": 10, "2.5": 10}}))
    assert burn == pytest.approx(1.0)


def test_scaler_kv_pressure_signal():
    sc = _scaler(kv_used_frac=0.9)
    reasons = sc._pressure_reasons(_sig(kv_pages_total=100,
                                        kv_pages_free=5))
    assert any("kv_pages" in r for r in reasons)
    assert sc._pressure_reasons(_sig(kv_pages_total=100,
                                     kv_pages_free=50)) == []


def test_provisioner_backend_acquires_and_releases():
    """ProvisionerBackend: one slice per dynamic replica, deprovision
    on destroy, deprovision-on-failed-build, typed ScaleError on
    acquisition failure."""
    events = []

    class FakeProv:
        def __init__(self, slot, fail=False):
            self.slot, self.fail = slot, fail

        def provision(self):
            if self.fail:
                raise RuntimeError("quota")
            events.append(("provision", self.slot))
            return [f"10.0.0.{self.slot}"]

        def deprovision(self):
            events.append(("deprovision", self.slot))

    backend = ProvisionerBackend(lambda slot: FakeProv(slot),
                                 lambda hosts: {"hosts": hosts})
    s0 = backend.create()
    assert s0 == {"hosts": ["10.0.0.0"]}
    backend.destroy(s0)
    assert events == [("provision", 0), ("deprovision", 0)]
    with pytest.raises(ScaleError, match="provision failed"):
        ProvisionerBackend(lambda slot: FakeProv(slot, fail=True),
                           lambda hosts: None).create()
    # server build failing after a successful provision tears the
    # slice back down — no leaked capacity
    events.clear()

    def bad_build(hosts):
        raise RuntimeError("oom")

    backend2 = ProvisionerBackend(lambda slot: FakeProv(slot), bad_build)
    with pytest.raises(ScaleError, match="server build"):
        backend2.create()
    assert events == [("provision", 0), ("deprovision", 0)]


def test_scaler_survives_backend_failure(tiny):
    """A broken backend costs a recorded up_failed decision + a
    cooldown, never a dead loop or a broken gateway."""

    def explode():
        raise RuntimeError("no capacity")

    gw = Gateway([_server(tiny)], max_queue=64).start()
    sc = AutoScaler(gw, ThreadBackend(explode), min_replicas=1,
                    max_replicas=2, up_stable=1, up_queue_depth=0.5,
                    cooldown_up_s=30.0)
    tickets = [gw.submit(GenRequest([1, 2, 3], max_new_tokens=8, id=i))
               for i in range(8)]
    assert sc.tick() == "up"  # pressured -> tries, fails, records
    assert sc.errors == 1 and sc.scale_ups == 0
    assert [d["action"] for d in sc.decisions] == ["up_failed"]
    assert sc.tick() is None  # cooldown: no hot-looping the backend
    for t in tickets:
        t.result(timeout=120)  # gateway unharmed
    assert gw.drain(timeout=60)


def test_scale_up_failed_join_releases_capacity(tiny):
    """Capacity acquired for a scale-up whose gateway join then fails
    (e.g. the gateway closed while a slow slice provision was in
    flight) is released — a billed TPU slice must never leak."""
    events = []

    class Backend:
        def create(self):
            events.append("create")
            return "capacity"

        def destroy(self, server):
            events.append(("destroy", server))

        def describe(self):
            return "fake"

    gw = Gateway([_server(tiny)], max_queue=8).start()
    sc = AutoScaler(gw, Backend(), min_replicas=1, max_replicas=2)
    assert gw.drain(timeout=60)  # closes the gateway (and stops sc)
    sc._scale_up(_sig(), ["test"])  # add_replica -> GatewayClosed
    assert events == ["create", ("destroy", "capacity")]
    assert sc.scale_ups == 0 and sc.errors == 1
    assert [d["action"] for d in sc.decisions] == ["up_failed"]


# ------------------------------------------------- the scaling anchor


def test_autoscaler_burst_scales_up_probe_admitted_then_drains(tiny):
    """The ISSUE-9 chaos-style scaling anchor: a synthetic burst makes
    the autoscaler add a replica (entering via probe admission), every
    stream stays byte-exact with zero 5xx, and once idle the fleet
    drains back to the floor with zero accepted-request loss."""
    gw = Gateway([_server(tiny, chunk_steps=1)], max_queue=256,
                 breaker_base_s=0.02, breaker_max_s=0.1).start()
    sc = AutoScaler(
        gw, ThreadBackend(lambda: _server(tiny, chunk_steps=1)),
        min_replicas=1, max_replicas=2, interval_s=0.05,
        up_queue_depth=1.5, up_wait_s=0.5, up_stable=1, down_stable=3,
        cooldown_up_s=0.1, cooldown_down_s=0.2,
        drain_timeout_s=120).start()
    prompts = [[1 + i % 5, 2, 3] for i in range(24)]
    streams: dict[int, list] = {i: [] for i in range(len(prompts))}

    def on_event(ticket, event):
        if event[0] == "tokens":
            streams[ticket.request.id].extend(event[1])

    tickets = [gw.submit(GenRequest(p, max_new_tokens=12, id=i), on_event)
               for i, p in enumerate(prompts)]
    results = [t.result(timeout=240) for t in tickets]
    deadline = time.monotonic() + 60
    while sc.scale_ups < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sc.scale_ups >= 1, sc.status()
    # probe admission: the newcomer went through a real probe
    assert gw.stats.probes >= 1 and gw.stats.rejoins >= 1
    # byte-exact everywhere: result AND the streamed deltas
    for i, res in enumerate(results):
        want = _solo(tiny, prompts[i], 12)
        assert res.tokens == want, i
        assert streams[i] == want, i
    # idle -> drains back to the floor, zero loss along the way
    while len(gw.live_replicas) > 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(gw.live_replicas) == 1, sc.status()
    assert sc.scale_downs >= 1
    snap = gw.snapshot()
    assert snap["completed"] == len(prompts)
    assert snap["shed"] == {}  # zero 5xx (or any shed) throughout
    assert snap["scaler"]["scale_ups"] >= 1
    assert snap["scaler"]["last_decisions"], snap["scaler"]
    assert gw.drain(timeout=120)


def test_scaling_decisions_land_in_history(tiny, tmp_path):
    from tony_tpu.gateway import GatewayHistory

    hist = GatewayHistory(str(tmp_path), n_replicas=1)
    gw = Gateway([_server(tiny)], max_queue=64, history=hist,
                 breaker_base_s=0.02, breaker_max_s=0.1).start()
    sc = AutoScaler(gw, ThreadBackend(lambda: _server(tiny)),
                    min_replicas=1, max_replicas=2, up_stable=1,
                    cooldown_up_s=0.0)
    tickets = [gw.submit(GenRequest([1, 2], max_new_tokens=6, id=i))
               for i in range(10)]
    assert sc.tick() == "up"
    for t in tickets:
        t.result(timeout=120)
    assert gw.drain(timeout=120)
    rows = [json.loads(ln) for ln in open(
        tmp_path / "intermediate" / hist.app_id / "metrics" /
        "scaling.jsonl")]
    assert rows and rows[0]["action"] == "up"
    assert rows[0]["reasons"] and "replicas_live" in rows[0]


# ---------------------------------------------------------------- http


def test_http_quota_retry_after_and_priority(tiny):
    # slow refill on purpose: the first request's decode time must not
    # refill the bucket enough to admit the second
    gw = Gateway([_server(tiny, chunk_steps=1)], max_queue=16,
                 tenant_quota_rate=0.5, tenant_quota_burst=12.0).start()
    http = GatewayHTTP(gw).start()
    url = f"http://{http.host}:{http.port}"
    try:
        req = urllib.request.Request(
            url + "/v1/generate",
            data=json.dumps({"token_ids": [1, 2, 3], "max_new_tokens": 8,
                             "tenant": "acme",
                             "priority": "interactive"}).encode(),
            headers={"Content-Type": "application/json"})
        doc = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert doc["metrics"]["priority"] == "interactive"
        assert doc["metrics"]["tenant"] == "acme"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                url + "/v1/generate",
                data=json.dumps({"token_ids": [1] * 10,
                                 "max_new_tokens": 20,
                                 "tenant": "acme"}).encode(),
                headers={"Content-Type": "application/json"}),
                timeout=120)
        assert e.value.code == 429
        assert int(e.value.headers["Retry-After"]) >= 1
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                url + "/v1/generate",
                data=json.dumps({"token_ids": [9], "max_new_tokens": 2,
                                 "priority": "vip"}).encode(),
                headers={"Content-Type": "application/json"}),
                timeout=120)
        assert e.value.code == 400
        # /stats and /metrics carry the new families
        stats = json.loads(urllib.request.urlopen(
            url + "/stats", timeout=30).read())
        assert stats["queue"]["depth"] == 0
        assert stats["admission"]["quota"]["rejections"] == 1
        text = urllib.request.urlopen(url + "/metrics",
                                      timeout=30).read().decode()
        assert "tony_quota_rejections_total 1" in text
        assert 'tony_tier_queue_wait_seconds_bucket{tier="interactive"' \
            in text
        assert "tony_queue_oldest_wait_seconds" in text
    finally:
        gw.drain(timeout=60)
        http.stop()


# ----------------------------------------------------------- slow gate


@pytest.mark.slow  # timing comparison; tier-1 runs -m 'not slow'
def test_scaleup_beats_fixed_fleet_p99_queue_wait(tiny):
    """The acceptance's perf clause: under the same burst, the
    autoscaled fleet's p99 queue wait drops vs a fixed-size control."""

    def burst(gw):
        tickets = [gw.submit(GenRequest([1 + i % 5, 2, 3],
                                        max_new_tokens=24, id=i))
                   for i in range(24)]
        for t in tickets:
            t.result(timeout=300)
        waits = sorted(t.metrics["queue_wait_ms"] for t in tickets)
        return waits[int(0.99 * (len(waits) - 1))]

    fixed = Gateway([_server(tiny, chunk_steps=1)], max_queue=256).start()
    p99_fixed = burst(fixed)
    assert fixed.drain(timeout=120)

    gw = Gateway([_server(tiny, chunk_steps=1)], max_queue=256,
                 breaker_base_s=0.02, breaker_max_s=0.1).start()
    AutoScaler(gw, ThreadBackend(lambda: _server(tiny, chunk_steps=1)),
               min_replicas=1, max_replicas=3, interval_s=0.05,
               up_queue_depth=1.5, up_wait_s=0.3, up_stable=1,
               down_stable=50, cooldown_up_s=0.2,
               drain_timeout_s=120).start()
    p99_scaled = burst(gw)
    assert gw.scaler.scale_ups >= 1
    assert gw.drain(timeout=120)
    assert p99_scaled < p99_fixed, (p99_scaled, p99_fixed)
