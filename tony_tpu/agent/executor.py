"""Per-task agent process — TaskExecutor equivalent.

Reference: TaskExecutor.java (452 LoC): reads identity env, connects the
control-plane + metrics RPC proxies, reserves rendezvous/TensorBoard ports,
registers its worker spec and polls until the runtime's gate opens, runs a
heartbeater thread (with fault-injected miss support) and the metrics
sampler, releases ports, delegates to the runtime task adapter to exec the
user process, and registers the exit code back to the coordinator.

Process entry: ``python -m tony_tpu.agent`` with env injected by the
coordinator's launcher (ref: TaskExecutor.main :189 / initConfigs :240-281).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from tony_tpu import constants as C
from tony_tpu.config import TonyConf
from tony_tpu.metrics import TaskMetricsMonitor
from tony_tpu.rpc import RpcClient
from tony_tpu.runtime import TaskContext, get_task_adapter
from tony_tpu.utils import local_host_name, reserve_port

log = logging.getLogger(__name__)


class Heartbeater(threading.Thread):
    """Ref: inner Heartbeater (TaskExecutor.java:322-362): pings every
    interval, tolerates 5 consecutive send failures, supports the
    TEST_TONY_NUM_HB_MISS injection that skips N pings."""

    MAX_SEND_FAILURES = 5

    def __init__(self, client: RpcClient, task_id: str, interval_ms: int,
                 workdir: str | None = None, on_lost=None,
                 lost_after_s: float | None = None):
        super().__init__(name="heartbeater", daemon=True)
        self.client = client
        self.task_id = task_id
        self.interval_s = max(interval_ms, 50) / 1000
        self.misses_to_skip = int(os.environ.get(C.TEST_TASK_NUM_HB_MISS, "0"))
        self.workdir = workdir
        self.on_lost = on_lost
        # keep pinging through failures until this much time has passed
        # (the coordinator-side expiry horizon); only then declare it lost
        self.lost_after_s = lost_after_s
        self._stop = threading.Event()

    def _handle_commands(self, response) -> None:
        """Coordinator->agent commands piggybacked on the heartbeat ack."""
        if not isinstance(response, dict):
            return
        for cmd in response.get("commands") or []:
            if cmd.get("type") == "profile" and self.workdir:
                from tony_tpu.profiler import write_trigger

                write_trigger(self.workdir, int(cmd.get("num_steps", 5)),
                              task_id=self.task_id)
                log.info("profile trigger dropped for %s", self.task_id)
            elif cmd.get("type") == "save_and_exit" and self.workdir:
                from tony_tpu.elastic import write_save_and_exit

                write_save_and_exit(self.workdir, task_id=self.task_id,
                                    reason=str(cmd.get("reason", "resize")))
                log.info("save_and_exit requested for %s", self.task_id)
            else:
                log.warning("unknown coordinator command: %s", cmd)

    def run(self) -> None:
        failures = 0
        outage_start: float | None = None
        while not self._stop.wait(self.interval_s):
            if self.misses_to_skip > 0:
                self.misses_to_skip -= 1
                log.info("skipping heartbeat (fault injection, %d left)",
                         self.misses_to_skip)
                continue
            try:
                response = self.client.call("task_executor_heartbeat",
                                            retries=0, task_id=self.task_id)
                failures = 0
                outage_start = None
                try:
                    self._handle_commands(response)
                except Exception:
                    # a bad command must not count against liveness — the
                    # ping itself already landed
                    log.exception("coordinator command failed")
            except Exception:
                failures += 1
                if outage_start is None:
                    outage_start = time.monotonic()
                log.warning("heartbeat send failure %d/%d", failures,
                            self.MAX_SEND_FAILURES)
                if self.on_lost is not None and self.lost_after_s:
                    # keep pinging through the outage; only past the
                    # coordinator's own expiry horizon is it truly gone.
                    # WALL-CLOCK since the first consecutive failure, not
                    # failures x interval: a blackholed host makes each
                    # failed RPC block for its own connect timeout, which
                    # would stretch a count-based horizon far past the
                    # client's respawn fence
                    outage_s = time.monotonic() - outage_start
                    if outage_s >= self.lost_after_s:
                        log.error("coordinator lost (unreachable for "
                                  "%.0fs)", outage_s)
                        self.on_lost()
                        return
                elif failures >= self.MAX_SEND_FAILURES:
                    log.error("too many heartbeat failures; giving up")
                    return

    def stop(self) -> None:
        self._stop.set()


class TaskAgent:
    def __init__(self, env: dict[str, str] | None = None):
        e = env or os.environ
        self.role = e[C.JOB_NAME]
        self.index = int(e[C.TASK_INDEX])
        self.task_num = int(e.get(C.TASK_NUM, "1"))
        self.is_chief = e.get(C.IS_CHIEF, "false") == "true"
        self.app_id = e.get(C.JOB_ID, "")
        self.session_id = int(e.get(C.SESSION_ID, "0"))
        self.mode = e.get(C.DISTRIBUTED_MODE, C.GANG)
        self.coord_host = e[C.COORDINATOR_HOST]
        self.coord_port = int(e[C.COORDINATOR_PORT])
        self.metrics_port = int(e.get(C.METRICS_PORT, "0"))
        self.secret = e.get(C.JOB_TOKEN) or None
        self.command = e.get("TONY_TASK_COMMAND", "")
        self.job_dir = e.get("TONY_JOB_DIR", ".")
        conf_path = e.get("TONY_CONF_PATH", "")
        self.conf = TonyConf.from_final(conf_path) if conf_path and \
            os.path.exists(conf_path) else TonyConf()
        self.task_id = f"{self.role}:{self.index}"
        tls_fp = e.get(C.TLS_FINGERPRINT) or None
        self.client = RpcClient(self.coord_host, self.coord_port,
                                secret=self.secret, tls_fingerprint=tls_fp)
        self.metrics_client = RpcClient(
            self.coord_host, self.metrics_port, secret=self.secret,
            tls_fingerprint=tls_fp) if self.metrics_port else None
        self.adapter = get_task_adapter(str(self.conf.get("tony.application.framework")))
        self._user_pid: int | None = None
        self.preempted = False

    def _install_preemption_handler(self) -> None:
        """SIGTERM = TPU spot preemption / maintenance notice (the
        heartbeat-expiry analog of SURVEY 7.9b): forward it to the user
        process group with a checkpoint grace window, and report the exit
        as preempted so the coordinator retry can resume from checkpoint.
        Main-thread only (signal module restriction); launch modes that run
        the agent off the main thread just skip it."""
        import signal as _signal

        from tony_tpu.utils.shell import request_graceful_shutdown

        grace = self.conf.get_int("tony.task.preemption-grace-ms", 15_000)

        def forward():
            # runs on a worker thread: request_graceful_shutdown (and
            # logging) take locks, which a handler on the interrupted main
            # thread could self-deadlock on
            log.warning("SIGTERM: preemption/maintenance — forwarding to "
                        "user process with %d ms checkpoint grace", grace)
            if request_graceful_shutdown(grace) == 0:
                # nothing registered to forward to (e.g. an adapter that
                # spawns children outside the exec registry, or between
                # exec points): don't swallow the signal and hang — die
                # like the default disposition would have (128+SIGTERM;
                # signal.signal can't be called off the main thread)
                log.warning("no active user process; exiting on SIGTERM")
                os._exit(143)

        def on_sigterm(signum, frame):
            self.preempted = True
            threading.Thread(target=forward, daemon=True).start()

        try:
            _signal.signal(_signal.SIGTERM, on_sigterm)
        except ValueError:  # not on the main thread
            log.debug("not main thread; preemption handler not installed")

    def _clean_stale_control_files(self) -> None:
        """A previous epoch's save_and_exit/profile file for this task id
        must not fire at step 0 of the new epoch. Runs on the task's own
        host, so it also covers ssh launch mode where the coordinator's
        job-dir cleanup can't reach."""
        import contextlib

        from tony_tpu.elastic import control_path
        from tony_tpu.profiler import trigger_path

        for path in (control_path(self.job_dir, self.task_id),
                     trigger_path(self.job_dir, self.task_id)):
            with contextlib.suppress(OSError):
                os.remove(path)

    # -- fault injection (ref: skewAndHangIfTesting :364-384) ---------------
    def _skew_if_testing(self) -> None:
        spec = os.environ.get(C.TEST_TASK_SKEW, "")
        if not spec:
            return
        try:
            role, idx, ms = spec.split("#")
            if role == self.role and int(idx) == self.index:
                log.info("skew injection: sleeping %s ms", ms)
                time.sleep(int(ms) / 1000)
        except ValueError:
            log.warning("bad skew spec %r", spec)

    # -- main flow ----------------------------------------------------------
    def run(self) -> int:
        """Ref: TaskExecutor.main :189-237."""
        self._skew_if_testing()
        self._clean_stale_control_files()
        reuse = self.conf.get_bool("tony.task.reuse-port", False)
        rdzv = None
        tb = None
        if self.adapter.need_reserve_rdzv_port(self.role, self.conf):
            rdzv = reserve_port(reuse=reuse)
        if self.adapter.need_reserve_tb_port(self.role, self.is_chief, self.conf):
            tb = reserve_port(reuse=reuse)

        def coordinator_lost():
            # the gang's brain is gone: a replacement coordinator will
            # relaunch this task, so finish the orphan instead of leaving
            # two generations of user processes running side by side
            from tony_tpu.utils.shell import request_graceful_shutdown

            grace = self.conf.get_int("tony.task.preemption-grace-ms", 15_000)
            log.error("coordinator unreachable; shutting down task (grace "
                      "%d ms)", grace)
            request_graceful_shutdown(grace)
            # the SIGKILL backstop runs on a daemon thread — exiting now
            # would kill it and orphan a SIGTERM-ignoring user process on
            # the chip; outlive the grace window before dying
            time.sleep(grace / 1000 + 2)
            os._exit(1)

        hb_interval_ms = self.conf.get_int("tony.task.heartbeat-interval-ms",
                                           1000)
        # only kill the task once the coordinator's OWN liveness horizon
        # has passed (shared formula in coordinator/liveness.py): a shorter
        # fuse would hard-fail healthy jobs on a transient RPC blip the
        # coordinator itself tolerates
        from tony_tpu.coordinator.liveness import (
            heartbeat_rpc_timeout_s,
            liveness_expiry_s,
        )

        # dedicated short-timeout channel: a blackholed coordinator must
        # not block each ping for the default 30 s RPC timeout, which
        # would push loss detection far past the client's respawn fence
        hb_client = RpcClient(
            self.coord_host, self.coord_port, secret=self.secret,
            timeout=heartbeat_rpc_timeout_s(self.conf),
            tls_fingerprint=os.environ.get(C.TLS_FINGERPRINT) or None)
        hb = Heartbeater(
            hb_client, self.task_id, hb_interval_ms,
            workdir=self.job_dir, on_lost=coordinator_lost,
            lost_after_s=liveness_expiry_s(self.conf))
        hb.start()
        monitor = None
        if self.metrics_client is not None:
            monitor = TaskMetricsMonitor(
                lambda: self._user_pid or os.getpid(),
                lambda m: self.metrics_client.call(
                    "update_metrics", retries=0, task_id=self.task_id, metrics=m),
                self.conf.get_int("tony.task.metrics-interval-ms", 5000),
                tpu_info_exec_path=str(
                    self.conf.get("tony.tpu.info-exec-path", "")),
            ).start()

        host = local_host_name()
        port = rdzv.port if rdzv else 0
        spec_str = f"{host}:{port}"
        log.info("registering %s at %s", self.task_id, spec_str)
        cluster_spec_json = self.client.poll_till_non_null(
            lambda: self.client.call("register_worker_spec",
                                     task_id=self.task_id, spec=spec_str),
            interval_s=0.3,
        )
        cluster_spec = json.loads(cluster_spec_json)
        # runtime-private payload rides the spec under "__aux__" (e.g. the
        # horovod rendezvous/slot plan); strip it so role->hosts stays pure
        aux = cluster_spec.pop("__aux__", {})
        log.info("gang ready; cluster spec: %s", cluster_spec)

        # release before exec so the user process can bind (ref:
        # TaskExecutor.java:202-215; SO_REUSEPORT mode skips the release)
        if rdzv and not reuse:
            rdzv.release()
        if tb and not reuse:
            tb.release()
        if tb:
            # ref: TaskExecutor.registerTensorBoardUrl :303-311 -> AM
            # registerTensorBoardUrlToRM; here it lands in the app status
            try:
                self.client.call("register_tensorboard_url",
                                 url=f"http://{host}:{tb.port}")
            except Exception:
                log.warning("failed to register tensorboard url", exc_info=True)

        ctx = TaskContext(
            conf=self.conf,
            role=self.role,
            index=self.index,
            task_num=self.task_num,
            is_chief=self.is_chief,
            cluster_spec=cluster_spec,
            command=self.command,
            app_id=self.app_id,
            session_id=self.session_id,
            rdzv_port=port,
            tb_port=tb.port if tb else -1,
            log_path=os.path.join(self.job_dir, "logs",
                                  f"{self.role}-{self.index}-user{C.LOG_SUFFIX}"),
            workdir=self.job_dir,
            aux=aux,
            callback_to_am=lambda info: self.client.call(
                "register_callback_info", task_id=self.task_id, info=info),
            extra_env={
                C.JOB_ID: self.app_id,
                C.SESSION_ID: str(self.session_id),
                C.DISTRIBUTED_MODE: self.mode,
                C.ATTEMPT_NUMBER: os.environ.get(C.ATTEMPT_NUMBER, "0"),
                C.AGENT_PID: str(os.getpid()),
            },
        )
        self._install_preemption_handler()
        try:
            exit_code = self.adapter.run(ctx)
        except Exception:
            log.exception("task adapter run failed")
            exit_code = C.EXIT_FAIL
        finally:
            if monitor:
                monitor.stop()
            hb.stop()
            if rdzv:
                rdzv.release()
            if tb:
                tb.release()

        try:
            self.client.call("register_execution_result",
                             task_id=self.task_id, exit_code=exit_code,
                             session_id=self.session_id,
                             preempted=self.preempted)
        except Exception:
            # coordinator's launcher exit-watch is the backup path
            log.exception("failed to register execution result")
        self.client.close()
        if self.metrics_client:
            self.metrics_client.close()
        return exit_code


def main() -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    agent = TaskAgent()
    code = agent.run()
    log.info("agent for %s exiting with %d", agent.task_id, code)
    return code


if __name__ == "__main__":
    raise SystemExit(main())
