"""Sharding presets: logical-axis rules -> PartitionSpecs for model states.

The framework's models annotate arrays with *logical* axis names
("batch", "seq", "embed", "heads", "kv_heads", "mlp", "vocab", "expert",
"layers"); "kv_heads" is the GQA-shrunk K/V head dim — always replicated,
since its size (n_kv_heads) is typically smaller than the tensor axis;
a preset maps logical names to mesh axes. This is the pjit idiom: the same
model runs DP, FSDP, TP, or combinations by swapping the rule set, and XLA
inserts the collectives (no NCCL-style explicit comms as in the reference's
delegated data plane, SURVEY.md section 2.5).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel.mesh import DATA, EXPERT, FSDP, PIPE, SEQ, TENSOR

# logical axis -> mesh axis (or None = replicated) per strategy
RULES: dict[str, dict[str, Any]] = {
    # pure data parallelism: params replicated, batch sharded
    "dp": {
        "batch": (DATA, FSDP),
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None, "layers": None,
    },
    # fsdp: params sharded on the fsdp axis along their largest dim
    "fsdp": {
        "batch": (DATA, FSDP),
        "embed": FSDP,
        "seq": None, "heads": None, "kv": None, "kv_heads": None, "mlp": None,
        "vocab": None, "expert": None, "layers": None,
    },
    # tensor parallelism (megatron-style): heads + mlp sharded
    "tp": {
        "batch": (DATA, FSDP),
        "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "embed": None, "kv": None, "kv_heads": None, "expert": None, "layers": None,
    },
    # fsdp + tp combined (the common large-model preset)
    "fsdp_tp": {
        "batch": (DATA, FSDP),
        "embed": FSDP, "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "kv": None, "kv_heads": None, "expert": None, "layers": None,
    },
    # sequence/context parallelism: activations sharded along seq
    "sp": {
        "batch": (DATA, FSDP),
        "act_seq": SEQ,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None, "layers": None,
    },
    # expert parallelism for MoE blocks
    "ep": {
        "batch": (DATA, FSDP),
        "expert": EXPERT,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "layers": None,
    },
    # expert + tensor combined (large MoE: experts over the expert axis,
    # each expert's ffn dim + attention heads over tensor, batch over data)
    "ep_tp": {
        "batch": (DATA, FSDP),
        "expert": EXPERT, "heads": TENSOR, "mlp": TENSOR, "vocab": TENSOR,
        "seq": None, "embed": None, "kv": None, "kv_heads": None,
        "layers": None,
    },
    # pipeline: layers sharded across stages (used with parallel.pipeline)
    "pp": {
        "batch": (DATA, FSDP),
        "layers": PIPE,
        "seq": None, "embed": None, "heads": None, "kv": None, "kv_heads": None,
        "mlp": None, "vocab": None, "expert": None,
    },
    # SERVING tensor/expert parallelism (the sharded-replica preset,
    # ISSUE-14). Differs from "tp" in three deliberate ways:
    #   - "kv_heads" CAN shard: the paged KV pools shard on the kv-head
    #     axis, so the K/V projections must produce kv-head-sharded
    #     outputs to write into them locally (``serve_spec_for``'s
    #     validation replicates any dim the tensor axis does not
    #     divide, so small-GQA models degrade to replicated pools
    #     instead of failing);
    #   - batch replicated: a serving replica's slots are its own, the
    #     mesh buys per-chip capacity, not batch splitting;
    #   - NO contraction dim is ever sharded: row-parallel kernels
    #     (attention o, MLP wo — anything whose logical axes end in
    #     "embed" with a tensor-sharded "heads"/"mlp" before it) FLIP
    #     to output-dim (embed) sharding. A Megatron-style row-parallel
    #     layout psums per-shard partial products — a different float
    #     reduction order than one chip, which would break the serving
    #     engine's token-exactness contract. Output-dim sharding keeps
    #     every arithmetic reduction whole on one chip (identical
    #     contraction extents, identical order); all cross-chip ICI
    #     traffic is all-gather — pure data movement, bitwise. That is
    #     the structural argument behind the mesh=1 vs mesh=N
    #     byte-identical-streams gate (tests/test_shard_serve.py).
    "serve": {
        "batch": None,
        "heads": TENSOR, "kv_heads": TENSOR, "mlp": TENSOR,
        "vocab": TENSOR, "expert": EXPERT,
        "seq": None, "embed": None, "kv": None, "layers": None,
    },
}

# logical names that mark a column-parallel kernel's OUTPUT-turned-
# contraction dim in the row-parallel sibling (o consumes heads, wo
# consumes mlp) — the serve preset flips these to embed-sharded
_SERVE_FLIP_AXES = ("heads", "kv_heads", "mlp")


def spec_for(logical_axes: tuple[str | None, ...], rules: dict[str, Any]) -> P:
    """PartitionSpec from per-dimension logical names."""
    parts = []
    for name in logical_axes:
        if name is None:
            parts.append(None)
        else:
            parts.append(rules.get(name))
    # trailing Nones can be dropped but keeping them is harmless
    return P(*parts)


def tree_shardings(mesh: Mesh, logical_tree: Any, preset: str) -> Any:
    """Map a pytree of logical-axis tuples to NamedShardings."""
    rules = RULES[preset]
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_params_by_size(mesh: Mesh, params: Any, axis: str = FSDP,
                         min_size: int = 2**14) -> Any:
    """Heuristic FSDP sharding for arbitrary param trees (when a model has
    no logical annotations): shard each large array along its largest
    dimension divisible by the axis size; replicate the rest."""
    n = mesh.shape.get(axis, 1)

    def spec(x):
        if n <= 1 or x.size < min_size:
            return NamedSharding(mesh, P())
        dims = sorted(range(x.ndim), key=lambda d: -x.shape[d])
        for d in dims:
            if x.shape[d] % n == 0:
                parts: list = [None] * x.ndim
                parts[d] = axis
                return NamedSharding(mesh, P(*parts))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, params)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs: batch dim sharded over (data, fsdp)."""
    axes = tuple(a for a in (DATA, FSDP) if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ------------------------------------------------- serving (ISSUE-14)


def _axis_size(mesh: Mesh, assignment) -> int:
    """Total shard count an axis assignment (name | tuple | None)
    splits a dim into."""
    if assignment is None:
        return 1
    if isinstance(assignment, tuple):
        n = 1
        for a in assignment:
            n *= mesh.shape.get(a, 1)
        return n
    return mesh.shape.get(assignment, 1)


def validated_spec(mesh: Mesh, spec: P, shape: tuple) -> P:
    """Drop per-dim assignments the dim size does not divide — the
    shape-safe fallback (a NamedSharding over a non-divisible dim
    fails at placement; replicating that dim is always correct)."""
    parts = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, assignment in zip(shape, parts):
        n = _axis_size(mesh, assignment)
        out.append(assignment if n > 1 and dim % n == 0 else None)
    return P(*out)


def serve_spec_for(logical_axes: tuple, rules: dict[str, Any]) -> P:
    """``spec_for`` plus the serve preset's row-parallel FLIP: a kernel
    whose logical axes END in "embed" with a tensor-sharded
    "heads"/"kv_heads"/"mlp" before it (attention o, MLP/MoE wo, and
    their int8 kernel_q8 twins) is the Megatron row-parallel layout —
    sharding that leading axis would shard the CONTRACTION and psum
    per-shard partials (a different float reduction order than one
    chip). Instead the sharding moves to the trailing embed (output)
    dim: each chip reads its kernel slice, contracts over the FULL
    gathered input, and produces exact output columns — all
    cross-chip traffic stays all-gather."""
    parts = [rules.get(name) if name is not None else None
             for name in logical_axes]
    if len(parts) >= 2 and logical_axes[-1] == "embed":
        flip = [i for i, name in enumerate(logical_axes[:-1])
                if name in _SERVE_FLIP_AXES and parts[i] == TENSOR]
        if flip:
            for i in flip:
                parts[i] = None
            parts[-1] = TENSOR
    return P(*parts)


def serving_shardings(mesh: Mesh, params: Any,
                      preset: str = "serve") -> Any:
    """NamedShardings for a transformer param tree under the serving
    preset: logical axes from the param path names
    (``models.transformer.logical_axis_rules_tree`` — int8 kernel_q8 /
    scale leaves shard alongside their bf16 twins), the serve rules'
    row-parallel flip, and per-dim divisibility validation (anything
    the mesh does not divide replicates — GQA kv heads smaller than
    the tensor axis, odd vocab sizes, adapter ranks)."""
    from tony_tpu.models.transformer import logical_axis_rules_tree

    rules = RULES[preset]
    logical = logical_axis_rules_tree(params)

    def spec(axes, leaf):
        p = serve_spec_for(axes, rules) if preset == "serve" \
            else spec_for(axes, rules)
        return NamedSharding(mesh, validated_spec(mesh, p, leaf.shape))

    return jax.tree.map(spec, logical, params,
                        is_leaf=lambda x: isinstance(x, tuple))


def _kv_leaf_head_axis(path, leaf) -> int | None:
    """kv-head axis of a serving-cache leaf, by the cache name
    contract (serve/slots.cache_batch_axis keys the same names for
    the page/batch axis): KV buffers are [..., pages|b, len, kvh, dh],
    scales [..., pages|b, len, kvh]. None = not a KV leaf (shared
    counters) — replicated."""
    name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
    if name in ("cached_key", "cached_value"):
        return leaf.ndim - 2
    if name in ("cached_key_scale", "cached_value_scale"):
        return leaf.ndim - 1
    return None


def kv_cache_shardings(mesh: Mesh, cache: Any, axis: str = TENSOR) -> Any:
    """NamedShardings for a serving KV cache pytree (paged pools or
    fixed-shape rows): every KV leaf shards its KV-HEAD dim over
    ``axis`` — the page/batch and position dims stay whole, so the
    host-side page tables, free-list allocator, and reservation ledger
    are untouched (a page id means the same thing on every chip; only
    the page's CONTENT is split by head). Leaves whose kv-head count
    the axis does not divide replicate (small-GQA fallback), as do the
    shared position counters."""
    n = mesh.shape.get(axis, 1)

    def spec(path, leaf):
        ax = _kv_leaf_head_axis(path, leaf)
        if ax is None or n <= 1 or leaf.shape[ax] % n:
            return NamedSharding(mesh, P())
        parts: list = [None] * leaf.ndim
        parts[ax] = axis
        return NamedSharding(mesh, P(*parts))

    return jax.tree_util.tree_map_with_path(spec, cache)


def kv_shard_count(mesh: Mesh, cache: Any, axis: str = TENSOR) -> int:
    """How many ways ``kv_cache_shardings`` actually splits the KV
    pools (1 = replicated fallback) — the divisor per-chip KV byte
    pricing and the capacity math use."""
    n = mesh.shape.get(axis, 1)
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        ax = _kv_leaf_head_axis(path, leaf)
        if ax is not None:
            return n if n > 1 and leaf.shape[ax] % n == 0 else 1
    return 1


def tree_shard_bytes(tree: Any, shardings: Any) -> int:
    """PER-CHIP bytes of ``tree`` placed under ``shardings`` — each
    leaf contributes its shard's bytes (replicated leaves their whole
    size). The number the capacity-unlock math and the goodput
    ledger's per-chip dispatch pricing are built on."""
    leaves = jax.tree_util.tree_leaves(tree)
    shards = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    total = 0
    for leaf, sh in zip(leaves, shards):
        shape = sh.shard_shape(tuple(leaf.shape))
        total += int(np.prod(shape)) * leaf.dtype.itemsize
    return total


def tree_shard_count(tree: Any, shardings: Any) -> int:
    """PER-CHIP element count under ``shardings`` (the FLOPs twin of
    ``tree_shard_bytes`` — per-chip matmul FLOPs track the parameters
    resident on that chip)."""
    leaves = jax.tree_util.tree_leaves(tree)
    shards = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    return sum(int(np.prod(sh.shard_shape(tuple(leaf.shape))))
               for leaf, sh in zip(leaves, shards))
