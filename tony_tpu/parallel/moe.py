"""Mixture-of-Experts with expert parallelism.

Absent from the reference (SURVEY.md section 2.4: EP "NO"). Implementation
is the pjit idiom: expert weights carry a leading expert dim annotated with
the ``expert`` mesh axis; dispatch/combine are einsums against a capacity-
limited one-hot dispatch tensor, so under pjit XLA lowers the token
exchange to all-to-all over ICI — no hand-written comms.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass
class MoEConfig:
    num_experts: int = 8
    capacity_factor: float = 1.25
    top_k: int = 2
    d_model: int = 512
    d_ff: int = 2048
    # Mixtral-family experts: SwiGLU, wo(act(wg x) * (wi x)) per expert,
    # instead of the 2-matmul wo(act(wi x)) expert
    gated: bool = False
    activation: str = "gelu"  # gelu | silu
    # HF Mixtral renormalizes the selected top-k gate weights to sum to 1
    renormalize_top_k: bool = False
    # dropless=True computes EVERY expert on every token and combines by
    # gate weight — exact (no capacity dropping), memory O(E*T*ff), the
    # eval/checkpoint-parity path. False = capacity-limited dispatch
    # einsums (all-to-all under pjit), the training path.
    dropless: bool = False
    # int8 expert serving over expert parallelism: a vmapped pallas call
    # is opaque to GSPMD, so expert-sharded q8 weights fed to the vmapped
    # dequant matmul under bare pjit would be ALL-GATHERED (defeating the
    # only way a 47B Mixtral fits a slice). With ``mesh`` set and the
    # ``expert_axis`` present, the q8 expert FFN runs under shard_map over
    # that axis: each device dequant-matmuls its LOCAL experts only.
    mesh: Any = None
    expert_axis: str = "expert"


def _act(name: str):
    """Same semantics as models/transformer._activation: 'gelu' is the
    erf form, 'gelu_tanh' the approximation (HF gelu_new/pytorch_tanh)."""
    table = {
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
        "silu": jax.nn.silu,
    }
    if name not in table:
        raise ValueError(f"unsupported MoE activation {name!r} "
                         f"(supported: {sorted(table)})")
    return table[name]


def _gates(logits: jnp.ndarray, k: int, renormalize: bool):
    """Shared routing math for the routed and dropless paths: softmax
    probs, top-k gate (values, indices) — optionally renormalized to sum
    to 1 per token (Mixtral) — and the load-balancing aux loss."""
    e = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
    if renormalize:
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    return probs, gate_vals, gate_idx, _aux_loss(probs, gate_idx, e, k)


def _expert_ffn(params: dict, x: jnp.ndarray, cfg: "MoEConfig",
                up_spec: str, down_spec: str) -> jnp.ndarray:
    """Per-expert FFN shared by both paths: 2-matmul act(wi) or SwiGLU
    act(wg)*wi (``cfg.gated``), then wo. The einsum specs carry the
    layout difference (routed [E,C,D] vs dropless [T,D]-broadcast).

    int8 serving (Mixtral --int8): ``wi_q8 [E, D, F] + wi_scale [E, F]``
    (per-expert, per-output-channel — models/quantize.py) run through the
    pallas dequant matmul vmapped over the expert dim: expert weights
    cross HBM as int8, dequantized in VMEM, matching the q8 dense path.
    The vmapped outputs are exactly the einsums' expert-major layouts
    ([E, T, F] dropless / [E, C, F] routed)."""
    act = _act(cfg.activation)
    if "wi_q8" in params:
        x_axis = None if x.ndim == 2 else 0  # dropless broadcasts tokens
        ep = _expert_shards(cfg)
        if ep > 1:
            # expert-sharded int8 serving: shard_map over the expert axis
            # so each device's pallas dequant matmul sees only its local
            # expert shard (vmapped pallas is opaque to GSPMD — bare pjit
            # would all-gather the very weights EP exists to split)
            from jax.sharding import PartitionSpec as P

            from tony_tpu.utils.compat import shard_map

            ax = cfg.expert_axis
            w3, w2 = P(ax, None, None), P(ax, None)
            xspec = P(None, None) if x_axis is None else P(ax, None, None)
            names = [nm for nm in ("wi", "wg", "wo")
                     if nm + "_q8" in params]
            weights = [params[nm + sfx] for nm in names
                       for sfx in ("_q8", "_scale")]
            w_specs = [sp for _ in names for sp in (w3, w2)]

            def local_ffn(x_l, *flat):
                local = {nm + sfx: flat[2 * i + j]
                         for i, nm in enumerate(names)
                         for j, sfx in enumerate(("_q8", "_scale"))}
                return _q8_expert_ffn(local, x_l, x_axis, act, cfg.gated)

            return shard_map(
                local_ffn, mesh=cfg.mesh,
                in_specs=(xspec, *w_specs),
                out_specs=P(ax, None, None),
                check_vma=False,
            )(x, *weights)
        return _q8_expert_ffn(params, x, x_axis, act, cfg.gated)
    up = jnp.einsum(up_spec, x, params["wi"])
    if cfg.gated:
        h = act(jnp.einsum(up_spec, x, params["wg"])) * up
    else:
        h = act(up)
    return jnp.einsum(down_spec, h, params["wo"])


def _expert_shards(cfg: MoEConfig) -> int:
    """Way size of the expert axis when the q8 shard_map path applies
    (mesh set, axis present, experts divisible); 1 = run unsharded."""
    if cfg.mesh is None or cfg.expert_axis not in cfg.mesh.shape:
        return 1
    ways = cfg.mesh.shape[cfg.expert_axis]
    return ways if ways > 1 and cfg.num_experts % ways == 0 else 1


def _q8_expert_ffn(params: dict, x, x_axis, act, gated: bool):
    """The vmapped int8 expert FFN body (shard-local or global): expert
    weights cross HBM as int8 tiles and dequantize in VMEM (ops/quant)."""
    from tony_tpu.ops.quant import q8_matmul

    up_mm = jax.vmap(q8_matmul, in_axes=(x_axis, 0, 0))
    up = up_mm(x, params["wi_q8"], params["wi_scale"])
    if gated:
        h = act(up_mm(x, params["wg_q8"], params["wg_scale"])) * up
    else:
        h = act(up)
    return jax.vmap(q8_matmul)(h, params["wo_q8"], params["wo_scale"])


def init_moe_params(key, cfg: MoEConfig, dtype=jnp.float32) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale_in = cfg.d_model ** -0.5
    params = {
        "router": jax.random.normal(k1, (cfg.d_model, cfg.num_experts),
                                    dtype) * scale_in,
        # leading expert dim -> sharded on the "expert" mesh axis
        "wi": jax.random.normal(k2, (cfg.num_experts, cfg.d_model, cfg.d_ff),
                                dtype) * scale_in,
        "wo": jax.random.normal(k3, (cfg.num_experts, cfg.d_ff, cfg.d_model),
                                dtype) * (cfg.d_ff ** -0.5),
    }
    if cfg.gated:
        params["wg"] = jax.random.normal(
            k4, (cfg.num_experts, cfg.d_model, cfg.d_ff), dtype) * scale_in
    return params


def moe_logical_axes() -> dict:
    """Logical sharding annotations (see parallel.sharding RULES['ep'])."""
    return {
        "router": (None, None),
        "wi": ("expert", None, "mlp"),
        "wg": ("expert", None, "mlp"),
        "wo": ("expert", "mlp", None),
    }


def _aux_loss(probs, gate_idx, e, k):
    """Switch/GShard load-balancing loss from routing decisions."""
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jnp.sum(jax.nn.one_hot(gate_idx, e), axis=1), axis=0)
    return e * jnp.sum(me * ce) / k


def top_k_gating(logits: jnp.ndarray, k: int, capacity: int,
                 renormalize: bool = False):
    """Top-k token->expert routing with per-expert capacity.

    logits: [T, E]. Returns (dispatch [T, E, C] one-hot, combine [T, E, C]
    weights, aux_loss scalar). ``renormalize`` rescales the k selected
    gate weights to sum to 1 per token (Mixtral's convention).
    """
    t, e = logits.shape
    probs, gate_vals, gate_idx, aux_loss = _gates(logits, k, renormalize)

    dispatch = jnp.zeros((t, e, capacity), dtype=logits.dtype)
    combine = jnp.zeros((t, e, capacity), dtype=logits.dtype)
    # position of each token within its expert's buffer, per top-k choice
    taken = jnp.zeros((e,), dtype=jnp.int32)
    for choice in range(k):
        idx = gate_idx[:, choice]  # [T]
        one_hot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, E]
        pos_within = jnp.cumsum(one_hot, axis=0) - 1 + taken[None, :]
        taken = taken + jnp.sum(one_hot, axis=0)
        pos = jnp.sum(pos_within * one_hot, axis=1)  # [T]
        keep = pos < capacity
        w = gate_vals[:, choice] * keep
        dispatch = dispatch + (
            jax.nn.one_hot(idx, e, dtype=logits.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                             dtype=logits.dtype)[:, None, :]
            * keep[:, None, None]
        )
        combine = combine + (
            jax.nn.one_hot(idx, e, dtype=logits.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                             dtype=logits.dtype)[:, None, :]
            * w[:, None, None]
        )
    return dispatch, combine, aux_loss


def _dropless_moe(params: dict, tokens: jnp.ndarray, logits: jnp.ndarray,
                  cfg: MoEConfig):
    """Exact dense evaluation: every expert runs on every token; outputs
    combine by (optionally renormalized) top-k gate weight. No capacity,
    no dropping — the checkpoint-parity/eval path (compute O(E) of the
    routed path, memory O(E*T*ff))."""
    t, e = logits.shape
    probs, gate_vals, gate_idx, aux = _gates(logits, cfg.top_k,
                                             cfg.renormalize_top_k)
    # [T, E] combine weights: selected experts carry their gate weight
    weights = jnp.sum(
        jax.nn.one_hot(gate_idx, e, dtype=gate_vals.dtype)
        * gate_vals[..., None], axis=1)
    expert_out = _expert_ffn(params, tokens, cfg,
                             "td,edf->etf", "etf,efd->etd")
    out = jnp.einsum("etd,te->td", expert_out, weights)
    return out, aux


def moe_layer(params: dict, x: jnp.ndarray, cfg: MoEConfig):
    """x: [B, L, D] -> ([B, L, D], aux_loss).

    Token exchange happens in the two einsums against dispatch/combine;
    with wi/wo sharded on the expert axis XLA emits all-to-all.
    """
    b, l, d = x.shape
    tokens = x.reshape(b * l, d)
    logits = tokens @ params["router"]
    if cfg.dropless:
        out, aux = _dropless_moe(params, tokens, logits, cfg)
        return out.reshape(b, l, d), aux
    capacity = max(1, int(cfg.capacity_factor * (b * l) / cfg.num_experts))
    dispatch, combine, aux = top_k_gating(logits, cfg.top_k, capacity,
                                          renormalize=cfg.renormalize_top_k)
    # [E, C, D]: gather each expert's tokens (all-to-all under pjit)
    expert_in = jnp.einsum("td,tec->ecd", tokens, dispatch)
    expert_out = _expert_ffn(params, expert_in, cfg,
                             "ecd,edf->ecf", "ecf,efd->ecd")
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out.reshape(b, l, d), aux
