"""Direct unit tests for ``coordinator/liveness.LivenessMonitor``.

It always had indirect coverage (the gateway watchdog, the session
supervision paths) but no dedicated file; now it is ALSO the lease
authority for remote replica agents (gateway/remote.py) — expiry
timing, re-register-after-expiry and the unregister-vs-expiry race
are exactly the behaviors the remote failover story leans on.
"""

import threading
import time

from tony_tpu.config import ConfError, TonyConf
from tony_tpu.coordinator.liveness import (LivenessMonitor,
                                           heartbeat_rpc_timeout_s,
                                           liveness_expiry_s)


def _monitor(interval_ms=20, max_missed=3, expired=None):
    expired = expired if expired is not None else []
    mon = LivenessMonitor(interval_ms=interval_ms, max_missed=max_missed,
                          on_expired=expired.append)
    return mon, expired


class TestExpiry:
    def test_expiry_horizon_formula(self):
        import pytest

        # expiry = interval * max(3, max_missed): the floor keeps a
        # 1-miss config from flapping on scheduler jitter
        mon, _ = _monitor(interval_ms=100, max_missed=7)
        assert mon.expiry_s == pytest.approx(0.7)
        mon, _ = _monitor(interval_ms=100, max_missed=1)
        assert mon.expiry_s == pytest.approx(0.3)

    def test_silent_task_expires_once(self):
        mon, expired = _monitor()
        mon.register("a")
        mon.start()
        try:
            deadline = time.monotonic() + 5
            while not expired and time.monotonic() < deadline:
                time.sleep(0.01)
            assert expired == ["a"]
            # the entry is REMOVED on expiry: no repeat firing for the
            # same outage (the remote lease leans on one-shot expiry)
            time.sleep(mon.expiry_s * 3)
            assert expired == ["a"]
        finally:
            mon.stop()

    def test_pinged_task_survives(self):
        mon, expired = _monitor()
        mon.register("a")
        mon.start()
        try:
            until = time.monotonic() + mon.expiry_s * 4
            while time.monotonic() < until:
                mon.ping("a")
                time.sleep(0.005)
            assert expired == []
        finally:
            mon.stop()

    def test_expiry_timing_not_early(self):
        # a task must NOT expire before the horizon: ping once at
        # t=0, it should still be watched at expiry_s/2
        mon, expired = _monitor(interval_ms=50, max_missed=4)  # 0.2s
        mon.register("a")
        mon.start()
        try:
            time.sleep(mon.expiry_s / 2)
            assert expired == []
        finally:
            mon.stop()

    def test_ping_after_expiry_is_inert(self):
        # ping() only refreshes REGISTERED tasks: after an expiry
        # removed the entry, pings are no-ops (the caller must
        # re-register — pinned next)
        mon, expired = _monitor()
        mon.register("a")
        mon.start()
        try:
            deadline = time.monotonic() + 5
            while not expired and time.monotonic() < deadline:
                time.sleep(0.01)
            mon.ping("a")
            time.sleep(mon.expiry_s * 2)
            assert expired == ["a"]  # the ping resurrected nothing
        finally:
            mon.stop()

    def test_reregister_after_expiry_watches_again(self):
        # the remote-lease recovery story: the heartbeat loop calls
        # register() on every success, so a host that comes back is
        # watched (and can expire) again
        mon, expired = _monitor()
        mon.register("a")
        mon.start()
        try:
            deadline = time.monotonic() + 5
            while not expired and time.monotonic() < deadline:
                time.sleep(0.01)
            assert expired == ["a"]
            mon.register("a")  # the agent is back
            deadline = time.monotonic() + 5
            while len(expired) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert expired == ["a", "a"]  # dies again, fires again
        finally:
            mon.stop()


class TestUnregisterRace:
    def test_unregister_stops_watching(self):
        mon, expired = _monitor()
        mon.register("a")
        mon.start()
        try:
            mon.unregister("a")
            time.sleep(mon.expiry_s * 3)
            assert expired == []
        finally:
            mon.stop()

    def test_unregister_vs_expiry_race_never_doubles(self):
        # hammer register/unregister against a fast-expiring monitor:
        # however the race lands, a task unregistered and never
        # re-registered must not fire afterwards, and concurrent
        # mutation must never crash the monitor thread
        mon, expired = _monitor(interval_ms=5, max_missed=3)
        mon.start()
        stop = threading.Event()

        def churn():
            while not stop.is_set():
                mon.register("r")
                mon.unregister("r")

        threads = [threading.Thread(target=churn) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join()
        mon.unregister("r")
        fired_before = len(expired)
        time.sleep(mon.expiry_s * 4)
        mon.stop()
        # no firing after the final unregister (races during the churn
        # may legitimately have fired when a register stood >expiry)
        assert len(expired) == fired_before
        # the monitor thread survived the churn (stop() joined it)
        assert not mon._thread.is_alive()

    def test_clear_drops_everything(self):
        mon, expired = _monitor()
        mon.register("a")
        mon.register("b")
        mon.clear()
        mon.start()
        try:
            time.sleep(mon.expiry_s * 3)
            assert expired == []
        finally:
            mon.stop()

    def test_on_expired_exception_does_not_kill_monitor(self):
        fired = []

        def boom(task_id):
            fired.append(task_id)
            raise RuntimeError("handler bug")

        mon = LivenessMonitor(interval_ms=10, max_missed=3,
                              on_expired=boom)
        mon.register("a")
        mon.register("b")
        mon.start()
        try:
            deadline = time.monotonic() + 5
            while len(fired) < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sorted(fired) == ["a", "b"]  # the first handler
            # exception didn't stop the second expiry
        finally:
            mon.stop()


class TestConfFormulas:
    def test_liveness_expiry_from_conf(self):
        conf = TonyConf(load_defaults=False)
        conf.set("tony.task.heartbeat-interval-ms", "500")
        conf.set("tony.task.max-missed-heartbeats", "10")
        assert liveness_expiry_s(conf) == 5.0

    def test_expiry_floor_of_three_misses(self):
        conf = TonyConf(load_defaults=False)
        conf.set("tony.task.heartbeat-interval-ms", "1000")
        conf.set("tony.task.max-missed-heartbeats", "1")
        assert liveness_expiry_s(conf) == 3.0

    def test_heartbeat_rpc_timeout_coercion(self):
        # string conf values coerce through get_int; the timeout is
        # 2x the interval with a 2 s floor
        conf = TonyConf(load_defaults=False)
        conf.set("tony.task.heartbeat-interval-ms", "4000")
        assert heartbeat_rpc_timeout_s(conf) == 8.0
        conf.set("tony.task.heartbeat-interval-ms", "100")
        assert heartbeat_rpc_timeout_s(conf) == 2.0  # the floor

    def test_bad_numeric_conf_raises_typed_error_naming_key(self):
        import pytest

        conf = TonyConf(load_defaults=False)
        with pytest.raises(ConfError, match="heartbeat-interval-ms"):
            conf.set("tony.task.heartbeat-interval-ms", "fast")
