"""Single source of truth for "are pallas kernels compiled here?".

Pallas kernels lower through Mosaic on real TPU backends; everywhere else
they must run in interpret mode. The tunneled single-chip backend reports
platform "axon", not "tpu" — it is the same Mosaic lowering path, so it
counts as compiled TPU. Keeping the check in one place stops the failure
mode ADVICE r3 flagged: ops/quant.py treated axon as non-TPU and silently
ran the interpreter on the real chip, forfeiting the int8 bandwidth win
while the bench artifact carried TPU provenance.
"""

from __future__ import annotations

import jax

_TPU_PLATFORMS = ("tpu", "axon")


def on_tpu() -> bool:
    """True when the default backend compiles pallas via Mosaic."""
    try:
        return jax.devices()[0].platform in _TPU_PLATFORMS
    except Exception:
        return False


def interpret_mode() -> bool:
    """Value for ``pallas_call(interpret=...)`` on this backend."""
    return not on_tpu()
