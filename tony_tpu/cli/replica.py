"""``python -m tony_tpu.cli.replica`` — one replica agent on this host.

The remote TaskExecutor of the serving story: boots ONE
``serve.Server`` (same engine knobs as the gateway CLI) behind the
agent HTTP shim (``serve/agent.py``) and waits. The gateway launches
this on provisioned hosts (``--remote-replica`` / the provisioner
backend) or attaches to already-running ones (``--agents``), then
drives it over POST /v1/submit + resumable GET /v1/stream.

    python -m tony_tpu.cli.replica --demo-model --port 8101

SIGTERM/SIGINT deregisters by DRAINING: new submits 503, every
in-flight and pending request finishes, then exit 0 — the gateway's
lease sees ``draining`` on /healthz instead of a vanished host. A
second signal force-exits.

``--port-file`` writes "host port" once the socket is bound — how a
launcher (gateway ``--remote-replica``, tools/serve_smoke.sh) learns
an ephemeral port without parsing stdout.

``--replica-index`` addresses ``TONY_SERVE_FAULTS`` engine faults at
this agent (chaos rounds arm replica N's ENGINE here while the
gateway arms replica N's TRANSPORT at its stub — one env var, both
failure planes).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu replica",
        description="one serving replica agent (engine + HTTP shim)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model", help="local checkpoint directory (HF format)")
    src.add_argument("--demo-model", action="store_true",
                     help="serve a tiny random decoder (no checkpoint) "
                          "— for smoke tests")
    p.add_argument("--serve-batch", type=int, default=4,
                   help="cache slots")
    p.add_argument("--chunk-steps", type=int, default=1)
    p.add_argument("--prefill-chunk-tokens", type=int, default=0)
    p.add_argument("--prefix-cache-mb", type=float, default=64.0)
    p.add_argument("--kv-host-mb", type=float, default=0.0)
    p.add_argument("--speculate-k", type=int, default=0)
    p.add_argument("--kv-page-size", type=int, default=0)
    p.add_argument("--kv-pages", type=int, default=0)
    p.add_argument("--no-paged-kv", action="store_true")
    p.add_argument("--mesh", default="",
                   help="sharded replica: devices for THIS agent's "
                        "engine (count or 'tensor=N,expert=M'; see "
                        "cli.gateway --mesh)")
    p.add_argument("--shard-rules", default="serve")
    p.add_argument("--no-in-dispatch-eos", action="store_true")
    p.add_argument("--max-pending", type=int, default=1024)
    p.add_argument("--eos-id", type=int, default=-1)
    p.add_argument("--dtype", choices=("fp32", "bf16"), default="fp32")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8101,
                   help="0 picks an ephemeral port (see --port-file)")
    p.add_argument("--port-file", default="",
                   help="write 'host port' here once bound — how a "
                        "launcher learns an ephemeral port")
    p.add_argument("--replica-index", type=int, default=0,
                   help="fleet index for TONY_SERVE_FAULTS engine-"
                        "fault addressing")
    p.add_argument("--host-share", type=int, default=1,
                   help="how many agents share THIS host's HBM "
                        "(auto-sized KV page pools divide by it; a "
                        "gateway launching N localhost agents passes "
                        "its fleet ceiling so the pools cannot "
                        "oversubscribe the device). 1 = alone on the "
                        "host (the provisioned-slice default)")
    p.add_argument("--agent-id", default="",
                   help="stable id reported on /healthz (default: "
                        "a generated one)")
    p.add_argument("--profile-dir", default="",
                   help="where POST /v1/profile (the gateway's "
                        "/debug/profile fan-out) drops THIS host's "
                        "xplane captures (default: "
                        "$TONY_PROFILE_DIR or ./profiles)")
    p.add_argument("--drain-timeout", type=float, default=120.0,
                   help="max seconds to finish in-flight work on "
                        "SIGTERM")
    p.add_argument("--park-ttl", type=float, default=60.0,
                   help="seconds a parked session (orphaned snapshot "
                        "or finished-but-undelivered result) stays "
                        "adoptable before it is reaped")
    p.add_argument("--gateway-grace", type=float, default=0.0,
                   help="seconds of gateway silence before in-flight "
                        "slots freeze into parked snapshots (0 "
                        "disables the watchdog; in-flight work runs "
                        "to completion and parks as results)")
    p.add_argument("--compile-cache",
                   default=os.path.join(os.path.expanduser("~"), ".cache",
                                        "tony_tpu", "compile-cache"),
                   help="persistent XLA compile-cache dir ('' disables)")
    return p


def build_server(args):
    """The engine, configured exactly like a gateway boot replica
    (cli/gateway.server_factory) — remote must not mean different."""
    from tony_tpu.cli.gateway import demo_model, server_factory

    if args.demo_model:
        model, params = demo_model()
        eos = [args.eos_id] if args.eos_id >= 0 else []
    else:
        from tony_tpu.cli.generate import load_model
        from tony_tpu.models.generate import normalize_eos_ids

        model, wrapped, config = load_model(args.model)
        params = wrapped["params"]
        if args.dtype == "bf16":
            import jax
            import jax.numpy as jnp

            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        eos = normalize_eos_ids(args.eos_id) or \
            normalize_eos_ids(getattr(config, "eos_token_id", None))
    # this process IS one replica, but auto-sized KV pools must still
    # divide the host's HBM by every agent sharing it — the factory's
    # fleet-ceiling sizing keyed off args.replicas does exactly that
    args.replicas = max(1, args.host_share)
    return server_factory(args, model, params, eos)(args.replica_index)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.compile_cache:
        from tony_tpu.utils import compilecache

        compilecache.enable(args.compile_cache)

    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    server = build_server(args)
    if server.fault_plan is not None:
        logging.getLogger(__name__).warning(
            "engine fault injection ARMED on this agent (replica %d) "
            "via TONY_SERVE_FAULTS", args.replica_index)
    agent = ReplicaAgent(server, agent_id=args.agent_id or None,
                         profile_dir=args.profile_dir or None,
                         park_ttl_s=args.park_ttl,
                         gateway_grace_s=args.gateway_grace)
    http = AgentHTTP(agent, host=args.host, port=args.port).start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{http.host} {http.port}\n")
        os.replace(tmp, args.port_file)  # atomic: launchers poll it
    print(f"tony-tpu replica agent {agent.agent_id} at "
          f"http://{http.host}:{http.port}", flush=True)

    signals_seen = []

    def _on_signal(signum, frame):
        # count SIGNALS, not agent.draining: a gateway-initiated
        # /v1/drain followed by one polite SIGTERM (the scale-down /
        # close() sequence) must exit 0, not take the force path
        signals_seen.append(signum)
        if len(signals_seen) > 1:  # second signal: force exit
            os._exit(1)
        print(f"signal {signum}: draining agent (new submits 503, "
              f"finishing in-flight)...", file=sys.stderr, flush=True)
        # drain on a helper thread: the handler must return promptly
        # (idempotent — a drain already running just finishes)
        import threading

        threading.Thread(target=agent.drain,
                         args=(args.drain_timeout,),
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    agent.drained.wait()
    http.stop()
    print("agent drained clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
