"""End-to-end serving observability: traces, timeline, exposition.

The TonY lesson (PAPER.md L4/L6) applied to serving: orchestration is
worth little if you cannot see where a request's time went. Three
layers, each consumable on its own:

- ``trace``: per-request span trees (queue-wait -> admit -> decode
  rounds, one attempt span per engine run across failovers), exported
  as Chrome trace-event JSON for Perfetto (``/debug/trace/<id>``);
- ``timeline``: per-dispatch engine records (kind / occupancy / shape
  bucket / host-wall duration, compile split from steady state) — the
  ``/stats`` ``dispatches`` block and the sensor for dispatch-overhead
  work;
- ``prom`` + ``export``: dependency-free Prometheus text exposition of
  the gateway's counters, gauges, and latency histograms
  (``GET /metrics``).

The whole layer is always-on-cheap (appends under small locks, export
cost only when asked); bench ``extras.obs`` pins the overhead.
"""

from tony_tpu.obs.export import prometheus_text
from tony_tpu.obs.prom import (DEFAULT_TIME_BUCKETS_S, Histogram,
                               MetricFamily, escape_label_value, render)
from tony_tpu.obs.timeline import DispatchRecord, DispatchTimeline
from tony_tpu.obs.trace import (RequestTrace, Span, TraceBuffer,
                                check_invariants)

__all__ = [
    "DEFAULT_TIME_BUCKETS_S",
    "DispatchRecord",
    "DispatchTimeline",
    "Histogram",
    "MetricFamily",
    "RequestTrace",
    "Span",
    "TraceBuffer",
    "check_invariants",
    "escape_label_value",
    "prometheus_text",
    "render",
]
