"""Multi-replica serving front door: admission, deadlines, routing, drain.

The layer above ``tony_tpu.serve``: PR 1's ``Server`` multiplexes many
requests onto ONE resident KV cache; this module multiplexes many
CLIENTS onto N such servers (data-parallel replicas, one scheduler
thread each — the serving analog of TonY's coordinator packing a fleet
of role tasks onto a container pool). The pieces, front to back:

- ``Gateway.submit()`` is the ADMISSION gate: a bounded queue (past
  ``max_queue`` waiting requests it sheds with ``GatewayQueueFull`` ->
  HTTP 429) with a per-request deadline (``ttl_s``); requests whose
  deadline passes while they wait are shed with ``DeadlineExceeded``
  (-> 504) BEFORE they ever occupy a cache slot — a dead client's
  request must not spend decode steps nobody will read.
- Routing picks the replica with the LEAST OUTSTANDING TOKENS
  (queued + in-flight prompt+budget estimate — queue-length routing
  would park a burst of 512-token requests behind one another while a
  replica full of 8-token requests sits idle). A ``session`` key opts
  into affinity (hash -> replica), keeping a conversation's requests
  on one replica.
- Each ``_Replica`` owns a ``serve.Server`` and drives it on its own
  thread: admit from its queue (deadline-checked at the moment a slot
  is actually free), ``step()``, stream per-token deltas to tickets,
  deliver results. The engine's lock-protected ``submit()`` plus this
  single-owner step loop is the whole concurrency story — no lock is
  ever held across a device dispatch.
- ``drain()`` is the SIGTERM story: close the front door (new submits
  shed with ``GatewayClosed`` -> 503), let every replica finish its
  queue and in-flight slots, then join the threads — zero accepted
  requests lost.
- Every finished request records queue-wait / TTFT / TPOT / tokens
  in+out: into the rolling ``/stats`` window (p50/p99), into a
  ``metrics.MetricsStore`` under ``gateway:replica-<i>`` (the
  coordinator-side sink TaskMetricsMonitor pushes to), and optionally
  into a portal-browsable history job (``GatewayHistory``).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from tony_tpu.serve import QueueFull, Request, Server

log = logging.getLogger(__name__)


class Shed(Exception):
    """A request the gateway refused or gave up on; ``http_status`` is
    the status the front door maps it to."""

    http_status = 500

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class BadRequest(Shed):
    http_status = 400


class GatewayQueueFull(Shed):
    http_status = 429


class GatewayClosed(Shed):
    http_status = 503


class DeadlineExceeded(Shed):
    http_status = 504


@dataclass
class GenRequest:
    """One client request. ``ttl_s`` bounds its whole life (queue wait
    included): ``None`` = no deadline. ``session`` opts into replica
    affinity. Sampling knobs mirror ``serve.Request``."""

    prompt: list
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    id: Any = None
    ttl_s: float | None = None
    session: str | None = None


# ticket lifecycle states
QUEUED, RUNNING, DONE, SHED = "QUEUED", "RUNNING", "DONE", "SHED"


class Ticket:
    """The caller's handle on a submitted request: an event stream plus
    a blocking ``result()``.

    Events (also forwarded to ``on_event`` from the replica thread):
      ("tokens", [ids])          newly generated tokens (streaming)
      ("done", Result, metrics)  finished; metrics = the per-request
                                 observability record (queue_wait_ms,
                                 ttft_ms, tpot_ms, tokens_in/out, ...)
      ("shed", status, reason)   refused after admission (deadline hit
                                 in queue, replica failure)
    """

    def __init__(self, request: GenRequest, deadline: float | None,
                 on_event: Callable | None = None):
        self.request = request
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self.t_admit: float | None = None
        self.t_first: float | None = None
        self.replica: int | None = None
        self.state = QUEUED
        self.metrics: dict | None = None  # the done-event record
        self.events: queue.Queue = queue.Queue()
        self._on_event = on_event
        self._n_emitted = 0  # tokens already streamed out

    # estimate used by least-outstanding-tokens routing: the work a
    # replica signs up for when it accepts this ticket
    @property
    def cost(self) -> int:
        return len(self.request.prompt) + self.request.max_new_tokens

    def _emit(self, event: tuple) -> None:
        self.events.put(event)
        if self._on_event is not None:
            try:
                self._on_event(self, event)
            except Exception:
                log.exception("ticket on_event callback failed")

    def result(self, timeout: float | None = None):
        """Block until the request finishes; returns the
        ``serve.Result``. Raises the mapped ``Shed`` subclass if the
        gateway gave up on it. Token events are drained silently (use
        ``on_event`` or read ``events`` yourself to stream)."""
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            left = None if t_end is None else max(0.0, t_end - time.monotonic())
            try:
                kind, *rest = self.events.get(timeout=left)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.request.id!r} not finished after "
                    f"{timeout}s (state {self.state})") from None
            if kind == "done":
                return rest[0]
            if kind == "shed":
                status, reason = rest
                exc = {429: GatewayQueueFull, 503: GatewayClosed,
                       504: DeadlineExceeded}.get(status, Shed)(reason)
                exc.http_status = status
                raise exc


class _Replica:
    """One ``serve.Server`` + the thread that drives it."""

    def __init__(self, index: int, server: Server, gateway: "Gateway"):
        self.index = index
        self.server = server
        self.gateway = gateway
        self.queue: deque[Ticket] = deque()
        self.cv = threading.Condition()
        self.outstanding = 0  # token-cost estimate: queued + in-flight
        self.completed = 0
        self.shed = 0
        self._stop = False
        self._tickets: dict[int, Ticket] = {}  # engine id -> ticket
        self._next_id = 0
        self._thread = threading.Thread(target=self._loop,
                                        name=f"gateway-replica-{index}",
                                        daemon=True)

    # ---------------------------------------------------------- intake

    def enqueue(self, ticket: Ticket) -> None:
        with self.cv:
            if self._stop:
                # closes the submit-vs-drain race: a ticket landing
                # after the stop signal could otherwise strand forever
                # on a thread that already exited
                raise GatewayClosed("gateway is draining")
            ticket.replica = self.index
            self.queue.append(ticket)
            self.outstanding += ticket.cost
            self.cv.notify()

    @property
    def n_queued(self) -> int:
        return len(self.queue)

    @property
    def busy(self) -> bool:
        return bool(self.server.slots.n_active or self.server.n_pending
                    or self.queue)

    # ------------------------------------------------------------ loop

    def start(self) -> None:
        self._thread.start()

    def signal_stop(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify()

    def join(self, timeout: float | None = None) -> None:
        if self._thread.ident is not None:  # join pre-start is an error
            self._thread.join(timeout)

    def _loop(self) -> None:
        while True:
            with self.cv:
                while not self.queue and not self._server_busy() \
                        and not self._stop:
                    self.cv.wait()
                if self._stop and not self.queue \
                        and not self._server_busy():
                    return
            try:
                self._admit_from_queue()
                if self._server_busy():
                    finished = self.server.step()
                    now = time.monotonic()
                    self._stream_deltas(now)
                    self._deliver(finished, now)
            except Exception as e:  # a wedged replica must not strand
                # its tickets with no terminal event: shed everything
                # this replica holds, then keep consuming (each later
                # ticket sheds fast rather than hanging its client)
                log.exception("replica %d step failed", self.index)
                self._abort(f"replica {self.index} failure: "
                            f"{type(e).__name__}: {e}")

    def _server_busy(self) -> bool:
        return bool(self.server.slots.n_active or self.server.n_pending)

    def _admit_from_queue(self) -> None:
        """Move tickets into the engine, AT MOST as many as there are
        free slots — the deadline check runs at the moment a slot is
        genuinely available, so an expired request is shed having never
        occupied one (and never cost a prefill dispatch)."""
        free = len(self.server.slots.free_slots()) - self.server.n_pending
        while free > 0:
            with self.cv:
                if not self.queue:
                    return
                ticket = self.queue.popleft()
            now = time.monotonic()
            if ticket.deadline is not None and now >= ticket.deadline:
                self._shed(ticket, 504,
                           f"deadline exceeded after "
                           f"{now - ticket.t_submit:.3f}s in queue")
                continue
            req = ticket.request
            engine_id = self._next_id
            self._next_id += 1
            try:
                self.server.submit(Request(
                    list(req.prompt), req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    seed=req.seed, id=engine_id))
            except QueueFull:
                # engine bound hit (shouldn't happen: we feed at most
                # free-slot many) — put it back and stop admitting
                with self.cv:
                    self.queue.appendleft(ticket)
                return
            except ValueError as e:
                self._shed(ticket, 400, str(e))
                continue
            ticket.t_admit = now
            ticket.state = RUNNING
            self._tickets[engine_id] = ticket
            free -= 1

    def _stream_deltas(self, now: float) -> None:
        emitted = {eid: t._n_emitted for eid, t in self._tickets.items()}
        for engine_id, new in self.server.live_progress(emitted).items():
            ticket = self._tickets.get(engine_id)
            if ticket is None or not new:
                continue
            if ticket.t_first is None:
                ticket.t_first = now
            ticket._n_emitted += len(new)
            ticket._emit(("tokens", new))

    def _deliver(self, finished, now: float) -> None:
        for res in finished:
            ticket = self._tickets.pop(res.id, None)
            if ticket is None:
                continue
            if ticket.t_first is None:
                ticket.t_first = now
            tail = res.tokens[ticket._n_emitted:]
            if tail:
                ticket._emit(("tokens", tail))
            ticket.state = DONE
            self.completed += 1
            with self.cv:
                self.outstanding -= ticket.cost
            metrics = self._request_metrics(ticket, res, now)
            ticket.metrics = metrics  # unary responders read it after
            # result(); same record the stream's final line carries
            res = type(res)(ticket.request.id, res.prompt, res.tokens,
                            res.finish_reason, res.prefix_hit_tokens,
                            res.prefill_tokens_saved,
                            res.drafted, res.accepted)
            self.gateway._record_done(self, metrics)
            ticket._emit(("done", res, metrics))

    def _request_metrics(self, ticket: Ticket, res, now: float) -> dict:
        n_out = len(res.tokens)
        ttft = (ticket.t_first - ticket.t_submit) if ticket.t_first else 0.0
        tpot = ((now - ticket.t_first) / (n_out - 1)
                if n_out > 1 and ticket.t_first else 0.0)
        return {
            "id": ticket.request.id,
            "replica": self.index,
            "queue_wait_ms": round(
                (ticket.t_admit - ticket.t_submit) * 1e3, 3),
            "ttft_ms": round(ttft * 1e3, 3),
            "tpot_ms": round(tpot * 1e3, 3),
            "e2e_ms": round((now - ticket.t_submit) * 1e3, 3),
            "tokens_in": len(res.prompt),
            "tokens_out": n_out,
            "prefix_hit_tokens": res.prefix_hit_tokens,
            "prefill_tokens_saved": res.prefill_tokens_saved,
            "drafted": res.drafted,
            "accepted": res.accepted,
            "draft_hit_rate": round(res.draft_hit_rate, 4),
            "finish_reason": res.finish_reason,
        }

    def _shed(self, ticket: Ticket, status: int, reason: str) -> None:
        ticket.state = SHED
        self.shed += 1
        with self.cv:
            self.outstanding -= ticket.cost
        self.gateway._record_shed(self, status)
        ticket._emit(("shed", status, reason))

    def _abort(self, reason: str) -> None:
        """Terminal-event every ticket this replica holds (engine-
        admitted AND queued) after an unrecoverable step failure."""
        for ticket in list(self._tickets.values()):
            self._shed(ticket, 500, reason)
        self._tickets.clear()
        self.server.reset()  # pending + _live + slots together: slots
        # alone would leave engine ghosts decoding phantom results
        while True:
            with self.cv:
                if not self.queue:
                    return
                ticket = self.queue.popleft()
            self._shed(ticket, 500, reason)

    def stats(self) -> dict:
        out = {
            "queued": self.n_queued,
            "active_slots": self.server.slots.n_active,
            "batch_size": self.server.slots.batch_size,
            "outstanding_tokens": self.outstanding,
            "completed": self.completed,
            "shed": self.shed,
        }
        # engine counters (prefills, decode_steps, dispatches, the
        # prefix_* family) flat, so the MetricsStore numeric filter and
        # /stats both carry them per replica
        out.update(self.server.counters())
        return out


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


class _Stats:
    """Rolling per-request window + monotonic counters behind /stats."""

    def __init__(self, window: int = 1024):
        self.lock = threading.Lock()
        self.window: deque[dict] = deque(maxlen=window)
        self.accepted = 0
        self.completed = 0
        self.shed_by_status: dict[int, int] = {}
        self.tokens_in = 0
        self.tokens_out = 0
        self.prefix_hit_tokens = 0
        self.prefill_tokens_saved = 0
        self.drafted = 0
        self.draft_accepted = 0

    def snapshot(self) -> dict:
        with self.lock:
            recent = list(self.window)
            out = {
                "accepted": self.accepted,
                "completed": self.completed,
                "shed": dict(self.shed_by_status),
                "tokens_in": self.tokens_in,
                "tokens_out": self.tokens_out,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prefill_tokens_saved": self.prefill_tokens_saved,
                "drafted": self.drafted,
                "draft_accepted": self.draft_accepted,
            }
        for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
            vals = sorted(r[key] for r in recent)
            out[key] = {"p50": _percentile(vals, 0.50),
                        "p95": _percentile(vals, 0.95),
                        "p99": _percentile(vals, 0.99)}
        out["window"] = len(recent)
        return out


class GatewayHistory:
    """Portal hookup: the gateway as a browsable history job.

    Writes the coordinator's on-disk layout (``events/history.py``)
    under ``<history>/intermediate/<app_id>/``: an in-progress
    ``.jhist.jsonl`` event log (inited/finished) plus per-request
    metric rows in ``metrics/requests.jsonl`` — the portal's existing
    /job/<id>/metrics page renders them with zero portal changes, and
    the history mover/purger manage the directory like any other job's.
    """

    def __init__(self, history_root: str, app_id: str = "",
                 n_replicas: int = 1):
        from tony_tpu.events import history
        from tony_tpu.events.event import application_inited

        self._lock = threading.Lock()
        started = int(time.time() * 1000)
        self.app_id = app_id or f"application_gateway_{started}"
        self.started = started
        self.job_dir = history.intermediate_dir(history_root, self.app_id)
        os.makedirs(os.path.join(self.job_dir, "metrics"), exist_ok=True)
        self.jhist = os.path.join(
            self.job_dir, history.inprogress_name(self.app_id, started))
        self._append_event(application_inited(
            self.app_id, n_replicas, os.uname().nodename))
        self._metrics_path = os.path.join(self.job_dir, "metrics",
                                          "requests.jsonl")

    def _append_event(self, event) -> None:
        with self._lock, open(self.jhist, "a") as f:
            f.write(json.dumps(event.to_dict()) + "\n")

    def record(self, row: dict) -> None:
        with self._lock, open(self._metrics_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def close(self, status: str = "SUCCEEDED",
              metrics: dict | None = None) -> None:
        from tony_tpu.events import history
        from tony_tpu.events.event import application_finished

        self._append_event(application_finished(
            self.app_id, status, 0, metrics or {}))
        completed = int(time.time() * 1000)
        final = os.path.join(self.job_dir, history.finished_name(
            self.app_id, self.started, completed,
            os.environ.get("USER", "unknown"), status))
        with self._lock:
            os.replace(self.jhist, final)


class Gateway:
    """The front door over N replica servers. See the module docstring
    for the full story; the API surface:

    - ``submit(req, on_event=None) -> Ticket`` (raises ``Shed``)
    - ``drain()`` then ``stop()`` — or just ``stop()`` (drains)
    - ``snapshot()`` — the /stats payload
    - ``ready`` / ``draining`` — the /readyz signal
    """

    def __init__(self, servers: list[Server], *, max_queue: int = 128,
                 default_ttl_s: float | None = None,
                 metrics_store=None, history: GatewayHistory | None = None):
        if not servers:
            raise ValueError("gateway needs at least one replica server")
        self.replicas = [_Replica(i, s, self) for i, s in enumerate(servers)]
        self.max_queue = max(1, max_queue)
        self.default_ttl_s = default_ttl_s
        self.metrics_store = metrics_store
        self.history = history
        self.stats = _Stats()
        self._lock = threading.Lock()
        self._drain_lock = threading.Lock()
        self._drain_done: bool | None = None
        self._ids = iter(range(1 << 62))
        self._started = False
        self._closed = False

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Gateway":
        for r in self.replicas:
            r.start()
        self._started = True
        return self

    @property
    def ready(self) -> bool:
        return self._started and not self._closed

    @property
    def draining(self) -> bool:
        return self._closed

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admitting (submit -> 503), let every
        replica finish its queue and in-flight slots, join the threads.
        Returns True when everything drained inside ``timeout``.
        Idempotent — a second call (stop() after drain()) returns the
        first outcome instead of re-finalizing the history job."""
        with self._drain_lock:
            if self._drain_done is not None:
                return self._drain_done
            self._closed = True
            for r in self.replicas:
                r.signal_stop()
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            ok = True
            for r in self.replicas:
                left = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                r.join(left)
                ok = ok and not r._thread.is_alive()
            if self.history is not None:
                self.history.close("SUCCEEDED" if ok else "KILLED",
                                   self.stats.snapshot())
            self._drain_done = ok
            return ok

    def stop(self, timeout: float | None = None) -> bool:
        return self.drain(timeout)

    # --------------------------------------------------------- admission

    def submit(self, request: GenRequest,
               on_event: Callable | None = None) -> Ticket:
        """Admission gate + router. Raises ``GatewayClosed`` (503) when
        draining, ``BadRequest`` (400) on invalid shapes,
        ``GatewayQueueFull`` (429) past ``max_queue`` waiting requests,
        ``DeadlineExceeded`` (504) for an already-dead ttl."""
        if self._closed:
            self.stats_shed(503)
            raise GatewayClosed("gateway is draining")
        prompt = list(request.prompt)
        max_len = self.replicas[0].server.model.cfg.max_seq_len
        if not prompt:
            self.stats_shed(400)
            raise BadRequest("empty prompt")
        if len(prompt) >= max_len:
            self.stats_shed(400)
            raise BadRequest(f"prompt ({len(prompt)}) leaves no room for "
                             f"generation in max_seq_len ({max_len})")
        if request.max_new_tokens < 1:
            self.stats_shed(400)
            raise BadRequest("max_new_tokens must be >= 1")
        ttl = request.ttl_s if request.ttl_s is not None \
            else self.default_ttl_s
        if ttl is not None and ttl <= 0:
            self.stats_shed(504)
            raise DeadlineExceeded("ttl_s already expired at submit")
        if request.id is None:
            request.id = next(self._ids)
        with self._lock:
            if sum(r.n_queued for r in self.replicas) >= self.max_queue:
                self.stats_shed(429)
                raise GatewayQueueFull(
                    f"admission queue at max_queue={self.max_queue}")
            replica = self._route(request)
            ticket = Ticket(request,
                            None if ttl is None
                            else time.monotonic() + ttl, on_event)
            try:
                # enqueue INSIDE the gateway lock: the bound check and
                # the depth increment must be atomic or two concurrent
                # submits both pass at max_queue - 1 and overshoot.
                # Lock order gateway._lock -> replica.cv is safe: no
                # replica-thread path takes the gateway lock.
                replica.enqueue(ticket)
            except GatewayClosed:  # the drain race
                self.stats_shed(503)
                raise
        with self.stats.lock:
            self.stats.accepted += 1
        return ticket

    def _route(self, request: GenRequest) -> _Replica:
        """Session affinity when asked; least outstanding tokens
        otherwise (ties -> lowest index, deterministic)."""
        if request.session is not None:
            key = zlib.crc32(str(request.session).encode())
            return self.replicas[key % len(self.replicas)]
        return min(self.replicas, key=lambda r: (r.outstanding, r.index))

    # -------------------------------------------------------- accounting

    def stats_shed(self, status: int) -> None:
        with self.stats.lock:
            self.stats.shed_by_status[status] = \
                self.stats.shed_by_status.get(status, 0) + 1

    def _record_shed(self, replica: _Replica, status: int) -> None:
        self.stats_shed(status)
        self._push_replica_metrics(replica)

    def _record_done(self, replica: _Replica, metrics: dict) -> None:
        with self.stats.lock:
            self.stats.completed += 1
            self.stats.tokens_in += metrics["tokens_in"]
            self.stats.tokens_out += metrics["tokens_out"]
            self.stats.prefix_hit_tokens += \
                metrics.get("prefix_hit_tokens", 0)
            self.stats.prefill_tokens_saved += \
                metrics.get("prefill_tokens_saved", 0)
            self.stats.drafted += metrics.get("drafted", 0)
            self.stats.draft_accepted += metrics.get("accepted", 0)
            self.stats.window.append(metrics)
        if self.history is not None:
            try:
                self.history.record(metrics)
            except OSError:
                log.exception("history metrics write failed")
        self._push_replica_metrics(replica)

    def _push_replica_metrics(self, replica: _Replica) -> None:
        if self.metrics_store is None:
            return
        try:
            self.metrics_store.update_metrics(
                f"gateway:replica-{replica.index}",
                {k: v for k, v in replica.stats().items()
                 if isinstance(v, (int, float))})
        except Exception:
            log.exception("metrics store push failed")

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        out["ready"] = self.ready
        out["draining"] = self.draining
        out["replicas"] = [r.stats() for r in self.replicas]
        out["queued"] = sum(r.n_queued for r in self.replicas)
        out["max_queue"] = self.max_queue
        out["engine"] = self._engine_summary()
        return out

    def _engine_summary(self) -> dict:
        """Fleet-level engine counters: the device work behind the
        request percentiles (prefills run, decode rounds, occupancy,
        overshoot waste) plus the speculative-decoding and prefix-cache
        effectiveness blocks, summed across replicas — so /stats shows
        savings NEXT TO the work they avoided."""
        servers = [r.server for r in self.replicas]
        counts = [s.counters() for s in servers]
        total = lambda key: sum(c.get(key, 0) for c in counts)  # noqa: E731
        lookups = total("prefix_lookups")
        drafted = total("spec_drafted")
        return {
            "prefills": total("prefills"),
            "decode_steps": total("decode_steps"),
            "dispatches": total("dispatches"),
            "wasted_steps": total("wasted_steps"),
            "active_slots": sum(s.slots.n_active for s in servers),
            "slots": sum(s.slots.batch_size for s in servers),
            "spec": {
                "enabled": any(s.speculate_k > 0 for s in servers),
                "rounds": total("spec_rounds"),
                "drafted": drafted,
                "accepted": total("spec_accepted"),
                "acceptance_rate": round(
                    total("spec_accepted") / drafted, 4)
                if drafted else 0.0,
            },
            "prefix": {
                "enabled": any(s.prefix is not None for s in servers),
                "lookups": lookups,
                "hits": total("prefix_hits"),
                "hit_rate": round(total("prefix_hits") / lookups, 4)
                if lookups else 0.0,
                "hit_tokens": total("prefix_hit_tokens"),
                "prefill_tokens_saved": total("prefill_tokens_saved"),
                "entries": total("prefix_entries"),
                "bytes": total("prefix_bytes"),
                "budget_bytes": total("prefix_budget_bytes"),
                "evictions": total("prefix_evictions"),
            },
        }
