"""Remote replicas: agent protocol, the RemoteServer stub, transport
fault injection, and the remote chaos anchor.

The acceptance pins for ISSUE 11: 2 localhost agents under concurrent
load with one killed (network-SIGKILL) mid-stream and the other's
transport disconnected mid-stream -> zero 5xx, every client stream
byte-identical to a fault-free control, the survivor keeps serving, a
restarted agent rejoins through the probe path, and stale-epoch
responses from a revived/superseded host are discarded. Plus: a full
black-hole partition funnels through lease expiry into token-exact
failover, and a dead remote replica's slice is deprovisioned with
nothing leaked.

Agents here are in-process ``AgentHTTP`` servers speaking REAL HTTP
over localhost — ``kill()`` drops them off the network exactly like a
SIGKILLed process (open streams die mid-line, new connections are
refused) while the test stays fast. The subprocess flavor of the same
story runs in ``tools/serve_smoke.sh`` (``make remote-smoke``).
"""

import time

import pytest

from tony_tpu.serve.engine import Request, Server
from tony_tpu.serve.faults import Fault, FaultPlan

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


@pytest.fixture(scope="module")
def demo():
    from tony_tpu.cli.gateway import demo_model

    model, params = demo_model()
    return model, params


def make_server(demo, **kw):
    model, params = demo
    kw.setdefault("batch_size", 2)
    kw.setdefault("eos_id", -1)
    return Server(model, params, **kw)


def start_agent(demo, port=0, **server_kw):
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    return AgentHTTP(ReplicaAgent(make_server(demo, **server_kw)),
                     port=port).start()


def make_stub(address, **kw):
    from tony_tpu.gateway.remote import RemoteServer

    kw.setdefault("heartbeat_interval_s", 0.1)
    kw.setdefault("lease_misses", 3)
    kw.setdefault("read_timeout_s", 2.0)
    kw.setdefault("boot_timeout_s", 20.0)
    return RemoteServer(address, **kw)


def make_gateway(stubs, **kw):
    from tony_tpu.gateway.core import Gateway

    kw.setdefault("stall_timeout_s", 10.0)
    kw.setdefault("breaker_base_s", 0.05)
    kw.setdefault("breaker_max_s", 0.25)
    kw.setdefault("quarantine_after", 100)
    return Gateway(stubs, **kw).start()


def control_outputs(demo, requests):
    """The fault-free control: the same requests on a fresh local
    engine (deterministic decode -> the remote fleet must match it
    token for token, faults or not)."""
    server = make_server(demo)
    for r in requests:
        server.submit(Request(list(r.prompt), r.max_new_tokens,
                              temperature=r.temperature, top_k=r.top_k,
                              seed=r.seed, id=r.id))
    return {res.id: list(res.tokens) for res in server.run()}


def wait_for(cond, timeout=20.0, interval=0.01, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# --------------------------------------------------------------------
# transport fault plan units (no jax, no sockets)
# --------------------------------------------------------------------

class TestTransportFaults:
    def test_engine_op_rejects_call_trigger(self):
        with pytest.raises(ValueError, match="'call' trigger"):
            Fault("fail", call=1)

    def test_transport_op_rejects_dispatch_trigger(self):
        with pytest.raises(ValueError, match="'dispatch' trigger"):
            Fault("refuse", dispatch=1)

    def test_delay_needs_seconds(self):
        with pytest.raises(ValueError, match="seconds > 0"):
            Fault("delay", call=1)

    def test_refuse_fires_on_call_count_and_spends(self):
        plan = FaultPlan([Fault("refuse", call=2)])
        plan.on_call("a")  # call 1: below trigger
        with pytest.raises(ConnectionRefusedError):
            plan.on_call("b")
        plan.on_call("c")  # spent (times=1)
        assert plan.fired == 1

    def test_blackhole_times_forever(self):
        plan = FaultPlan([Fault("blackhole", call=1, times=-1)])
        for _ in range(3):
            with pytest.raises(TimeoutError):
                plan.on_call("x")
        assert plan.fired == 3

    def test_disconnect_fires_on_stream_not_call(self):
        plan = FaultPlan([Fault("disconnect", call=1, times=-1)])
        plan.on_call("connect")  # call ops don't include disconnect
        with pytest.raises(ConnectionResetError):
            plan.on_stream("read")

    def test_half_open_fires_on_stream(self):
        plan = FaultPlan([Fault("half_open", call=1)])
        plan.on_call("connect")
        with pytest.raises(TimeoutError):
            plan.on_stream("read")

    def test_request_triggered_transport_fault(self):
        plan = FaultPlan([Fault("refuse", request=7)])
        plan.on_call("a", request=3)
        with pytest.raises(ConnectionRefusedError):
            plan.on_call("b", request=7)

    def test_delay_proceeds(self):
        plan = FaultPlan([Fault("delay", call=1, seconds=0.01)])
        t0 = time.monotonic()
        plan.on_call("a")  # no raise
        assert time.monotonic() - t0 >= 0.01

    def test_env_partition_engine_vs_transport(self):
        env = {"TONY_SERVE_FAULTS":
               '[{"op": "fail", "dispatch": 3, "replica": 0},'
               ' {"op": "blackhole", "call": 1, "replica": 1,'
               '  "times": -1}]'}
        eng0 = FaultPlan.from_env(replica=0, env=env)
        assert [f.op for f in eng0.faults] == ["fail"]
        assert FaultPlan.from_env(replica=1, env=env) is None
        tr1 = FaultPlan.transport_from_env(replica=1, env=env)
        assert [f.op for f in tr1.faults] == ["blackhole"]
        assert FaultPlan.transport_from_env(replica=0, env=env) is None

    def test_env_invalid_transport_spec_raises(self):
        env = {"TONY_SERVE_FAULTS": '[{"op": "refuse", "dispatch": 1}]'}
        with pytest.raises(ValueError):
            FaultPlan.transport_from_env(replica=0, env=env)


# --------------------------------------------------------------------
# agent protocol (direct HTTP, no gateway)
# --------------------------------------------------------------------

class TestAgentProtocol:
    @pytest.fixture()
    def agent(self, demo):
        http = start_agent(demo)
        yield http
        http.stop()

    def transport(self, agent, **kw):
        from tony_tpu.gateway.remote import AgentTransport

        kw.setdefault("read_timeout_s", 5.0)
        return AgentTransport(agent.address, **kw)

    def test_healthz_shape(self, agent):
        t = self.transport(agent)
        doc = t.call("GET", "/healthz")
        assert doc["ok"] is True
        assert doc["epoch"] == 0
        assert doc["batch_size"] == 2
        assert doc["max_seq_len"] == 64
        assert "decode_steps" in doc["counters"]
        assert doc["stepper_age_s"] < 5.0

    def test_submit_stream_roundtrip_token_exact(self, agent, demo):
        t = self.transport(agent)
        resp = t.call("POST", "/v1/submit", {
            "id": 0, "prompt": [1, 2, 3], "max_new_tokens": 12,
            "epoch": 0})
        assert resp["ok"] and resp["id"] == 0
        tokens, result = [], None
        for doc in t.stream_lines("/v1/stream/0?offset=0&epoch=0"):
            if doc.get("keepalive"):
                continue
            if "token_ids" in doc:
                assert doc["offset"] == len(tokens)  # absolute offsets
                tokens.extend(doc["token_ids"])
            if doc.get("done"):
                result = doc["result"]
                break
        assert result is not None
        assert tokens == result["tokens"]
        ctrl = control_outputs(
            demo, [Request([1, 2, 3], 12, id=0)])
        assert tokens == ctrl[0]

    def test_stream_resume_by_offset(self, agent, demo):
        t = self.transport(agent)
        t.call("POST", "/v1/submit", {"id": 5, "prompt": [4, 5],
                                      "max_new_tokens": 16, "epoch": 0})
        # read a couple of windows, then "drop the connection"
        got = []
        stream = t.stream_lines("/v1/stream/5?offset=0&epoch=0")
        for doc in stream:
            if "token_ids" in doc:
                got.extend(doc["token_ids"])
                if len(got) >= 2:
                    break
        stream.close()
        # reconnect AT THE OFFSET HELD: the tail picks up exactly there
        for doc in t.stream_lines(
                f"/v1/stream/5?offset={len(got)}&epoch=0"):
            if "token_ids" in doc:
                assert doc["offset"] == len(got)
                got.extend(doc["token_ids"])
            if doc.get("done"):
                assert got == doc["result"]["tokens"]  # gap/dup-free
                break
        ctrl = control_outputs(demo, [Request([4, 5], 16, id=5)])
        assert got == ctrl[5]

    def test_long_chunked_sampled_stream_token_exact(self, demo):
        """Regression pin: the stepper must APPEND live_progress tails
        (they are deltas past the held count) — the old replace-if-
        longer merge delivered wrong tokens at wrong offsets for any
        generation spanning >2 chunks, masked by the constant-token
        greedy demo output. Sampled + chunk_steps=4 + 40 tokens makes
        the corruption visible, and the mid-stream lines (not just the
        terminal doc) must match the control."""
        agent = start_agent(demo, chunk_steps=4)
        try:
            from tony_tpu.gateway.remote import AgentTransport

            t = AgentTransport(agent.address)
            t.call("POST", "/v1/submit", {
                "id": 11, "prompt": [3, 1, 4], "max_new_tokens": 40,
                "temperature": 1.0, "top_k": 8, "seed": 123,
                "epoch": 0})
            streamed, result = [], None
            lines_before_done = 0
            for doc in t.stream_lines("/v1/stream/11?offset=0&epoch=0"):
                if "token_ids" in doc:
                    assert doc["offset"] == len(streamed)
                    streamed.extend(doc["token_ids"])
                    if result is None:
                        lines_before_done += 1
                if doc.get("done"):
                    result = doc["result"]
                    break
            ctrl = control_outputs(demo, [Request(
                [3, 1, 4], 40, temperature=1.0, top_k=8, seed=123,
                id=11)])
            assert streamed == result["tokens"] == ctrl[11]
            assert lines_before_done >= 2  # it actually STREAMED
        finally:
            agent.stop()

    def test_submit_idempotent_on_request_id(self, agent):
        # the stub's connect-retry may re-send a submit the agent
        # already processed: the second must be a no-op ack, not a
        # duplicate engine request burning a second slot
        t = self.transport(agent)
        doc = {"id": 8, "prompt": [2, 2], "max_new_tokens": 30,
               "epoch": 0}
        t.call("POST", "/v1/submit", doc)
        resp = t.call("POST", "/v1/submit", doc)
        assert resp["ok"] and resp.get("duplicate") is True
        srv = agent.agent.server
        assert srv.n_pending + srv.n_active <= 1
        assert len(agent.agent._tickets) == 1

    def test_finished_result_still_fetchable(self, agent):
        t = self.transport(agent)
        t.call("POST", "/v1/submit", {"id": 9, "prompt": [7],
                                      "max_new_tokens": 4, "epoch": 0})
        wait_for(lambda: agent.agent._tickets[9].result is not None,
                 msg="result")
        # a client reconnecting AFTER the finish still gets the
        # terminal line (the reconnect-grace window)
        docs = list(t.stream_lines("/v1/stream/9?offset=0&epoch=0"))
        assert any(d.get("done") for d in docs)

    def test_stale_epoch_refused_and_adopted(self, agent):
        from tony_tpu.gateway.remote import AgentHTTPError

        t = self.transport(agent)
        t.call("POST", "/v1/reset", {"epoch": 3})
        assert t.call("GET", "/healthz")["epoch"] == 3
        # older epoch -> 409, body names the agent's epoch
        with pytest.raises(AgentHTTPError) as ei:
            t.call("POST", "/v1/submit", {"id": 1, "prompt": [1],
                                          "max_new_tokens": 2,
                                          "epoch": 2})
        assert ei.value.status == 409
        assert ei.value.doc["epoch"] == 3
        # stream with an older epoch: 409 too
        with pytest.raises(AgentHTTPError) as ei:
            list(t.stream_lines("/v1/stream/1?offset=0&epoch=1"))
        assert ei.value.status == 409

    def test_reset_drops_tickets_and_engine_state(self, agent):
        t = self.transport(agent)
        t.call("POST", "/v1/submit", {"id": 2, "prompt": [1, 1],
                                      "max_new_tokens": 30, "epoch": 0})
        t.call("POST", "/v1/reset", {"epoch": 1})
        wait_for(lambda: agent.agent.server.done, msg="engine reset")
        assert agent.agent._tickets == {}
        from tony_tpu.gateway.remote import AgentHTTPError

        with pytest.raises(AgentHTTPError) as ei:
            list(t.stream_lines("/v1/stream/2?offset=0&epoch=1"))
        assert ei.value.status == 404  # ticket gone

    def test_submit_validation_maps_to_400(self, agent):
        from tony_tpu.gateway.remote import AgentHTTPError

        t = self.transport(agent)
        with pytest.raises(AgentHTTPError) as ei:
            t.call("POST", "/v1/submit", {"id": 3, "prompt": [],
                                          "max_new_tokens": 2,
                                          "epoch": 0})
        assert ei.value.status == 400
        assert ei.value.doc["kind"] == "ValueError"

    def test_obs_channel_cursor_semantics(self, agent, demo):
        """GET /v1/obs (ISSUE-15): records are cursor-incremental,
        the summary is lifetime, the goodput ledger rides along, and
        timestamps are the AGENT's monotonic clock (t_mono brackets
        them)."""
        t = self.transport(agent)
        t.call("POST", "/v1/submit", {"id": 20, "prompt": [1, 2],
                                      "max_new_tokens": 6, "epoch": 0})
        wait_for(lambda: agent.agent._tickets[20].result is not None,
                 msg="result")
        doc = t.call("GET", "/v1/obs?cursor=0")
        assert doc["cursor"] > 0
        kinds = {r["kind"] for r in doc["records"]}
        assert "prefill" in kinds and "decode" in kinds
        prefills = [r for r in doc["records"] if r["kind"] == "prefill"]
        assert prefills[0]["request_id"] == 20
        decodes = [r for r in doc["records"] if r["kind"] == "decode"]
        assert all(20 in r["tags"]["requests"] for r in decodes)
        # timestamps live in the agent's monotonic clock
        assert all(0 < r["t0"] <= doc["t_mono"] for r in doc["records"])
        assert doc["summary"]["prefill"]["count"] >= 1
        assert doc["goodput"] is not None
        assert sum(doc["goodput"]["buckets"].values()) <= 1.0 + 1e-6
        # incremental: re-reading at the cursor returns nothing new,
        # but the lifetime summary stays
        doc2 = t.call("GET", f"/v1/obs?cursor={doc['cursor']}")
        assert doc2["records"] == []
        assert doc2["cursor"] == doc["cursor"]
        assert doc2["summary"]["prefill"]["count"] \
            == doc["summary"]["prefill"]["count"]

    def test_drain_finishes_then_refuses(self, agent):
        from tony_tpu.gateway.remote import AgentHTTPError

        t = self.transport(agent)
        t.call("POST", "/v1/submit", {"id": 4, "prompt": [2],
                                      "max_new_tokens": 6, "epoch": 0})
        doc = t.call("POST", "/v1/drain", {"timeout_s": 60},
                     timeout=90.0)
        assert doc["drained"] is True
        with pytest.raises(AgentHTTPError) as ei:
            t.call("POST", "/v1/submit", {"id": 6, "prompt": [2],
                                          "max_new_tokens": 2,
                                          "epoch": 0})
        assert ei.value.status == 503
        assert agent.agent.drained.is_set()  # the CLI exit signal


# --------------------------------------------------------------------
# transport backoff + fault hooks at the stub
# --------------------------------------------------------------------

class TestAgentTransport:
    def test_backoff_capped_and_jittered(self):
        from tony_tpu.gateway.remote import AgentTransport

        t = AgentTransport("127.0.0.1:1", backoff_base_s=0.1,
                           backoff_max_s=0.4)
        for attempt, cap in ((0, 0.1), (1, 0.2), (2, 0.4), (9, 0.4)):
            vals = {t._backoff(attempt) for _ in range(16)}
            assert all(cap * 0.5 <= v <= cap for v in vals)
        # jitter actually varies
        assert len({t._backoff(3) for _ in range(16)}) > 1

    def test_connect_retries_heal_transient_refusal(self, demo):
        # a times=2 refusal is a transient blip: the in-lease retry
        # path absorbs it and the call still succeeds — and the retry
        # count surfaces for the transport stats block
        from tony_tpu.gateway.remote import AgentTransport

        agent = start_agent(demo)
        try:
            plan = FaultPlan([Fault("refuse", call=1, times=2)])
            t = AgentTransport(agent.address, fault_plan=plan,
                               backoff_base_s=0.01, backoff_max_s=0.02)
            doc = t.call("GET", "/healthz")
            assert doc["ok"] is True
            assert t.retries == 2
            assert t.connect_errors == 2
        finally:
            agent.stop()

    def test_refusal_beyond_budget_raises(self, demo):
        from tony_tpu.gateway.remote import AgentTransport

        agent = start_agent(demo)
        try:
            plan = FaultPlan([Fault("refuse", call=1, times=-1)])
            t = AgentTransport(agent.address, fault_plan=plan,
                               connect_retries=2, backoff_base_s=0.01,
                               backoff_max_s=0.02)
            with pytest.raises(ConnectionRefusedError):
                t.call("GET", "/healthz")
            assert t.retries == 2
        finally:
            agent.stop()

    def test_blackhole_not_retried(self, demo):
        from tony_tpu.gateway.remote import AgentTransport

        agent = start_agent(demo)
        try:
            plan = FaultPlan([Fault("blackhole", call=1)])
            t = AgentTransport(agent.address, fault_plan=plan,
                               backoff_base_s=0.01)
            with pytest.raises(TimeoutError):
                t.call("GET", "/healthz")
            assert t.retries == 0  # the caller already paid the wait
        finally:
            agent.stop()


# --------------------------------------------------------------------
# the multiplexed agent channel (ISSUE-16)
# --------------------------------------------------------------------

class TestMuxChannel:
    """ONE long-lived /v1/channel connection carries every ticket
    stream as tagged frames. make_stub defaults to mux, so the whole
    remote suite (epoch fence, chaos anchor, disconnect-resume) runs
    over the channel; this class pins the channel-specific claims."""

    def _drain(self, stub, n, timeout=120.0):
        got = {}
        deadline = time.monotonic() + timeout
        while len(got) < n and time.monotonic() < deadline:
            for res in stub.step():
                got[res.id] = list(res.tokens)
        return got

    def test_64_streams_one_connection_token_exact(self, demo,
                                                   monkeypatch):
        from tony_tpu.serve.agent import AgentHandler

        calls = {"stream": 0, "channel": 0}
        orig_get = AgentHandler.do_GET
        orig_post = AgentHandler.do_POST

        def counting_get(self):
            if self.path.startswith("/v1/stream/"):
                calls["stream"] += 1
            return orig_get(self)

        def counting_post(self):
            if self.path.partition("?")[0] == "/v1/channel":
                calls["channel"] += 1
            return orig_post(self)

        monkeypatch.setattr(AgentHandler, "do_GET", counting_get)
        monkeypatch.setattr(AgentHandler, "do_POST", counting_post)
        agent = start_agent(demo, batch_size=8)
        stub = make_stub(agent.address)
        try:
            reqs = [Request([1 + (i % 5), 2, 3], 4, id=f"m{i}")
                    for i in range(64)]
            ctrl = control_outputs(demo, reqs)
            for r in reqs:
                stub.submit(r)
            got = self._drain(stub, len(reqs))
            assert sorted(got) == sorted(ctrl)
            for rid, toks in got.items():
                assert toks == ctrl[rid], rid
            # the whole fan-in rode ONE channel connection: no
            # per-ticket stream was ever opened
            assert calls["channel"] == 1, calls
            assert calls["stream"] == 0, calls
            assert stub.transport_stats()["channel"] == "mux"
            assert stub.reconnects == 0
        finally:
            stub.close()
            agent.stop()

    def test_warm_engine_fast_finish_race(self, demo):
        """Regression pin: a warm engine can finish a request and the
        channel deliver EVERY frame before the submit POST returns.
        The stub pre-registers tickets (and ignores the racing `gone`)
        so nothing is dropped — this exact shape deadlocked before."""
        agent = start_agent(demo, batch_size=8)
        stub = make_stub(agent.address)
        try:
            stub.submit(Request([9, 2, 3], 4, id="warm"))
            assert "warm" in self._drain(stub, 1)
            # now every submit races a hot engine
            reqs = [Request([1 + i, 2, 3], 4, id=f"r{i}")
                    for i in range(8)]
            ctrl = control_outputs(demo, reqs)
            for r in reqs:
                stub.submit(r)
            got = self._drain(stub, len(reqs), timeout=60.0)
            assert sorted(got) == sorted(ctrl), got
            for rid, toks in got.items():
                assert toks == ctrl[rid], rid
        finally:
            stub.close()
            agent.stop()

    def test_garbled_frame_degrades_not_dies(self, demo, monkeypatch):
        """WIRE-LEVEL pin for the ISSUE-16 bugfix: one corrupted
        channel frame must be counted + resynced (reconnect at held
        offsets), never kill the demux loop — streams stay
        token-exact."""
        from tony_tpu.serve.agent import AgentHandler

        orig_chunk = AgentHandler._chunk
        hits = {"n": 0}

        def corrupting(self, doc):
            if "token_ids" in doc and "rid" in doc:
                hits["n"] += 1
                if hits["n"] == 2:  # swallow a REAL token frame and
                    # emit garbage instead: both the parse failure and
                    # the hidden window must heal via resync
                    data = b'{"rid": ### not json\n'
                    self.wfile.write(f"{len(data):X}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()
                    return
            return orig_chunk(self, doc)

        monkeypatch.setattr(AgentHandler, "_chunk", corrupting)
        agent = start_agent(demo, batch_size=4)
        stub = make_stub(agent.address)
        try:
            reqs = [Request([2 + i, 3, 4], 8, id=f"g{i}")
                    for i in range(4)]
            ctrl = control_outputs(demo, reqs)
            for r in reqs:
                stub.submit(r)
            got = self._drain(stub, len(reqs))
            for rid, toks in got.items():
                assert toks == ctrl[rid], rid
            assert len(got) == len(reqs)
            assert stub.garbled_frames >= 1
            assert stub.transport_stats()["garbled_frames"] >= 1
        finally:
            stub.close()
            agent.stop()

    def test_mux_disconnect_resume_by_offset(self, demo):
        """The PR-11 resume contract over the channel: injected
        disconnects mid-channel -> reconnect re-establishes every
        in-flight stream at its absolute offset, token-exact."""
        agent = start_agent(demo, batch_size=4)
        stub = make_stub(agent.address)
        try:
            # warm first so faults land mid-decode, not mid-compile
            stub.submit(Request([8, 8], 2, id="w"))
            self._drain(stub, 1)
            stub.transport.fault_plan = FaultPlan(
                [Fault("disconnect", call=1, times=3)])
            reqs = [Request([1 + i, 2, 3], 24, id=f"d{i}")
                    for i in range(4)]
            ctrl = control_outputs(demo, reqs)
            for r in reqs:
                stub.submit(r)
            got = self._drain(stub, len(reqs), timeout=120.0)
            for rid, toks in got.items():
                assert toks == ctrl[rid], rid
            assert len(got) == len(reqs)
            assert stub.reconnects >= 1
        finally:
            stub.close()
            agent.stop()

    def test_per_ticket_ab_control(self, demo):
        """--agent-channel per-ticket: the pre-mux path stays as the
        A/B control and produces identical outputs."""
        agent = start_agent(demo, batch_size=4)
        stub = make_stub(agent.address, agent_channel="per-ticket")
        try:
            reqs = [Request([1 + i, 2, 3], 6, id=f"p{i}")
                    for i in range(4)]
            ctrl = control_outputs(demo, reqs)
            for r in reqs:
                stub.submit(r)
            got = self._drain(stub, len(reqs))
            for rid, toks in got.items():
                assert toks == ctrl[rid], rid
            assert len(got) == len(reqs)
            assert stub.transport_stats()["channel"] == "per-ticket"
        finally:
            stub.close()
            agent.stop()


# --------------------------------------------------------------------
# the stub + gateway over remote replicas
# --------------------------------------------------------------------

class TestRemoteGateway:
    def test_parity_and_host_attribution(self, demo):
        from tony_tpu.gateway.core import GenRequest

        agents = [start_agent(demo) for _ in range(2)]
        stubs = [make_stub(a.address) for a in agents]
        gw = make_gateway(stubs)
        try:
            reqs = [Request([1 + i, 2, 3], 10, id=i) for i in range(4)]
            reqs.append(Request([9, 9], 8, temperature=1.0, top_k=4,
                                seed=7, id="sampled"))
            ctrl = control_outputs(demo, reqs)
            tickets = [gw.submit(GenRequest(
                list(r.prompt), max_new_tokens=r.max_new_tokens,
                temperature=r.temperature, top_k=r.top_k, seed=r.seed,
                id=r.id)) for r in reqs]
            addrs = {a.address for a in agents}
            for r, t in zip(reqs, tickets):
                res = t.result(timeout=120)
                assert list(res.tokens) == ctrl[r.id]
                # host attribution (ISSUE-11 satellite): the record
                # names the machine that served the request
                assert t.metrics["host"] in addrs
            snap = gw.snapshot()
            assert snap["shed"] == {}
            for row in snap["replicas"]:
                tr = row["transport"]
                assert tr["address"] in addrs
                assert tr["rtt_ms"] >= 0.0
                assert tr["lease_expiries"] == 0
            # both replicas actually served (least-outstanding spread)
            assert all(row["completed"] > 0
                       for row in snap["replicas"])
        finally:
            gw.drain(timeout=60)
            for a in agents:
                a.stop()

    def test_local_replica_host_is_local(self, demo):
        from tony_tpu.gateway.core import GenRequest

        gw = make_gateway([make_server(demo)])
        try:
            t = gw.submit(GenRequest([1, 2], max_new_tokens=4))
            t.result(timeout=60)
            assert t.metrics["host"] == "local"
            assert "transport" not in gw.snapshot()["replicas"][0]
        finally:
            gw.drain(timeout=60)

    def test_stub_submit_typed_refusals(self, demo):
        agent = start_agent(demo)
        stub = make_stub(agent.address)
        try:
            with pytest.raises(ValueError):
                stub.submit(Request([], 4, id="bad"))
            from tony_tpu.serve.engine import QueueFull  # noqa: F401
        finally:
            stub.close()
            agent.stop()

    def test_transport_metrics_in_exposition(self, demo):
        from tony_tpu.gateway.core import GenRequest
        from tony_tpu.obs import prometheus_text

        agent = start_agent(demo)
        gw = make_gateway([make_stub(agent.address)])
        try:
            gw.submit(GenRequest([3, 1], max_new_tokens=4)) \
                .result(timeout=60)
            text = prometheus_text(gw)
            assert "tony_transport_rtt_seconds{" in text
            assert "tony_transport_reconnects_total{" in text
            assert f'host="{agent.address}"' in text
        finally:
            gw.drain(timeout=60)
            agent.stop()


# --------------------------------------------------------------------
# the fleet observability plane (ISSUE-15)
# --------------------------------------------------------------------


def wait_obs_settled(stub, expect_tokens, timeout=30.0):
    """Wait until the stub's pulled timeline accounts for
    ``expect_tokens`` landed tokens, then FREEZE the puller so the
    caller can compare surfaces exactly (no pull can land between two
    snapshots)."""
    def settled():
        summ = stub.timeline.summary()
        return summ and sum(a["tokens"] for a in summ.values()) \
            >= expect_tokens
    wait_for(settled, timeout=timeout, msg="obs pull settled")
    stub._obs_pull = False


class TestRemoteObservability:
    def test_dispatch_goodput_and_trace_spans_merged(self, demo):
        """The tentpole pin: a remote replica's dispatch timeline,
        goodput ledger, and per-request dispatch spans land in the
        gateway's surfaces exactly like a local engine's — merged
        engine.dispatch, a non-null per-replica goodput block, an
        explicit obs health block, and trace spans grafted into the
        attempt tree carrying host + clock-offset tags."""
        from tony_tpu.gateway.core import GenRequest
        from tony_tpu.obs.trace import check_invariants

        agent = start_agent(demo)
        stub = make_stub(agent.address)
        gw = make_gateway([stub])
        try:
            n, budget = 3, 10
            tickets = [gw.submit(GenRequest([1 + i, 2, 3],
                                            max_new_tokens=budget,
                                            id=f"ob{i}"))
                       for i in range(n)]
            for t in tickets:
                t.result(timeout=120)
            wait_obs_settled(stub, n * budget)
            snap = gw.snapshot()
            row = snap["replicas"][0]
            # the pulled timeline IS the replica's dispatch block, and
            # it agrees with the agent's own engine exactly
            agent_summ = agent.agent.server.timeline.summary()
            assert row["dispatch"] == agent_summ
            assert row["dispatch"]["prefill"]["count"] == n
            # ...and the fleet merge carries it
            eng = snap["engine"]["dispatch"]
            assert eng["prefill"]["count"] == n
            assert eng["decode"]["tokens"] > 0
            # the pulled ledger is a real merged-able goodput block
            assert row["goodput"] is not None
            assert sum(row["goodput"]["buckets"].values()) <= 1 + 1e-6
            fleet = snap["engine"]["goodput"]
            assert fleet and sum(fleet["buckets"].values()) <= 1 + 1e-6
            # the obs health block: pulls counted, lag fresh, errors 0
            obs = row["obs"]
            assert obs["enabled"] and obs["pulls"] >= 1
            assert obs["pull_errors"] == 0
            assert obs["cursor"] > 0 and obs["lag_s"] is not None
            # remote dispatch spans grafted into the attempt tree,
            # offset-corrected and tagged with the host + the offset
            # and its uncertainty
            tr = gw.traces.get("ob0")
            assert tr is not None and check_invariants(tr) == []
            attempts = [c for c in tr.root.children
                        if c.name.startswith("attempt-")]
            assert attempts[0].tags["host"] == agent.address
            remote_spans = [c for c in attempts[0].children
                            if c.tags.get("host") == agent.address]
            assert remote_spans, [c.name for c in attempts[0].children]
            assert any(s.name in ("prefill", "decode")
                       for s in remote_spans)
            for s in remote_spans:
                assert "clock_offset_ms" in s.tags
                assert "clock_offset_unc_ms" in s.tags
            # the Chrome export names the process after the host, and
            # /debug/traces summaries carry the host column
            doc = tr.to_chrome()
            procs = [e for e in doc["traceEvents"]
                     if e.get("name") == "process_name"]
            assert any(agent.address in e["args"]["name"]
                       for e in procs)
            rows = {r["request_id"]: r
                    for r in gw.traces.summaries()}
            assert rows["ob0"]["host"] == agent.address
        finally:
            gw.drain(timeout=60)
            agent.stop()

    def test_local_replica_traces_name_host_local(self, demo):
        from tony_tpu.gateway.core import GenRequest

        gw = make_gateway([make_server(demo)])
        try:
            gw.submit(GenRequest([4, 2], max_new_tokens=3,
                                 id="loc")).result(timeout=60)
            rows = {r["request_id"]: r for r in gw.traces.summaries()}
            assert rows["loc"]["host"] == "local"
        finally:
            gw.drain(timeout=60)

    def test_obs_pull_failure_degrades_to_staleness(self, demo):
        """The acceptance pin's graceful-degrade half: obs pulls that
        fail (here: an agent without the channel — 404s) count
        pull_errors and leave lag_s stale, but the replica stays
        HEALTHY, keeps serving with zero 5xx, and its /stats row says
        explicitly that it is unobserved (goodput null) rather than
        silently omitting the keys. Per-ticket mode: a pre-ISSUE-15
        agent predates the mux channel too (under mux the channel
        itself delivers obs, so the pull path never runs dry)."""
        from tony_tpu.gateway.core import GenRequest

        agent = start_agent(demo)
        stub = make_stub(agent.address, agent_channel="per-ticket")
        stub._OBS_PATH = "/v1/obs-not-there"  # a pre-ISSUE-15 agent
        gw = make_gateway([stub])
        try:
            t = gw.submit(GenRequest([5, 1], max_new_tokens=6,
                                     id="deg"))
            res = t.result(timeout=120)
            assert len(res.tokens) == 6
            wait_for(lambda: stub.obs_stats()["pull_errors"] >= 2,
                     msg="pull errors counted")
            snap = gw.snapshot()
            assert snap["shed"] == {}          # never a 5xx
            row = snap["replicas"][0]
            assert row["state"] == "healthy"   # never a failure
            obs = row["obs"]
            assert obs["pulls"] == 0 and obs["pull_errors"] >= 2
            assert obs["lag_s"] is None        # never pulled: stale
            # explicit "unobserved", not a silently missing key
            assert "goodput" in row and row["goodput"] is None
            assert row["dispatch"] == {}
        finally:
            gw.drain(timeout=60)
            agent.stop()

    def test_profile_fanout_arms_agents(self, demo):
        """POST /debug/profile's remote half: the gateway fans the
        capture request to each agent's /v1/profile and reports
        per-host armed/error — a busy agent's 409 never blocks the
        rest. (The real jax capture path is exercised by the smoke's
        remote round; here the agent profilers are recorders, so the
        fast tier never pays start_trace's >10 s first-call.)"""
        class FakeProfiler:
            def __init__(self, busy=False):
                self.busy_ = busy
                self.requests = []

            def request(self, steps, logdir=None):
                if self.busy_:
                    raise RuntimeError("a profile capture is already "
                                       "pending or active")
                self.requests.append(steps)
                return "/on/agent/profiles/profile-1"

            def status(self):
                return {"active": bool(self.requests),
                        "captures": 0}

            def close(self):
                pass

        agents = [start_agent(demo) for _ in range(2)]
        agents[0].agent.profiler = FakeProfiler()
        agents[1].agent.profiler = FakeProfiler(busy=True)
        stubs = [make_stub(a.address) for a in agents]
        gw = make_gateway(stubs)
        try:
            out = gw.arm_remote_profiles(3)
            assert out[agents[0].address]["armed"] is True
            assert out[agents[0].address]["logdir"] \
                == "/on/agent/profiles/profile-1"
            assert agents[0].agent.profiler.requests == [3]
            assert out[agents[1].address]["armed"] is False
            assert out[agents[1].address]["status"] == 409
            status = gw.remote_profile_status()
            assert status[agents[0].address]["active"] is True
        finally:
            gw.drain(timeout=60)
            for a in agents:
                a.stop()

    def test_autotune_never_samples_remote_stubs(self, demo):
        """Regression pin: the shape controller's 'remote stubs are
        never actuated' gate used to key on ``timeline is None`` —
        ISSUE-15 gave stubs a real (pulled) timeline, but their shape
        knobs still live on the AGENT's engine, so the gate must key
        on the transport instead."""
        from tony_tpu.serve.autotune import AutotuneController

        agent = start_agent(demo)
        stub = make_stub(agent.address)
        try:
            assert stub.timeline is not None  # the ISSUE-15 change
            assert AutotuneController()._sample(stub) is None
        finally:
            stub.close()
            agent.stop()

    def test_local_arm_does_not_block_remote_fanout(self, demo):
        """Mixed local+remote fleet: jax's one-global-session rule is
        PER PROCESS, so a pending gateway-local capture (armed, idle
        fleet — never burns down) must not 409 the agent fan-out. The
        POST reports the local refusal in ``local_error`` and still
        arms the agents; a LOCAL-only fleet keeps the 409 contract
        (pinned by test_http_profile_endpoint_real_capture)."""
        import json as _json
        import urllib.request

        from tony_tpu.gateway import GatewayHTTP

        class FakeProfiler:
            def request(self, steps, logdir=None):
                return "/on/agent/profiles/profile-x"

            def status(self):
                return {"active": True, "captures": 0}

            def close(self):
                pass

        agent = start_agent(demo)
        agent.agent.profiler = FakeProfiler()
        gw = make_gateway([make_server(demo),
                           make_stub(agent.address)])
        http = GatewayHTTP(gw, port=0).start()
        url = f"http://{http.host}:{http.port}"
        try:
            gw.profiler.request(5)  # pending local capture, idle fleet
            req = urllib.request.Request(url + "/debug/profile?steps=2",
                                         data=b"", method="POST")
            doc = _json.loads(
                urllib.request.urlopen(req, timeout=60).read())
            assert doc["remote"][agent.address]["armed"] is True
            assert doc["logdir"] is None
            assert "already" in doc["local_error"]
            assert doc["armed"] is True  # the fleet IS capturing
        finally:
            http.stop()
            gw.drain(timeout=60)
            agent.stop()


# --------------------------------------------------------------------
# epoch fence pins
# --------------------------------------------------------------------

class TestEpochFence:
    def test_reset_discards_superseded_stream(self, demo):
        # the revived-host shape: a stream opened under epoch 0 keeps
        # flowing while the stub moves to epoch 1 (reset) — the
        # agent's superseded stream ends, and whatever it still says
        # is dropped by the echo check, counted in stale_epoch_drops
        agent = start_agent(demo)
        stub = make_stub(agent.address)
        try:
            stub.submit(Request([1, 2], 40, id="long"))
            wait_for(lambda: stub.live_progress().get("long"),
                     msg="first tokens")
            stub.reset()  # epoch 0 -> 1; agent adopts 1
            wait_for(lambda: stub.stale_epoch_drops > 0,
                     msg="stale drop counted")
            assert stub.epoch == 1
            assert stub._tickets == {}  # nothing stale survives
            # and the agent refuses the OLD epoch outright now
            from tony_tpu.gateway.remote import AgentHTTPError

            with pytest.raises(AgentHTTPError) as ei:
                stub.transport.call("POST", "/v1/submit", {
                    "id": "z", "prompt": [1], "max_new_tokens": 2,
                    "epoch": 0})
            assert ei.value.status == 409
        finally:
            stub.close()
            agent.stop()

    def test_submit_after_agent_restart_adopts_epoch(self, demo):
        agent = start_agent(demo)
        stub = make_stub(agent.address)
        try:
            stub.reset()
            stub.reset()  # stub at epoch 2
            host, port = agent.address.split(":")
            agent.stop()
            agent = start_agent(demo, port=int(port))  # fresh epoch 0
            stub.submit(Request([5], 4, id="post"))
            assert agent.agent.epoch == 2  # adopted, not rewound
            results = []
            wait_for(lambda: results.extend(stub.step()) or results,
                     msg="finish")  # step() collects the result
            assert results[0].id == "post"
        finally:
            stub.close()
            agent.stop()


# --------------------------------------------------------------------
# chaos: the remote anchors
# --------------------------------------------------------------------

class TestRemoteChaos:
    def test_remote_chaos_anchor(self, demo):
        """THE ISSUE-11 anchor: 2 agents under concurrent load; agent
        0 dies a network-SIGKILL mid-stream (failover path), agent 1's
        streams are disconnected mid-read by injected transport faults
        (resume path) -> zero 5xx, byte-identical outputs, survivor
        keeps serving WITHOUT ever being failed, and a restarted agent
        0 rejoins through the probe path.

        ISSUE-15 extension: after the kill + failover, a victim's
        SINGLE trace carries attempt spans from BOTH hosts — the dead
        host's attempt holding offset-corrected remote dispatch spans
        pulled before it died — and the fleet goodput merge still
        sums <= 1 with the survivor's remote ledger included."""
        from tony_tpu.gateway.core import GenRequest
        from tony_tpu.obs.trace import check_invariants

        agents = [start_agent(demo) for _ in range(2)]
        stubs = [make_stub(a.address) for a in agents]
        gw = make_gateway(stubs)
        try:
            reqs = [Request([1 + i, 2, 3], 48, id=i) for i in range(6)]
            ctrl = control_outputs(demo, reqs)
            # warm the remote path so the kill lands mid-decode, not
            # mid-compile
            gw.submit(GenRequest([7, 7], max_new_tokens=2,
                                 id="warm")).result(timeout=120)

            # throttle the DOOMED engine (every dispatch sleeps a
            # beat, well under the stall horizon) so the kill lands
            # mid-decode even on a warm process — the mux channel
            # otherwise delivers all six streams before the grafted
            # span below is ever observed
            agents[0].agent.server.fault_plan = FaultPlan(
                [Fault("wedge", dispatch=1, seconds=0.25, times=-1)])

            # arm disconnect-mid-stream on the SURVIVOR's transport:
            # times=3 transient — resume-by-offset must absorb it
            stubs[1].transport.fault_plan = FaultPlan(
                [Fault("disconnect", call=1, times=3)])

            tickets = [gw.submit(GenRequest(
                list(r.prompt), max_new_tokens=r.max_new_tokens,
                id=r.id)) for r in reqs]
            wait_for(lambda: stubs[0].n_active > 0, msg="r0 active")

            # the kill must land AFTER at least one of the doomed
            # host's dispatch spans was pulled and grafted — that is
            # exactly the record the flight-recorder story needs to
            # survive the host's death
            a0 = agents[0].address

            def r0_span_attached():
                for t in tickets:
                    tr = t.trace
                    if tr is None:
                        continue
                    for att in tr.root.children:
                        if att.name.startswith("attempt-") \
                                and att.tags.get("host") == a0 \
                                and any(c.tags.get("host") == a0
                                        for c in att.children):
                            return True
                return False

            wait_for(r0_span_attached, msg="r0 dispatch span grafted")
            agents[0].kill()  # SIGKILL, as the network sees it

            for r, t in zip(reqs, tickets):
                res = t.result(timeout=180)
                assert list(res.tokens) == ctrl[r.id], \
                    f"request {r.id} diverged after chaos"
            # the lease is the death authority; the re-runs can finish
            # FASTER than the lease horizon on a warm engine, so wait
            # for the expiry rather than racing it
            wait_for(lambda: stubs[0].lease_expiries >= 1,
                     timeout=30, msg="lease expiry")
            snap = gw.snapshot()
            assert snap["shed"] == {}  # zero 5xx
            assert snap["supervision"]["replica_failures"] >= 1
            assert snap["supervision"]["failovers"] >= 1
            rows = {row["replica"]: row for row in snap["replicas"]}
            # the survivor resumed, never failed
            assert rows[1]["failures"] == 0
            assert rows[1]["transport"]["reconnects"] >= 1
            assert rows[1]["completed"] >= 1
            assert rows[0]["transport"]["lease_expiries"] >= 1

            # ISSUE-15: ONE trace spans both hosts of the failover
            victims = [t for t in tickets
                       if t.metrics and t.metrics["attempts"] >= 1]
            assert victims, "no ticket was failed over"
            both_hosts_seen = False
            for t in victims:
                tr = gw.traces.get(t.request.id)
                assert tr is not None and tr.n_attempts >= 2
                assert check_invariants(tr) == []
                hosts = [a.tags.get("host") for a in tr.root.children
                         if a.name.startswith("attempt-")]
                if {agents[0].address, agents[1].address} \
                        <= set(hosts):
                    both_hosts_seen = True
                # the dead host's attempt kept its pulled dispatch
                # spans, offset-corrected (the fence dropped only
                # what arrived AFTER the steal)
                for att in tr.root.children:
                    if not att.name.startswith("attempt-") \
                            or att.tags.get("host") != a0:
                        continue
                    spans = [c for c in att.children
                             if c.tags.get("host") == a0]
                    if spans:
                        assert all("clock_offset_ms" in c.tags
                                   for c in spans)
            assert both_hosts_seen
            # ...and the merged fleet ledger still holds its invariant
            # with the survivor's remote ledger included
            assert rows[1]["goodput"] is not None
            fleet = snap["engine"]["goodput"]
            assert fleet and sum(fleet["buckets"].values()) <= 1 + 1e-6

            # restart agent 0 on the SAME port: the breaker's probe
            # path must rejoin it without operator action
            host, port = agents[0].address.split(":")
            agents[0] = start_agent(demo, port=int(port))
            wait_for(lambda: gw.replicas[0].state == "healthy",
                     timeout=60, msg="rejoin via probe")
            assert gw.snapshot()["supervision"]["rejoins"] >= 1
            # post-chaos, the rejoined host's obs channel works: a new
            # request's trace grafts dispatch spans from the restarted
            # agent (same address, fresh agent-side timeline)
            t = gw.submit(GenRequest([3, 3, 3], max_new_tokens=6,
                                     id="post-rejoin",
                                     session="pin0"))
            assert len(t.result(timeout=120).tokens) == 6
        finally:
            gw.drain(timeout=60)
            for a in agents:
                a.stop()

    def test_blackhole_partition_fails_over_token_exact(self, demo):
        """A full network partition (every call to agent 0 times out,
        injected) is indistinguishable from a dead host: the lease
        expires, everything fails over token-exactly, zero 5xx."""
        from tony_tpu.gateway.core import GenRequest

        agents = [start_agent(demo) for _ in range(2)]
        stubs = [make_stub(a.address) for a in agents]
        gw = make_gateway(stubs)
        try:
            reqs = [Request([2 + i, 4], 32, id=i) for i in range(4)]
            ctrl = control_outputs(demo, reqs)
            gw.submit(GenRequest([7, 7], max_new_tokens=2,
                                 id="warm")).result(timeout=120)
            # drop the partition: EVERYTHING to/from agent 0
            # black-holes from here on — the submit the router sends
            # it next must fail over, and the heartbeat blackout must
            # expire the lease (permanent, so no timing race)
            stubs[0].transport.fault_plan = FaultPlan(
                [Fault("blackhole", call=1, times=-1)])
            tickets = [gw.submit(GenRequest(
                list(r.prompt), max_new_tokens=r.max_new_tokens,
                id=r.id)) for r in reqs]
            for r, t in zip(reqs, tickets):
                res = t.result(timeout=180)
                assert list(res.tokens) == ctrl[r.id]
            # the lease is the death authority: the heartbeat blackout
            # must expire it even though the failover already happened
            # via the admission route
            wait_for(lambda: stubs[0].lease_expiries >= 1,
                     timeout=30, msg="lease expiry")
            snap = gw.snapshot()
            assert snap["shed"] == {}  # zero 5xx
            assert snap["supervision"]["replica_failures"] >= 1
            rows = {row["replica"]: row for row in snap["replicas"]}
            tr0 = rows[0]["transport"]
            assert tr0["heartbeat_failures"] >= 1
            assert rows[0]["state"] in ("broken", "probing")
            assert rows[1]["completed"] >= len(reqs)
        finally:
            gw.drain(timeout=60)
            for a in agents:
                a.stop()

    def test_wedged_remote_engine_fails_over(self, demo):
        """A dispatch that WEDGES on the agent (engine wedge fault)
        stops the agent's stepper beat; the stub's heartbeat sees a
        busy agent whose stepper age exceeds the stall horizon and
        withholds the lease ping — same funnel, token-exact."""
        from tony_tpu.gateway.core import GenRequest
        from tony_tpu.serve.faults import FaultPlan as FP

        agents = [start_agent(demo) for _ in range(2)]
        # wedge replica 0's engine on a mid-generation dispatch, long
        # enough to blow the stub's (tight) stall horizon
        agents[0].agent.server.fault_plan = FP.wedge_at(
            dispatch=4, seconds=4.0)
        stubs = [make_stub(agents[0].address, stall_timeout_s=0.5),
                 make_stub(agents[1].address)]
        gw = make_gateway(stubs)
        try:
            req = Request([6, 1], 24, id="w")
            ctrl = control_outputs(demo, [req])
            # route to replica 0 via session affinity being moot on an
            # idle fleet: least-outstanding picks 0 first
            ticket = gw.submit(GenRequest([6, 1], max_new_tokens=24,
                                          id="w"))
            res = ticket.result(timeout=180)
            assert list(res.tokens) == ctrl["w"]
            assert gw.snapshot()["shed"] == {}
        finally:
            gw.drain(timeout=60)
            for a in agents:
                a.stop()


@pytest.mark.slow
def test_subprocess_agent_sigkill_e2e(tmp_path, demo):
    """The subprocess flavor of the anchor: two REAL ``python -m
    tony_tpu.cli.replica`` processes, one killed with an actual
    SIGKILL mid-stream -> zero 5xx, token-exact outputs, clean drain
    of the survivor. (The in-process anchor above runs in tier-1; this
    is the no-simulation version, also exercised by
    ``make remote-smoke``.)"""
    import os
    import signal as sig

    from tony_tpu.cli.gateway import build_gateway, build_parser
    from tony_tpu.gateway.core import GenRequest

    procs, addrs = [], []
    try:
        for i in range(2):
            proc, addr = launch_agent_subprocess(tmp_path, i)
            procs.append(proc)
            addrs.append(addr)
        # quarantine the corpse FAST: endless probe laps against a
        # dead port would starve the survivor's decode on a 1-CPU box
        args = build_parser().parse_args([
            "--agents", ",".join(addrs), "--serve-batch", "2",
            "--agent-heartbeat", "0.1", "--agent-lease-misses", "3",
            "--breaker-base", "0.05", "--breaker-max", "0.25",
            "--quarantine-after", "3", "--compile-cache", ""])
        gw = build_gateway(args, None, None, []).start()
        try:
            reqs = [Request([1 + i, 2, 3], 48, id=i) for i in range(6)]
            ctrl = control_outputs(demo, reqs)
            gw.submit(GenRequest([7, 7], max_new_tokens=2,
                                 id="warm")).result(timeout=180)
            tickets = [gw.submit(GenRequest(
                list(r.prompt), max_new_tokens=r.max_new_tokens,
                id=r.id)) for r in reqs]
            stub0 = gw.replicas[0].server
            wait_for(lambda: stub0.n_active > 0, timeout=60,
                     msg="r0 active")
            os.kill(procs[0].pid, sig.SIGKILL)  # the real thing
            for r, t in zip(reqs, tickets):
                assert list(t.result(timeout=180).tokens) == ctrl[r.id]
            snap = gw.snapshot()
            assert snap["shed"] == {}
            assert snap["supervision"]["replica_failures"] >= 1
        finally:
            gw.drain(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except Exception:
                p.kill()


@pytest.mark.slow
def test_remote_drain_then_sigterm_exits_zero(tmp_path):
    """Regression pin: the scale-down sequence (gateway POSTs
    /v1/drain, then close() sends ONE polite SIGTERM) must exit 0 —
    the signal handler counts SIGNALS for its force path, it must not
    read an HTTP-initiated drain as 'second signal'."""
    import signal as sig

    from tony_tpu.gateway.remote import AgentTransport

    proc, addr = launch_agent_subprocess(tmp_path, 0)
    try:
        t = AgentTransport(addr)
        assert t.call("POST", "/v1/drain",
                      {"timeout_s": 60}, timeout=90.0)["drained"]
        proc.send_signal(sig.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_agent_argv_passes_host_share():
    # launched localhost agents must size auto KV pools for the fleet
    # CEILING sharing the host (the PR-8 oversubscription rule)
    from tony_tpu.cli.gateway import agent_argv, build_parser

    args = build_parser().parse_args(
        ["--demo-model", "--remote-replica", "--replicas", "2",
         "--autoscale-max", "3"])
    argv = agent_argv(args, 1)
    i = argv.index("--host-share")
    assert argv[i + 1] == "3"


def launch_agent_subprocess(tmp_path, index):
    from tony_tpu.gateway.remote import launch_local_agent

    return launch_local_agent(
        ["--demo-model", "--serve-batch", "2", "--port", "0",
         "--replica-index", str(index), "--compile-cache", ""],
        port_file=str(tmp_path / f"agent-{index}.port"),
        boot_timeout_s=180.0)


# --------------------------------------------------------------------
# provisioner integration: no leaked capacity
# --------------------------------------------------------------------

class _FakeProvisioner:
    def __init__(self):
        self.provisioned = False
        self.deprovisioned = False

    def provision(self):
        self.provisioned = True
        return ["127.0.0.1"]

    def deprovision(self):
        self.deprovisioned = True


class TestProvisionerRemote:
    def test_dead_remote_slice_deprovisioned_no_leak(self, demo):
        """The acceptance pin: a scaled-up REMOTE replica whose host
        dies is quarantine-first victim at the next scale-down tick —
        remove_replica drains the corpse, the stub closes, and the
        slice is deprovisioned. Nothing leaks."""
        from tony_tpu.gateway.autoscale import (AutoScaler,
                                                ProvisionerBackend)

        agents = []

        def server_factory(hosts):
            assert hosts == ["127.0.0.1"]
            agent = start_agent(demo)
            agents.append(agent)
            return make_stub(agent.address)

        prov = _FakeProvisioner()
        gw = make_gateway([make_server(demo)], quarantine_after=1)
        backend = ProvisionerBackend(lambda slot: prov, server_factory)
        scaler = AutoScaler(gw, backend, min_replicas=1, max_replicas=2,
                            interval_s=3600, down_stable=1,
                            cooldown_up_s=0.0, cooldown_down_s=0.0)
        try:
            server = backend.create()
            assert prov.provisioned
            idx = gw.add_replica(server, probe=True)
            scaler._servers[idx] = server
            wait_for(lambda: gw.replicas[idx].state == "healthy",
                     timeout=60, msg="probe admission")
            agents[0].kill()  # the host dies
            wait_for(lambda: gw.replicas[idx].state == "quarantined",
                     timeout=60, msg="quarantine")
            # drive the control loop by hand: idle fleet + a dead
            # replica -> scale-down picks the corpse first
            wait_for(lambda: scaler.tick() == "down", timeout=30,
                     interval=0.05, msg="scale-down of the corpse")
            assert gw.replicas[idx].retired
            assert gw.replicas[idx].server is None
            assert prov.deprovisioned  # the slice went back
            assert backend._slices == {}  # nothing leaked
        finally:
            scaler.stop(timeout=5)
            gw.drain(timeout=60)
            for a in agents:
                try:
                    a.stop()
                except Exception:
                    pass
