from tony_tpu.metrics.sampler import (
    AVG_MEMORY_RSS,
    MAX_MEMORY_RSS,
    MetricsStore,
    TaskMetricsMonitor,
    process_tree_rss_bytes,
)

__all__ = [
    "AVG_MEMORY_RSS",
    "MAX_MEMORY_RSS",
    "MetricsStore",
    "TaskMetricsMonitor",
    "process_tree_rss_bytes",
]
