"""Config system tests, incl. the schema drift lock.

Reference analogs: TestTonyConfigurationFields.java:74 (keys<->defaults
bijection), TestUtils.java conf parsing, TestTonyClient conf processing.
"""

import json

import pytest

from tony_tpu.config import ConfError, TonyConf, build_conf, keys, role_key


def test_defaults_loaded():
    conf = TonyConf()
    assert conf.get("tony.application.framework") == "jax"
    assert conf.get_int("tony.task.heartbeat-interval-ms") == 1000
    assert conf.get_bool("tony.application.security.enabled") is True


def test_schema_drift_lock():
    """Every key has a doc and a default of the declared type (ref:
    TestTonyConfigurationFields keys<->xml bijection)."""
    for name, spec in {**keys.KEYS, **keys.ROLE_SUFFIXES}.items():
        assert spec.doc, f"{name} missing doc"
        assert isinstance(spec.default, spec.type), name
    # defaults() covers exactly KEYS
    assert set(keys.defaults()) == set(keys.KEYS)


def test_role_regex_arbitrary_names():
    conf = TonyConf()
    conf.set("tony.head.instances", "1")
    conf.set("tony.worker.instances", 2)
    conf.set("tony.worker.chips", 4)
    assert conf.roles() == ["head", "worker"]
    assert conf.role_get("worker", "chips") == 4
    # unset role keys fall back to suffix defaults
    assert conf.role_get("head", "memory") == "2g"
    assert conf.role_get("head", "depends-on") == ""


def test_reserved_namespaces_not_roles():
    conf = TonyConf()
    conf.set("tony.worker.instances", 1)
    assert "application" not in conf.roles()
    assert "task" not in conf.roles()


def test_type_coercion():
    conf = TonyConf()
    conf.set("tony.task.max-missed-heartbeats", "7")
    assert conf.get("tony.task.max-missed-heartbeats") == 7
    conf.set("tony.application.fail-on-worker-failure-enabled", "TRUE")
    assert conf.get_bool("tony.application.fail-on-worker-failure-enabled") is True


def test_layering_precedence(tmp_path):
    f = tmp_path / "tony.toml"
    f.write_text(
        '[tony.application]\nname = "from-file"\n\n[tony.worker]\ninstances = 3\n'
    )
    site_dir = tmp_path / "site"
    site_dir.mkdir()
    (site_dir / "tony-site.json").write_text(json.dumps({"tony.worker.instances": 5}))
    conf = build_conf(str(f), ["tony.application.name=from-cli"], str(site_dir))
    assert conf.get("tony.application.name") == "from-cli"  # cli > file
    assert conf.get_int("tony.worker.instances") == 5  # site > cli/file


def test_multi_value_append():
    conf = TonyConf()
    conf.apply_overrides(
        ["tony.application.untracked.jobtypes=a", "tony.application.untracked.jobtypes=b"]
    )
    assert conf.get_list("tony.application.untracked.jobtypes") == ["ps", "a", "b"]


def test_final_roundtrip(tmp_path):
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.application.name", "rt")
    p = tmp_path / "tony-final.json"
    conf.write_final(str(p))
    back = TonyConf.from_final(str(p))
    assert back.get_int("tony.worker.instances") == 2
    assert back.get("tony.application.name") == "rt"
    assert back.get_int("tony.task.heartbeat-interval-ms") == 1000


def test_validation_limits():
    conf = TonyConf()
    conf.set("tony.worker.instances", 4)
    conf.set("tony.worker.chips", 8)
    conf.set("tony.application.max-total-chips", 16)
    with pytest.raises(ConfError):
        conf.validate()
    conf.set("tony.application.max-total-chips", 32)
    conf.validate()


def test_validation_max_instances():
    conf = TonyConf()
    conf.set("tony.worker.instances", 4)
    conf.set("tony.worker.max-instances", 2)
    with pytest.raises(ConfError):
        conf.validate()


def test_bad_distributed_mode():
    conf = TonyConf()
    conf.set("tony.application.distributed-mode", "RING")
    with pytest.raises(ConfError):
        conf.validate()


def test_role_key_helper():
    assert role_key("worker", "instances") == "tony.worker.instances"
    with pytest.raises(KeyError):
        role_key("worker", "nope")


def test_config_reference_drift_lock():
    """CONFIG.md must be the exact rendering of the key schema — the
    rebuild's analog of TestTonyConfigurationFields locking
    TonyConfigurationKeys <-> tony-default.xml (SURVEY.md section 4.3).
    Regenerate with: python -m tony_tpu.config.docs > CONFIG.md"""
    import pathlib

    from tony_tpu.config.docs import render_config_reference

    root = pathlib.Path(__file__).resolve().parent.parent
    checked_in = (root / "CONFIG.md").read_text()
    assert checked_in == render_config_reference(), (
        "CONFIG.md is stale; regenerate with "
        "`python -m tony_tpu.config.docs > CONFIG.md`")


def test_config_reference_covers_every_key():
    from tony_tpu.config import keys as K
    from tony_tpu.config.docs import render_config_reference

    text = render_config_reference()
    for name in K.KEYS:
        assert f"`{name}`" in text, name
    for suffix in K.ROLE_SUFFIXES:
        assert f"`{suffix}`" in text, suffix
