"""Continuous-batching scheduler over one resident jitted decode step.

Design (the Orca/vLLM iteration-level result, on the TPU static-shape
path):

- ONE decode step of fixed shape [batch_size, 1] over the fixed
  [batch_size, max_seq_len] cache compiles once and serves the whole
  session. Per-slot positions ride in as a traced [b] vector
  (``Transformer.__call__(..., positions=...)``); per-request
  temperature/top-k are traced too, so a new mix of requests NEVER
  recompiles anything.
- Prefill runs as a separate batch-1 jit at a few BUCKETED lengths
  (powers of two): O(log max_seq_len) compiles ever, right-padded —
  causal attention keeps pad junk out of the real positions' K/V, and
  the slot's length masks the tail until decode overwrites it.
- Each ``step()``: admit pending prompts into free slots (prefill,
  slot copy and first-token sample FUSED into one dispatch per
  request), run a CHUNK of K batched decode micro-steps as one
  lax.scan dispatch (K adapts to the live slots' remaining budgets,
  rounded to a power of two so at most log2(chunk_steps)+1 programs
  ever compile), sample per-slot inside the chunk, then detect EOS /
  budget per slot host-side, evict finished slots and return their
  results. A finished slot is refilled the SAME iteration — mixed-
  length traffic never waits on the longest sequence in the batch (the
  fixed-batch ``generate()`` failure mode). Chunking amortizes the
  per-dispatch host cost over K tokens; a slot that finishes mid-chunk
  decodes garbage until the chunk ends (its row is independent — no
  other slot sees it) which the host trims before reporting, so
  results are unaffected and the waste is bounded by K-1 slot-steps
  per finish.

Greedy outputs are token-for-token identical to a solo ``generate()``
of the same prompt (the exactness contract tests/test_serve.py pins):
prefill math is position-exact under bucket padding and the per-slot
step runs the same attention reduction over the same [max_seq_len]
buffer as the scalar-index path.
"""

from __future__ import annotations

import functools
import itertools
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import (_is_eos, init_cache,
                                      multi_decode_step,
                                      normalize_eos_ids,
                                      single_decode_step)
from tony_tpu.obs.goodput import (CostModel, detect_hbm_gbps,
                                  detect_peak_flops, ledger)
from tony_tpu.obs.timeline import DispatchRecord, DispatchTimeline
from tony_tpu.serve.faults import FaultPlan
from tony_tpu.serve.migrate import SessionSnapshot, StaleDelta, \
    snapshot_from_doc
from tony_tpu.serve.prefix import PrefixStore
from tony_tpu.serve.slots import (PagePool, SlotCache, _gather_pages,
                                  _read_slot, _scatter_pages,
                                  cache_batch_axis, default_page_size,
                                  paged_view, paged_write_back)
from tony_tpu.serve.tier import (HostPageTier, decode_array,
                                 decode_payload, pad_host_pages,
                                 payload_pages)

log = logging.getLogger(__name__)


def bucket_len(n: int, max_len: int, minimum: int = 16) -> int:
    """Smallest power-of-two bucket >= n (floor ``minimum``, cap
    ``max_len``): prefill compiles once per bucket, not once per length."""
    b = minimum
    while b < n:
        b *= 2
    return min(b, max_len)


def _bucket_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1). Quantizes the verify
    window's draft width so at most log2(speculate_k)+1 verify programs
    ever compile — same discipline as the prefill buckets."""
    b = 1
    while b < n:
        b *= 2
    return b


def _propose_draft(ctx: np.ndarray, k: int,
                   max_ngram: int = 3) -> np.ndarray:
    """Prompt-lookup drafting (the n-gram self-speculation vLLM/HF
    popularized): find the most RECENT earlier occurrence of the
    longest suffix n-gram of ``ctx`` (n from ``max_ngram`` down to 1)
    and propose the up-to-``k`` tokens that followed it. No draft
    model, no device work — one numpy scan over a <= max_seq_len
    context per live slot per round, so a miss costs essentially
    nothing. Extractive / repetitive continuations (quoting the prompt,
    structured output, greedy loops) hit constantly; free-form text
    mostly misses and the engine's per-slot EMA stops asking. Returns
    [0..k] proposed continuation tokens (empty = no match)."""
    n_ctx = len(ctx)
    for n in range(min(max_ngram, n_ctx - 1), 0, -1):
        pat = ctx[n_ctx - n:]
        # windows over ctx[:-1]: every start with >= 1 token following
        # the match; the suffix itself (ending at the last token) is
        # structurally excluded
        win = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
        hits = np.flatnonzero((win == pat).all(axis=1))
        if hits.size:
            start = int(hits[-1]) + n
            return ctx[start:start + k]
    return ctx[:0]


def _seed_offset(cache, offset):
    """Set a cache pytree's shared position counters (per-layer
    ``cache_index``, learned-positional ``pos_index``) to ``offset`` —
    the scalar decode path then WRITES the next tokens at ``offset``,
    rotates them there (RoPE reads ``cache_index``), and lets their
    queries see everything at-or-before them: exactly the offset
    attention a suffix prefill over a seeded prefix row needs.
    ``offset`` is traced; scan_layers models carry stacked [n_layers]
    counters, which full_like broadcasts over."""
    def seed(path, leaf):
        name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
        if name in ("cache_index", "pos_index"):
            return jnp.full_like(leaf, offset)
        return leaf

    return jax.tree_util.tree_map_with_path(seed, cache)


@functools.partial(jax.jit, static_argnames=("model",))
def _prefill(model, params, prompt, length, offset=None, row=None):
    """Prefill ONE request's token window [1, Lb] (right-padded to its
    bucket) into a batch-1 cache. Returns (row_cache, logits [1, V] at
    the REAL last position ``length - 1`` of the window — the padded
    tail's logits are junk and never sampled).

    ``offset``/``row`` generalize this to SUFFIX prefill for the prefix
    store: ``row`` is a carried batch-1 cache whose positions
    ``[0, offset)`` already hold the shared prefix's K/V, and the
    window holds only the remaining prompt tokens, written/rotated/
    attended from position ``offset`` (counters seeded via
    ``_seed_offset``). With both None this is the classic full prefill
    of a fresh cache from position 0."""
    cache = init_cache(model, params, 1) if row is None else row
    if offset is not None:
        cache = _seed_offset(cache, offset)
    logits, vars_ = model.apply({"params": params, "cache": cache},
                                prompt, decode=True, mutable=["cache"])
    last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1, axis=1)
    return vars_["cache"], last[:, 0]


@functools.partial(jax.jit, static_argnames=("model", "with_row"))
def _prefill_admit(model, params, cache, prompt, length, slot, temp,
                   top_k, key, offset=None, row=None, *, with_row=False):
    """The fused admit: prefill [1, Lb] (optionally a suffix seeded
    from a prefix-store ``row`` at ``offset``), copy the row into
    ``slot`` of the resident cache, sample the first continuation
    token — ONE dispatch per admitted request (three separate
    dispatches measured ~3x the whole per-request host cost at CPU
    proxy sizes). Compiles once per prefill bucket; slot / length /
    offset / sampling knobs are traced. ``with_row=True`` additionally
    returns the prefilled row and its last-position logits so the
    engine can donate them to the prefix store."""
    from tony_tpu.serve.slots import write_slot_row

    new_row, last = _prefill(model, params, prompt, length, offset, row)
    cache = write_slot_row(cache, new_row, slot)
    tok, key = _sample_rows(last, key[None],
                            jnp.asarray(temp, jnp.float32)[None],
                            jnp.asarray(top_k, jnp.int32)[None])
    if with_row:
        return cache, tok[0].astype(jnp.int32), key[0], new_row, last
    return cache, tok[0].astype(jnp.int32), key[0]


@jax.jit
def _sample_first(logits, temp, top_k, key):
    """The PAGED exact-hit admit: the stored pages are aliased into the
    slot's table host-side (a refcount bump — no device copy at all,
    vs the unpaged path's full ``write_slot_row``), so the only device
    work left is sampling the first continuation from the stored
    last-position logits with THIS request's knobs. One tiny dispatch
    over [1, V]."""
    tok, key = _sample_rows(logits, key[None],
                            jnp.asarray(temp, jnp.float32)[None],
                            jnp.asarray(top_k, jnp.int32)[None])
    return tok[0].astype(jnp.int32), key[0]


@functools.partial(jax.jit, static_argnames=("model",))
def _paged_prefill_admit(model, params, cache, window, positions, length,
                         table, temp, top_k, key):
    """The paged fused admit: a prefill is ONE multi-token per-slot
    window over the resident page pool — ``window`` [1, Lb] holds the
    (suffix of the) prompt right-padded to its bucket, ``positions``
    [1, Lb] its absolute positions (padding = -1, whose writes DROP —
    unlike the unpaged bucket, no junk is ever written past the
    prompt), ``table`` [1, max_pages] the slot's page table. K/V land
    straight in the slot's pages (no separate row + slot-copy), the
    last REAL position's logits feed the first-token sample. Returns
    ``(cache, token, rng, last_logits [1, V])`` — the logits go to the
    prefix store so the next exact hit skips everything."""
    cache, logits = multi_decode_step(model, params, cache, window,
                                      positions, page_table=table)
    last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1,
                                        axis=1)[:, 0]
    tok, key = _sample_rows(last, key[None],
                            jnp.asarray(temp, jnp.float32)[None],
                            jnp.asarray(top_k, jnp.int32)[None])
    return cache, tok[0].astype(jnp.int32), key[0], last


@functools.partial(jax.jit, static_argnames=("model",))
def _paged_prefill_chunk(model, params, cache, window, positions, table):
    """One INTERMEDIATE chunk of a chunked prefill: a multi-token
    window written straight into the slot's pages at absolute
    ``positions`` — ``_paged_prefill_admit`` minus the first-token
    sample (only the FINAL chunk holds the real last position, so
    sampling here would be junk work). Compiles once per chunk bucket
    x view span — and the chunk budget is quantized to the bucket
    grid, so in practice ONE chunk program serves a whole serving
    session."""
    cache, _ = multi_decode_step(model, params, cache, window,
                                 positions, page_table=table)
    return cache


@jax.jit
def _hit_admit(cache, row, slot, logits, temp, top_k, key):
    """Exact-prompt prefix hit: NO prefill at all — copy the stored row
    into ``slot`` and sample the first continuation from the stored
    last-position logits with THIS request's sampling knobs (so a hit
    behaves identically across greedy/temperature/seed mixes). One
    dispatch, everything traced."""
    from tony_tpu.serve.slots import write_slot_row

    cache = write_slot_row(cache, row, slot)
    tok, key = _sample_rows(logits, key[None],
                            jnp.asarray(temp, jnp.float32)[None],
                            jnp.asarray(top_k, jnp.int32)[None])
    return cache, tok[0].astype(jnp.int32), key[0]


def _row_nbytes(cache) -> int:
    """Bytes one slot's row costs in the prefix store: batched leaves
    contribute one slot's share, shared counters their whole (tiny)
    size — what ``read_slot_row`` of this cache would occupy."""
    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        ax = cache_batch_axis(path, leaf)
        total += nbytes // leaf.shape[ax] if ax is not None else nbytes
    return total


def _padded_pages(pages: list, sentinel: int | None = None) -> list:
    """A page-id list pow2-padded to its gather/scatter bucket — the
    ONE place the padding convention lives: gathers duplicate the last
    page (junk rows the consumer slices or the receiving scatter
    drops), scatters pad with the pool's ``n_pages`` sentinel (writes
    drop)."""
    n_pad = _bucket_pow2(max(1, len(pages)))
    fill = pages[-1] if sentinel is None else sentinel
    return list(pages) + [fill] * (n_pad - len(pages))


def _usable_prefix(off: int, n: int, max_len: int, minimum: int) -> int:
    """Largest usable seed length <= ``off`` for an ``n``-token prompt:
    the suffix's power-of-two bucket must still fit the cache
    (``off + bucket <= max_len`` — dynamic_update_slice would otherwise
    clamp the write start and corrupt earlier positions). Shrinking
    ``off`` grows the suffix (and possibly its bucket), so iterate;
    terminates because ``off`` strictly decreases, and 0 (full prefill)
    always fits."""
    while off > 0:
        lb = bucket_len(n - off, max_len, minimum)
        if off + lb <= max_len:
            return off
        off = max(0, max_len - lb)
    return 0


def _sample_rows(logits, rngs, temps, top_ks):
    """Per-row sampling with TRACED temperature/top-k — one compiled
    program serves every request mix. Greedy rows (temp == 0) take
    argmax; sampled rows apply a per-row top-k cut by rank (ties beyond
    rank k are dropped, vs sample_logits' static-k threshold keeping
    them — indistinguishable for continuous logits), then draw from
    their own rng. Returns (tokens, advanced rngs).

    GATED on the live mix (lax.cond, traced preds): an all-greedy batch
    — the serving default — skips the rng splits and both sort passes
    entirely (measured 0.89 -> 0.04 ms per step at CPU proxy sizes,
    most of the micro-step gap to generate()'s scan body); the top-k
    sorts additionally skip whenever no live SAMPLED row requests a cut
    — a greedy row's top_k is dead weight (the final where discards its
    draw), so it must not force the two full-vocab sorts on the whole
    batch. Greedy rows never consume rng, so a request's draws stay
    reproducible regardless of what it is co-scheduled with."""
    greedy = jnp.argmax(logits, axis=-1)

    def sampled(_):
        scaled = logits / jnp.maximum(temps[:, None], 1e-6)

        def topk_cut(x):
            order = jnp.argsort(-x, axis=-1)
            ranks = jnp.argsort(order, axis=-1)
            keep = (top_ks[:, None] <= 0) | (ranks < top_ks[:, None])
            return jnp.where(keep, x, -1e30)

        cut = jax.lax.cond(jnp.any((temps > 0.0) & (top_ks > 0)),
                           topk_cut, lambda x: x, scaled)
        pair = jax.vmap(lambda k: jax.random.split(k, 2))(rngs)
        drawn = jax.vmap(jax.random.categorical)(pair[:, 1], cut)
        return jnp.where(temps == 0.0, greedy, drawn), pair[:, 0]

    return jax.lax.cond(jnp.any(temps > 0.0), sampled,
                        lambda _: (greedy, rngs), None)


def _frozen_body(model, params, temps, top_ks, eos_ids: tuple):
    """The in-dispatch-EOS decode micro-step (ISSUE-13): the scan body
    shared by ``_decode_chunk`` (freeze mode) and ``_verify_chunk``'s
    fused continuation. Carry is ``(cache, tok, positions, rngs, done,
    rem)``; a row whose emitted token hit EOS — or whose remaining
    budget ``rem`` ran out — FREEZES: its later micro-steps write to
    the dropped sentinel position (no KV bytes land), take the greedy
    sampling path (no rng advance — a frozen sampled row must not
    move any draw chain), and re-emit the frozen token, so the host's
    trim walk degenerates to a consistency check and the trailing
    positions land as padding, not overshoot. A row that never
    freezes runs EXACTLY the pre-freeze body (every ``where`` is
    identity), which is what keeps chunk-invariance bitwise."""
    def body(carry, _):
        cache, tok, positions, rngs, done, rem = carry
        eff_pos = jnp.where(done, -1, positions)
        cache, last = single_decode_step(model, params, cache, tok,
                                         positions=eff_pos)
        nxt, rngs = _sample_rows(last, rngs,
                                 jnp.where(done, 0.0, temps), top_ks)
        nxt = jnp.where(done, tok, nxt.astype(jnp.int32))
        positions = jnp.where(done | (positions < 0), positions,
                              positions + 1)
        rem = jnp.where(done, rem, rem - 1)
        done = done | _is_eos(nxt, eos_ids) | (rem <= 0)
        return (cache, nxt, positions, rngs, done, rem), nxt

    return body


@functools.partial(jax.jit, static_argnames=("model", "n_steps",
                                             "eos_ids", "freeze"))
def _decode_chunk(model, params, cache, tok, positions, temps, top_ks,
                  rngs, rem=None, table=None, *, n_steps: int,
                  eos_ids: tuple = (), freeze: bool = False):
    """The resident serving step: ``n_steps`` decode micro-steps for
    EVERY slot as one lax.scan dispatch (empty slots compute garbage
    that nothing reads — the price of a never-recompiled static shape).
    Per-slot sampling and rng advance ride inside the scan; returns
    (cache, tokens [b, n_steps], rngs). ``n_steps`` is static (the
    scheduler quantizes it to powers of two, so at most
    log2(chunk_steps)+1 programs ever compile).

    ``freeze`` (the ISSUE-13 in-dispatch EOS mode, the engine default)
    threads a per-slot ``done`` flag + remaining budget ``rem`` [b]
    through the scan (``_frozen_body``): a slot that samples EOS or
    exhausts its budget mid-chunk stops writing K/V (sentinel
    position), stops advancing rng, and re-emits its final token — so
    ``chunk_steps`` can grow without the trailing positions becoming
    the ``overshoot`` waste bucket, and the host trim becomes a
    consistency check. ``eos_ids`` is static per engine (one compile).

    ``table`` [b, max_pages] switches to the paged cache layout — but
    NOT by gathering inside every micro-step: the slot view is
    gathered from the pools ONCE (``paged_view``), the whole scan runs
    the plain unpaged per-slot program against it (bitwise-identical
    math, and the gather cost amortizes over the chunk depth), and
    only the chunk's ``b x n_steps`` new K/V entries scatter back to
    their pages at the end (``paged_write_back``; a frozen row's
    unwritten tail positions copy their own gathered content back —
    an identity write). The table is fixed across the chunk, so the
    host pre-extends it to cover every position the chunk will write
    (engine ``_decode_round``)."""
    max_len = model.cfg.max_seq_len
    pool_cache, start = cache, positions
    if table is not None:
        cache = paged_view(cache, table, max_len)

    if freeze:
        body = _frozen_body(model, params, temps, top_ks, eos_ids)
        carry = (cache, tok, positions, rngs,
                 positions < 0, jnp.asarray(rem, jnp.int32))
    else:
        def body(carry, _):
            cache, tok, positions, rngs = carry
            cache, last = single_decode_step(model, params, cache, tok,
                                             positions=positions)
            nxt, rngs = _sample_rows(last, rngs, temps, top_ks)
            nxt = nxt.astype(jnp.int32)
            positions = jnp.where(positions >= 0, positions + 1,
                                  positions)
            return (cache, nxt, positions, rngs), nxt

        carry = (cache, tok, positions, rngs)
    if n_steps > 1:
        carry, toks = jax.lax.scan(body, carry, None, length=n_steps)
        toks = jnp.moveaxis(toks, 0, 1)  # [steps, b] -> [b, steps]
    else:
        carry, tok1 = body(carry, None)
        toks = tok1[:, None]
    cache, rngs = carry[0], carry[3]
    if table is not None:
        cache = paged_write_back(pool_cache, cache, table, start,
                                 n_steps, max_len)
    return cache, toks, rngs


@functools.partial(jax.jit, static_argnames=("model", "window",
                                             "n_steps", "eos_ids"))
def _verify_chunk(model, params, cache, toks, positions, draft_len,
                  temps, top_ks, rngs, rem=None, table=None, *,
                  window: int, n_steps: int = 0, eos_ids: tuple = ()):
    """The speculative verify dispatch: score ``window`` positions for
    EVERY slot in one batched multi-token pass (multi_decode_step) and
    judge each row's draft against its own greedy verdicts — the
    Leviathan et al. draft-and-verify step on the resident cache.

    Row layout: ``toks[i] = [last_token, draft_1..draft_d, pad...]``
    at ``positions[i] = [p, p+1, .., p+d, -1...]`` (``d`` =
    ``draft_len[i]``; padding writes drop, padding logits are junk).
    Returns ``(cache, emit [b, window], accepted [b], rngs)``:

    - ``emit[i, 0]`` is the token following ``last_token`` under the
      row's OWN sampling knobs (_sample_rows: argmax for greedy rows,
      a real draw advancing the rng once for sampled rows — exactly
      one advance per emitted token, so a sampled request's draw chain
      is identical to the chunked path's). Non-speculating rows
      consume only this.
    - ``emit[i, 1:]`` are greedy verdicts: ``emit[i, j]`` follows the
      window prefix through ``draft_j``.
    - ``accepted[i]`` = length of the leading run of draft tokens
      equal to the previous position's greedy verdict. The scheduler
      appends ``emit[i, :accepted[i] + 1]`` — accepted drafts plus the
      bonus verdict after them — and rewinds nothing: K/V written for
      rejected drafts sits beyond the slot's advanced length, invisible
      under per-row masked visibility and overwritten as the slot
      decodes on.

    ``window`` is static and power-of-two-plus-one bucketed, so at most
    log2(speculate_k)+1 verify programs ever compile. ``table``
    [b, max_pages] switches to the paged cache layout (pre-extended by
    the host to cover the window's writes).

    ``n_steps`` > 0 is the FUSED speculation round (ISSUE-13): the
    same dispatch (a) caps ``accepted`` at the first emitted stop
    token, so a mid-window EOS costs zero bonus-past-finish waste,
    and (b) runs ``n_steps`` ``_frozen_body`` decode micro-steps
    CONTINUING from each row's own bonus verdict — the chunk dispatch
    that used to follow every verify round rides inside it, so a
    speculating round costs ONE dispatch for accepted+1+n_steps
    tokens instead of two dispatches. Paged mode then works like the
    chunk path: ONE ``paged_view`` gather feeds both the window pass
    and the continuation scan, and ``paged_write_back`` returns the
    whole written span (positions the row never wrote copy their own
    gathered content back — identity). Returns ``(cache, emit,
    accepted, cont [b, n_steps], rngs)``."""
    max_len = model.cfg.max_seq_len
    pool_cache, start = cache, positions[:, 0]
    if n_steps > 0 and table is not None:
        cache = paged_view(cache, table, max_len)
        step_table = None
    else:
        step_table = table
    cache, logits = multi_decode_step(model, params, cache, toks,
                                      positions, page_table=step_table)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [b, w]
    tok0, rngs = _sample_rows(logits[:, 0], rngs, temps, top_ks)
    emit = jnp.concatenate([tok0[:, None].astype(jnp.int32),
                            greedy[:, 1:]], axis=1)
    j = jnp.arange(window - 1)[None, :]
    match = (toks[:, 1:] == greedy[:, :-1]) & (j < draft_len[:, None])
    accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1),
                       axis=1)
    if n_steps == 0:
        return cache, emit, accepted, rngs
    # EOS-capped acceptance: the host appends emit[:accepted + 1] and
    # stops at the first stop token — capping accepted AT that index
    # makes the device and host agree that nothing past it was ever
    # accepted (the "verify bonus past EOS" waste bucket goes to zero;
    # the consumed token run is unchanged, so outputs are identical)
    if eos_ids:
        idx = jnp.arange(window)[None, :]
        first_stop = jnp.min(jnp.where(_is_eos(emit, eos_ids), idx,
                                       window), axis=1)
        accepted = jnp.minimum(accepted, first_stop)
    # fused continuation: each row resumes from its own bonus verdict
    # at its own position, with the frozen-body discipline bounding
    # EOS/budget — live rows decode n_steps more real tokens in THIS
    # dispatch, so non-drafting co-tenants are never dragged to one
    # token per round (the old batch-drag-gate failure mode)
    rows = jnp.arange(toks.shape[0])
    bonus = emit[rows, accepted]
    live = start >= 0
    consumed = accepted + 1
    rem_c = jnp.where(live, jnp.asarray(rem, jnp.int32) - consumed, 0)
    done = ~live | _is_eos(bonus, eos_ids) | (rem_c <= 0)
    cont_pos = jnp.where(live, start + consumed, -1)
    body = _frozen_body(model, params, temps, top_ks, eos_ids)
    carry = (cache, bonus, cont_pos, rngs, done, rem_c)
    if n_steps > 1:
        carry, cont = jax.lax.scan(body, carry, None, length=n_steps)
        cont = jnp.moveaxis(cont, 0, 1)  # [steps, b] -> [b, steps]
    else:
        carry, c1 = body(carry, None)
        cont = c1[:, None]
    cache, rngs = carry[0], carry[3]
    if table is not None:
        cache = paged_write_back(pool_cache, cache, table, start,
                                 window + n_steps, max_len)
    return cache, emit, accepted, cont, rngs


class QueueFull(RuntimeError):
    """``submit()`` refused: the pending queue is at ``max_pending``.

    The typed backpressure signal — callers (the gateway's admission
    layer, the JSONL loop) translate it into 429/shedding instead of
    letting the queue grow without bound and OOMing the host."""


class PoolExhausted(RuntimeError):
    """``submit()`` refused: the request's worst-case KV-page need
    (prompt + clamped max_new_tokens) exceeds the ENTIRE page pool, so
    it could never be admitted — waiting would wedge the queue behind
    it forever. Deliberately not a ValueError: the gateway sheds it
    503 (capacity), not 400 (malformed) — resubmitting against a
    bigger pool is legitimate. Transient pressure (the pool is
    momentarily full of live requests) never raises: the request just
    stays pending until pages free."""


@dataclass
class Request:
    """One generation request. ``prompt`` is token ids; sampling knobs
    are per-request (greedy default). ``id`` is echoed on the Result
    (auto-assigned when None).

    The disaggregation fields (both paged-engine-only):
    ``prefill_only`` makes the engine STOP after prefill — the Result
    comes back ``finish_reason="handoff"`` carrying the prompt's page
    content + last-position logits instead of tokens (the prefill
    pool's half of a role-split fleet). ``handoff`` is the other half:
    a payload produced by a prefill_only run; admission scatters it
    into fresh pages, samples the first token from the carried logits
    with THIS request's knobs/seed, and decodes — token-exact vs a
    single engine doing both (the first-token draw and every decode
    step see bitwise the state the donor engine would have had).

    ``migrate`` (ISSUE-18) is the live-migration entry: a
    ``SessionSnapshot`` (or its wire doc) another engine froze
    mid-stream via ``extract_session``. Admission adopts the carried
    pages + sampler state and resumes decode from the exact position
    — no prefill, no first-token sample (every emitted token,
    including the one the next step feeds, already rode over)."""

    prompt: list
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    id: Any = None
    prefill_only: bool = False
    handoff: Any = None
    migrate: Any = None


@dataclass
class Result:
    """A finished request: ``tokens`` = generated ids (the EOS token,
    when hit, included as the last element); ``finish_reason`` is
    "eos" or "length". ``prefix_hit_tokens`` = prompt tokens seeded
    from the prefix store instead of prefilled; ``prefill_tokens_saved``
    = bucketed prefill work skipped (both 0 with the store off).
    ``drafted``/``accepted`` = speculative-decoding draft tokens this
    request sent through verify dispatches / had accepted (both 0 with
    speculation off or for sampled requests)."""

    id: Any
    prompt: list
    tokens: list
    finish_reason: str
    prefix_hit_tokens: int = 0
    prefill_tokens_saved: int = 0
    drafted: int = 0
    accepted: int = 0
    # disaggregation surfaces: ``prefill_chunks`` = prefill dispatches
    # this request's prompt took (>= 2 means chunked; 0 = pure prefix
    # hit); ``handoff`` (finish_reason "handoff" only) = the page
    # payload + last-position logits a prefill_only run produced
    prefill_chunks: int = 0
    handoff: Any = None

    @property
    def draft_hit_rate(self) -> float:
        """Fraction of drafted tokens the verify step accepted (0.0
        when the request never drafted)."""
        return self.accepted / self.drafted if self.drafted else 0.0


@dataclass
class _Live:
    request: Request
    generated: list = field(default_factory=list)
    prefix_hit_tokens: int = 0
    prefill_tokens_saved: int = 0
    drafted: int = 0
    accepted: int = 0
    prefill_chunks: int = 0


@dataclass
class _PrefillState:
    """A slot mid-CHUNKED-prefill: admitted (reservation + any prefix
    seed already in place), prompt written up to ``done``, not yet
    decoding. The slot is excluded from both the free list and the
    decode batch until the final chunk samples its first token."""

    request: Request
    done: int       # prompt tokens already written/seeded
    chunks: int     # prefill dispatches so far (>= 1)
    hit_tokens: int
    saved: int
    row: Any = None  # unpaged: the carried batch-1 suffix-prefill cache


class Server:
    """Slot-based continuous-batching server.

    ``submit()`` enqueues; ``step()`` runs one scheduler iteration
    (admit -> batched decode -> per-slot EOS/evict) and returns whatever
    finished; ``run()`` drives to completion as a generator. ``params``
    is the bare param tree (the ``generate()`` convention).

    eos_id follows generate(): an int (-1 = none) or a list/tuple
    (stop on any).

    Threading contract: ONE thread owns the decode loop (``step()`` /
    ``drain()`` / ``run()`` — the device cache and per-slot host arrays
    are single-writer), while ``submit()`` may be called from any
    thread: the pending queue is lock-protected, so a network front
    door can feed requests while the owner thread keeps stepping.
    ``max_pending`` bounds the queue; past it ``submit()`` raises
    ``QueueFull`` instead of growing without bound.

    ``paged`` (default on; ``False`` keeps the fixed-shape rows for
    A/B, sliding-window models auto-downgrade) stores the KV cache as
    block-granular PAGES (``kv_page_size`` tokens each, auto-sized
    when 0) in a ``kv_pages``-page pool (auto = the unpaged-equivalent
    ``batch_size * max_pages`` when 0) with per-slot page tables:
    HBM residency is bounded by actual tokens, admission reserves each
    request's worst case (no mid-stream preemption, pool pressure just
    delays admission; a request bigger than the whole pool sheds
    ``PoolExhausted``), and the prefix store shares pages
    copy-on-write — an exact hit costs one [1, V] sampling dispatch
    and donation is a refcount bump. Greedy outputs are token-exact
    vs the unpaged path (tests/test_paged.py pins the matrix).

    ``speculate_k`` > 0 turns on speculative decoding (prompt-lookup
    drafting + batched verify, module functions ``_propose_draft`` /
    ``_verify_chunk``): rounds where any greedy slot's n-gram lookup
    proposes a draft run ONE verify dispatch scoring up to k draft
    tokens per slot instead of a single micro-step — non-drafting and
    sampled slots ride the same dispatch at one token per round, so the
    batch never splits. Greedy outputs are token-for-token unchanged
    (acceptance compares drafts against the verify pass's own greedy
    verdicts; rejection is pointer arithmetic — junk K/V beyond the
    accepted length is invisible and overwritten). A per-slot
    acceptance EMA (decay ``SPEC_EMA_DECAY``, floor
    ``SPEC_EMA_DISABLE``) stops drafting for requests whose proposals
    keep getting rejected, so the worst case is the plain chunked path
    plus one host-side numpy scan per round.

    ``mesh`` (a ``jax.sharding.Mesh``, ISSUE-14) makes this replica a
    MULTI-CHIP tensor/expert-sharded engine: params place under the
    ``parallel.sharding`` serving preset (``shard_rules``, default
    "serve" — output-dim sharding with the row-parallel flip), KV page
    pools shard on the kv-head axis, and every dispatch runs GSPMD-
    partitioned with XLA-inserted ICI collectives — same dispatch
    count per token, no new host syncs, and byte-identical greedy +
    seeded streams vs mesh=1 (all cross-chip traffic is all-gather;
    every float reduction runs whole on one chip). Page tables and the
    free-list allocator stay host-side and unchanged; prefix-store
    entries, CoW pages, handoff payloads and host-tier spills become
    sharded pytrees transparently. The goodput ledger prices sharded
    dispatches PER CHIP (bytes/FLOPs over the shard counts against
    the single-chip roofline). ``decode_attention="flash"`` is
    refused (GSPMD cannot partition a pallas_call).
    """

    # speculative-decoding gate: a slot drafts while its acceptance EMA
    # (seeded at 1.0 on admit, updated a/d per verify round it drafted
    # in) stays >= SPEC_EMA_DISABLE; two-ish fully-rejected rounds shut
    # a hopeless slot up for the rest of its request
    SPEC_EMA_DECAY = 0.5
    SPEC_EMA_DISABLE = 0.25

    def __init__(self, model, params, *, batch_size: int = 4, eos_id=-1,
                 min_bucket: int = 16, chunk_steps: int = 8,
                 max_pending: int = 1024, prefix_cache_mb: float = 0.0,
                 prefix_donate: bool = True, speculate_k: int = 0,
                 fault_plan: FaultPlan | None = None,
                 timeline: bool = True, paged: bool | None = None,
                 kv_page_size: int = 0, kv_pages: int = 0,
                 hbm_gbps: float = 0.0, prefill_chunk_tokens: int = 0,
                 kv_host_mb: float = 0.0, in_dispatch_eos: bool = True,
                 mesh=None, shard_rules: str = "serve",
                 page_pool: PagePool | None = None,
                 serialize_dispatch: bool = False):
        if model.cfg.quantized:
            # nothing structural in the way — the q8 apply is the same
            # model.apply — but untested here; fail loud, not wrong
            raise NotImplementedError(
                "serve over int8 weight-only models is untested")
        if prefix_cache_mb > 0 and model.cfg.sliding_window:
            # correctness is fine (causal K/V reuse holds under a
            # window) but the windowed prefill slices differently-sized
            # spans for full vs suffix prefill, so bitwise greedy
            # parity — the store's contract — is unpinned; fail loud
            raise NotImplementedError(
                "prefix cache over sliding-window models is untested")
        if paged and model.cfg.sliding_window:
            # same precedent: the paged gather itself is window-agnostic
            # but bitwise greedy parity against the unpaged windowed
            # slice path is unpinned; explicit paged=True fails loud,
            # the None default (and the CLIs) downgrade to unpaged
            raise NotImplementedError(
                "paged KV cache over sliding-window models is untested")
        # SHARDED replica (ISSUE-14): with a ``mesh``, the param tree
        # and the KV page pools are placed as NamedShardings under the
        # parallel.sharding serving preset — params shard on their
        # output dims (the row-parallel flip keeps every float
        # reduction whole on one chip), KV pools shard on the kv-head
        # axis, and EVERY dispatch below runs GSPMD-partitioned with
        # XLA-inserted ICI collectives. The page tables, free-list
        # allocator and reservation ledger stay host-side and
        # unchanged (a page id means the same thing on every chip);
        # dispatch counts per token are identical to single-chip — no
        # new host syncs. Greedy AND seeded streams are byte-identical
        # to mesh=1 (tests/test_shard_serve.py pins the matrix).
        self.mesh = mesh
        self.shard_rules = shard_rules
        self.kv_shards = 1
        self._param_shardings = None
        if mesh is not None:
            if model.cfg.decode_attention == "flash":
                # GSPMD cannot partition a pallas_call: the kernel
                # would be silently all-gathered per step. Fail loud.
                raise NotImplementedError(
                    "sharded serving over the pallas flash-decode "
                    "kernel is untested; use decode_attention='einsum'")
            import dataclasses

            from tony_tpu.parallel.sharding import serving_shardings

            # re-cfg the model with the mesh + the replicate pins that
            # make sharded math reduction-order-identical (a distinct
            # static jit key, so sharded and unsharded servers in one
            # process never share a miscompiled program)
            model = model.__class__(dataclasses.replace(
                model.cfg, mesh=mesh, shard_activations=True))
            self._param_shardings = serving_shardings(mesh, params,
                                                      shard_rules)
            params = jax.device_put(params, self._param_shardings)
        self.model = model
        self.params = params
        # deterministic fault injection (serve/faults.py); None = off,
        # zero overhead. Hooked at the top of step() and before each
        # admission's prefill — the two places device work starts
        self.fault_plan = fault_plan
        self.eos_ids = normalize_eos_ids(eos_id)
        self.min_bucket = min_bucket
        # in-dispatch EOS/refill (ISSUE-13, default ON): the decode
        # chunk and the verify round carry a per-slot ``done`` flag so
        # a slot finishing mid-dispatch freezes instead of decoding
        # trimmed overshoot — chunk_steps can grow without feeding the
        # ``overshoot`` waste bucket, the speculation path fuses its
        # follow-up chunk into the verify dispatch, and the host trim
        # walk becomes a consistency check. False = the pre-ISSUE-13
        # behavior, kept as the bench/regression A/B control.
        self.in_dispatch_eos = bool(in_dispatch_eos)
        self.frozen_steps = 0  # decode/verify positions spent frozen
        #                        (re-emitting a finished slot's token);
        #                        they cost no KV writes and land in the
        #                        ledger's padding bucket, not overshoot
        self.freeze_faults = 0  # frozen-tail consistency violations
        #                         (must stay 0; the old host trim, as
        #                         a check)
        # upper bound on decode micro-steps fused into one dispatch;
        # 1 = token-at-a-time (lowest latency to each token, highest
        # per-token dispatch cost — the right setting for streaming)
        self.chunk_steps = max(1, chunk_steps)
        self.max_pending = max(1, max_pending)
        # paged KV (the PagedAttention idea on the TPU static-shape
        # path): cache leaves become [n_pages, page_size, ...] pools,
        # slots hold page tables, residency is bounded by actual tokens
        # instead of batch * max_seq_len, and the prefix store shares
        # pages copy-on-write instead of copying rows. Default ON
        # (except sliding-window); paged=False keeps the fixed-shape
        # rows for A/B.
        self.paged = (not model.cfg.sliding_window) if paged is None \
            else bool(paged)
        if page_pool is not None and not self.paged:
            raise ValueError("a shared page_pool needs the paged KV "
                             "cache")
        if self.paged:
            if page_pool is not None:
                # SHARED pool (ISSUE-18): a gateway-owned fleet pool
                # lent to every co-located engine — the pool keeps
                # device-tree ownership (SlotCache delegates), and
                # the pool's lock is the single-writer dispatch
                # discipline serialized below
                pool = page_pool
            else:
                ps = int(kv_page_size) or default_page_size(model.cfg)
                ps = max(1, min(ps, model.cfg.max_seq_len))
                max_pages = -(-model.cfg.max_seq_len // ps)
                # auto pool: the unpaged-equivalent footprint — every
                # slot can still hold a full-length sequence, so
                # capacity parity with the fixed-shape path is the
                # floor; explicit kv_pages grows the batch into the
                # same HBM or shrinks the footprint for short-sequence
                # traffic
                n_pages = int(kv_pages) or batch_size * max_pages
                # mesh: the pool allocates DIRECTLY under its kv-head
                # shardings (slots._alloc_sharded) — a dense-then-
                # reshard order would transiently hold the whole pool
                # on one chip and OOM exactly the configurations the
                # mesh unlocks
                pool = PagePool(model, params, n_pages, ps, mesh=mesh)
            self.slots = SlotCache(model, params, batch_size, pool=pool)
        else:
            self.slots = SlotCache(model, params, batch_size, mesh=mesh)
        # dispatch concurrency (ISSUE-19): every engine owns ITS OWN
        # scheduler lock — co-located engines on a shared pool no
        # longer serialize whole step() iterations through one
        # pool-wide writer. The shared device TREE is protected at a
        # finer grain instead: ``_tree_lock`` (the pool's lock when
        # shared, else this same per-engine lock — a free re-entrant
        # acquire) brackets each read-dispatch-reassign window, held
        # only while ENQUEUEING a dispatch, never across the host sync
        # — so two engines' device work overlaps while the tree-version
        # chain stays linear. ``serialize_dispatch=True`` restores the
        # old pool-wide single-writer discipline (whole steps under
        # pool.lock) as the measured A/B control for bench
        # extras.migrate's concurrent-pool arm.
        shared = self.paged and self.slots.pool.shared
        self.serialize_dispatch = bool(serialize_dispatch) and shared
        self._dispatch_lock = self.slots.pool.lock \
            if self.serialize_dispatch else threading.RLock()
        self._tree_lock = self.slots.pool.lock if shared \
            else self._dispatch_lock
        cache_leaves = jax.tree_util.tree_leaves(self.slots.cache)
        self._kv_bytes_total = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize for x in cache_leaves)
        self._kv_bytes_chip = self._kv_bytes_total
        if mesh is not None:
            # the cache (page pools, or fixed-shape rows) was ALLOCATED
            # under its kv-head shardings above; this block only does
            # the accounting — shard count + per-chip bytes — off the
            # same rule, so the two can never disagree. Host-side
            # tables and the allocator never see the difference.
            from tony_tpu.parallel.sharding import (kv_cache_shardings,
                                                    kv_shard_count,
                                                    tree_shard_bytes)

            csh = kv_cache_shardings(mesh, self.slots.cache)
            self.kv_shards = kv_shard_count(mesh, self.slots.cache)
            self._kv_bytes_chip = tree_shard_bytes(self.slots.cache, csh)
            if self.kv_shards == 1 and mesh.size > 1:
                log.warning(
                    "KV pools replicated on the %d-device mesh: the "
                    "tensor axis does not divide kv_heads=%d — params "
                    "still shard, KV capacity does not",
                    mesh.size, model.cfg.kv_heads)
        self.pending: deque[Request] = deque()
        self._pending_lock = threading.Lock()
        self._live: list[_Live | None] = [None] * batch_size
        self._ids = itertools.count()
        self.steps = 0       # decode dispatch DEPTH, summed (chunk k /
        #                      verify window — once per dispatch, not
        #                      per slot)
        self.dispatches = 0  # decode dispatches (chunk + verify)
        self.prefills = 0    # prefill dispatches (exact hits skip one)
        self.wasted_steps = 0  # PER-SLOT token positions decoded and
        #                       thrown away. With in-dispatch EOS on
        #                       (the default) only REJECTED DRAFT
        #                       positions remain — chunk overshoot and
        #                       verify bonus past a finish are frozen
        #                       in-dispatch (frozen_steps) instead of
        #                       decoded and trimmed. The legacy
        #                       in_dispatch_eos=False engine still
        #                       counts all three. Different unit from
        #                       `steps` — compare against emitted
        #                       tokens for utilization, the pairing
        #                       bench.py reports
        # per-dispatch timeline (obs/timeline.py): one record per
        # prefill / hit-admit / decode / verify dispatch with host-wall
        # duration and a first-call compile flag; False = off, for the
        # obs overhead A/B (bench extras.obs) — the layer itself is
        # cheap enough to stay on in production
        self.timeline = DispatchTimeline() if timeline else None
        self._compiled: set = set()  # (kind, shape-bucket) pairs seen
        # goodput attribution (obs/goodput.py): wall-clock anchor for
        # the ledger plus the analytic cost model that stamps
        # est_bytes/est_flops on every timeline record. The roofline
        # reference (peak HBM GB/s) comes from --hbm-gbps when given,
        # else the chip table; 0 on CPU — records still carry bytes,
        # utilization reports null.
        self._t0 = time.monotonic()
        self.hbm_gbps = float(hbm_gbps) if hbm_gbps > 0 \
            else detect_hbm_gbps()
        self.peak_flops = detect_peak_flops()
        leaves = jax.tree_util.tree_leaves(params)
        self._param_bytes_total = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize for x in leaves)
        param_count_total = sum(int(np.prod(x.shape)) for x in leaves)
        if mesh is not None:
            # per-chip param residency under the actual shardings
            # (replicated leaves count whole) — what one chip's HBM
            # holds and re-reads per decode micro-step
            from tony_tpu.parallel.sharding import (tree_shard_bytes,
                                                    tree_shard_count)

            self._param_bytes_chip = tree_shard_bytes(
                params, self._param_shardings)
            self._param_count_chip = tree_shard_count(
                params, self._param_shardings)
        else:
            self._param_bytes_chip = self._param_bytes_total
            self._param_count_chip = param_count_total
        self.cost = None
        if self.timeline is not None:
            cfg = model.cfg
            if self.paged:
                pool = self.slots.pool
                kv_tok = pool.page_nbytes / max(1, pool.page_size)
            else:
                kv_tok = _row_nbytes(self.slots.cache) \
                    / max(1, cfg.max_seq_len)
            head_dim = cfg.explicit_head_dim \
                or cfg.d_model // cfg.n_heads
            # sharded replicas price dispatches PER CHIP (the ISSUE-14
            # goodput rule): each chip reads its param shard and its
            # kv-head slice of the pools, so bytes/FLOPs divide by the
            # shard counts while hbm_gbps/peak_flops stay the SINGLE-
            # chip roofline — HBM-BW% stays a per-chip percentage
            # instead of reading >100% on a mesh. Attention work
            # splits with the kv pools (kv_shards divides kv_heads
            # divides n_heads); a replicated-pool fallback prices
            # attention unsharded, conservatively.
            self.cost = CostModel(
                param_bytes=self._param_bytes_chip,
                param_count=self._param_count_chip,
                kv_token_bytes=kv_tok / max(1, self.kv_shards),
                n_heads=cfg.n_heads // max(1, self.kv_shards),
                head_dim=head_dim, vocab_size=cfg.vocab_size,
                hbm_gbps=self.hbm_gbps, peak_flops=self.peak_flops)
        # speculative decoding (0 = off: zero overhead, no new programs)
        self.speculate_k = max(0, int(speculate_k))
        self._spec_ema = np.ones(batch_size, np.float64)
        self.spec_rounds = 0    # verify dispatches run
        self.spec_drafted = 0   # draft tokens sent through verify
        self.spec_accepted = 0  # draft tokens accepted
        # prefix KV reuse (serve/prefix.py); 0 MB = off, zero overhead.
        # Paged engines get a POOL-BACKED store: entries are page
        # references (refcounted, copy-on-write), not copied rows
        self.prefix = PrefixStore(
            int(prefix_cache_mb * (1 << 20)),
            pool=self.slots.pool if self.paged else None) \
            if prefix_cache_mb > 0 else None
        self.prefix_donate = prefix_donate
        self.prefix_lookups = 0       # admits that consulted the store
        self.prefix_hits = 0          # admits seeded >= 1 cached token
        self.prefix_hit_tokens = 0    # prompt tokens seeded, total
        self.prefill_tokens_saved = 0  # bucketed prefill work skipped
        self._row_nbytes = 0 if self.paged \
            else _row_nbytes(self.slots.cache)
        # the smallest useful entry: unpaged = one cache row + its
        # [1, V] fp32 logits; paged = one PAGE + the logits
        entry_nbytes = (self.slots.pool.page_nbytes if self.paged
                        else self._row_nbytes) + 4 * model.cfg.vocab_size
        if self.prefix is not None \
                and entry_nbytes > self.prefix.budget_bytes:
            # a budget that cannot hold even ONE entry would reject
            # every insert while still paying the row-returning prefill
            # variant per admit — pure overhead, so turn it off loudly
            log.warning(
                "prefix cache disabled: one cached entry needs %.1f MB, "
                "budget is %.1f MB (raise --prefix-cache-mb)",
                entry_nbytes / (1 << 20), prefix_cache_mb)
            self.prefix = None
        # chunked prefill (ISSUE-12): bound how many prompt tokens one
        # admission dispatch may consume; long prompts prefill in
        # chunks interleaved between decode rounds, so a 30k-token
        # prompt stops holding co-tenants' decode hostage for one
        # monolithic prefill. Quantized DOWN to the bucket grid
        # (min_bucket * 2^k) so intermediate chunk windows are
        # pad-free — on the unpaged path, bucket-tail junk between
        # chunks would otherwise need overwrite proofs per geometry.
        # 0 = off (the old monolithic behavior).
        chunk_budget = max(0, int(prefill_chunk_tokens))
        if chunk_budget:
            b = min_bucket
            while b * 2 <= chunk_budget:
                b *= 2
            chunk_budget = min(b, model.cfg.max_seq_len)
        self.prefill_chunk = chunk_budget
        self._prefilling: dict[int, _PrefillState] = {}
        self.prefill_chunk_dispatches = 0  # chunk dispatches run
        self.prefill_chunked = 0           # requests that took >1 chunk
        self.handoffs_out = 0  # prefill_only requests handed off
        self.handoffs_in = 0   # handoff admissions (decode pool)
        # live session migration (ISSUE-18)
        self.migrations_out = 0      # sessions frozen + extracted here
        self.migrations_in = 0       # sessions adopted + resumed here
        self.migrations_local = 0    # extracts as zero-copy owner swap
        self.migrations_remote = 0   # extracts as gathered content
        self.migrate_pages_moved = 0  # pages whose CONTENT moved
        self.migrate_bytes_avoided = 0  # bytes owner swaps did NOT
        #                                 move (migration + shared-pool
        #                                 handoff aliasing)
        self.migrate_freeze_resume_ms = 0.0  # summed freeze->resume
        #                                      wall ms (mean = / in)
        # prefix-delta wire migration (ISSUE-19)
        self.migrate_bytes_wire = 0  # page bytes that actually crossed
        #                              the wire INTO this engine
        #                              (adopter-side; full docs count n
        #                              pages, delta docs n - k)
        self.migrate_delta_in = 0    # adoptions that reconstructed the
        #                              prefix from this engine's own
        #                              store pages
        # prefix entries pinned (refcount held) between a delta doc's
        # submit-time check and its admission — eviction between the
        # two would free the very pages the adopt aliases. Keyed by
        # request id; released at admit, on post-check submit failure,
        # and on reset().
        self._migrate_pins: dict = {}
        self._cache_treedef = jax.tree_util.tree_structure(
            self.slots.cache)
        # (flat leaf index, page axis) of the first paged leaf: lets
        # submit() read a WIRE payload's page count straight off its
        # carried shapes, before any decoding
        self._payload_leaf_spec = None
        if self.paged:
            flat = jax.tree_util.tree_flatten_with_path(
                self.slots.cache)[0]
            for i, (path, leaf) in enumerate(flat):
                ax = cache_batch_axis(path, leaf)
                if ax is not None:
                    self._payload_leaf_spec = (i, ax)
                    break
        # host-RAM page tier (serve/tier.py): evicted prefix-store
        # entries spill device->host instead of vanishing, and page
        # back in on a prefix hit — million-session reuse bounded by
        # host RAM, not HBM. Needs page-granular state AND a device
        # store to feed it, so both are hard requirements.
        self.host_tier = None
        if kv_host_mb > 0:
            if not self.paged or self.prefix is None:
                raise ValueError(
                    "kv_host_mb needs the paged KV cache and a prefix "
                    "store (prefix_cache_mb > 0): the tier holds "
                    "evicted prefix-store pages")
            self.host_tier = HostPageTier(int(kv_host_mb * (1 << 20)))
            self.prefix.on_evict = self._spill_entry

    # ----------------------------------------------------- observability

    def _record_dispatch(self, kind: str, t0: float, dur_ms: float,
                         occ: int, bucket: int, tokens: int, key_,
                         *, request_id=None, tags: dict | None = None,
                         work: int = 0, fed: int = 0,
                         rejected: int = 0,
                         est: tuple = (0.0, 0.0)) -> None:
        """One timeline record, goodput-stamped: position accounting
        (work/fed/rejected — the ledger's exact duration split) plus
        the cost model's bytes/FLOPs estimate, with per-dispatch
        HBM-BW% / MFU tags when a roofline reference is known."""
        tags = tags or {}
        est_bytes, est_flops = est
        if self.cost is not None and est_bytes:
            bw, mfu = self.cost.utilization(est_bytes, est_flops,
                                            dur_ms)
            if bw is not None:
                tags["hbm_bw_pct"] = bw
            if mfu is not None:
                tags["mfu_pct"] = mfu
        self.timeline.record(DispatchRecord(
            kind, t0, dur_ms, occ, bucket, tokens,
            key_ not in self._compiled, request_id=request_id,
            tags=tags, work=work, fed=fed, rejected=rejected,
            est_bytes=est_bytes, est_flops=est_flops))
        self._compiled.add(key_)

    def goodput(self) -> dict | None:
        """The per-replica goodput ledger (obs/goodput.py): this
        engine's wall clock decomposed into useful/compile/padding/
        overshoot/spec-rejected/idle bucket fractions that sum to
        <= 1.0, with per-kind HBM-BW%/MFU when the roofline reference
        is known. None with the timeline off (no data to attribute)."""
        if self.timeline is None:
            return None
        wall_ms = (time.monotonic() - self._t0) * 1e3
        return ledger(self.timeline.summary(), wall_ms,
                      hbm_gbps=self.hbm_gbps,
                      peak_flops=self.peak_flops)

    def mesh_info(self) -> dict | None:
        """Sharded-replica topology + per-chip residency (None on a
        single-chip engine): mesh axes, how many ways the KV pools
        split, and the per-chip vs total param/KV bytes — the numbers
        behind /stats ``engine.mesh`` and the capacity-unlock math
        (a model whose total footprint exceeds one chip serves when
        the per-chip numbers fit)."""
        if self.mesh is None:
            return None
        return {
            "devices": int(self.mesh.size),
            "axes": {str(k): int(v) for k, v in self.mesh.shape.items()
                     if int(v) > 1},
            "preset": self.shard_rules,
            "kv_shards": int(self.kv_shards),
            "param_bytes_total": int(self._param_bytes_total),
            "param_bytes_per_chip": int(self._param_bytes_chip),
            "kv_bytes_total": int(self._kv_bytes_total),
            "kv_bytes_per_chip": int(self._kv_bytes_chip),
        }

    # ------------------------------------------------------------ intake

    def submit(self, request: Request):
        """Enqueue a request; returns its id. Rejects prompts the cache
        cannot hold; clamps max_new_tokens to the remaining capacity
        (the generate() overflow contract, per slot). Raises
        ``QueueFull`` past ``max_pending`` queued requests — the
        caller's backpressure signal. Safe to call from any thread."""
        p = list(request.prompt)
        max_len = self.model.cfg.max_seq_len
        if not p:
            raise ValueError("empty prompt")
        if len(p) >= max_len:
            raise ValueError(
                f"prompt ({len(p)}) leaves no room for generation in "
                f"max_seq_len ({max_len})")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.prefill_only and request.handoff is not None:
            raise ValueError("prefill_only and handoff are the two "
                             "HALVES of a disaggregated request — one "
                             "request cannot be both")
        if request.migrate is not None \
                and (request.prefill_only or request.handoff is not None):
            raise ValueError("a migrated session is already past "
                             "prefill — it cannot also be a "
                             "prefill_only/handoff half")
        if (request.prefill_only or request.handoff is not None
                or request.migrate is not None) and not self.paged:
            raise ValueError(
                "prefill/decode disaggregation needs the paged KV "
                "cache (the handoff unit is a page list)")
        if request.id is None:
            request.id = next(self._ids)
        if request.migrate is not None:
            # geometry + continuity checked HERE, where a mismatch is
            # one request's clean 400 refusal instead of a whole-
            # replica admission crash (the handoff precedent below).
            # Needs the id assigned above: a delta doc's check PINS a
            # prefix entry keyed by it.
            self._check_migrate(request, p)
        if request.handoff is not None:
            if int(request.handoff["n_tokens"]) != len(p):
                raise ValueError(
                    f"handoff payload covers "
                    f"{request.handoff['n_tokens']} tokens, prompt "
                    f"has {len(p)}")
            # geometry checked HERE, where a mismatch is one request's
            # clean refusal (the gateway sheds it 400): discovered at
            # admission inside step() it would instead fail the whole
            # replica and cascade the crash-reset through every decode
            # replica the failover retries
            self._check_handoff_geometry(request.handoff, len(p))
            if "page_ids" in request.handoff \
                    and request.handoff.get("pool") \
                    is not self.slots.pool:
                raise ValueError(
                    "an owner-swap handoff carries page ids in a "
                    "shared pool this engine does not hold — gather "
                    "it to wire form to cross pools")
        request.max_new_tokens = min(request.max_new_tokens,
                                     max_len - len(p))
        try:
            if self.paged:
                pool = self.slots.pool
                # a prefill_only request never decodes here: its worst
                # case is the prompt's pages alone (the decode pool
                # pays for the generation budget)
                life = len(p) if request.prefill_only \
                    else len(p) + request.max_new_tokens
                worst = -(-life // pool.page_size)
                if worst > pool.n_pages:
                    # could NEVER be admitted — shedding now (503 at
                    # the gateway) beats wedging the queue head forever
                    raise PoolExhausted(
                        f"request needs {worst} KV pages worst-case, "
                        f"the pool holds {pool.n_pages} (raise "
                        "--kv-pages or lower max_new_tokens)")
            with self._pending_lock:
                if len(self.pending) >= self.max_pending:
                    raise QueueFull(
                        f"pending queue at "
                        f"max_pending={self.max_pending}")
                self.pending.append(request)
        except BaseException:
            # a refusal after _check_migrate pinned a prefix entry
            # must not strand the pin (the request never reaches
            # admission, where the pin is consumed)
            self._release_migrate_pin(request.id)
            raise
        return request.id

    def _release_migrate_pin(self, rid) -> None:
        entry = self._migrate_pins.pop(rid, None)
        if entry is not None and self.prefix is not None:
            self.prefix.release(entry)

    @property
    def n_pending(self) -> int:
        return len(self.pending)

    @property
    def n_active(self) -> int:
        # mid-chunked-prefill slots count: they hold a request the
        # engine is working on (a busy/done signal that ignored them
        # would let a front door idle out a half-prefilled prompt)
        return self.slots.n_active + len(self._prefilling)

    @property
    def n_prefilling(self) -> int:
        return len(self._prefilling)

    @property
    def done(self) -> bool:
        return not self.pending and self.slots.n_active == 0 \
            and not self._prefilling

    def _free_slots(self) -> list[int]:
        """Slots admittable RIGHT NOW: free on the device AND not
        parked mid-chunked-prefill."""
        return [i for i in self.slots.free_slots()
                if i not in self._prefilling]

    def prefix_match_len(self, tokens) -> int:
        """Longest prompt prefix this engine could seed without
        prefill work — the gateway's prefix-affinity routing signal.
        Device store and host tier both count (a page-in is still far
        cheaper than a re-prefill); no counters move, so a routing
        probe cannot skew admission hit rates."""
        n = self.prefix.match_len(tokens) if self.prefix is not None \
            else 0
        if self.host_tier is not None:
            n = max(n, self.host_tier.match_len(tokens))
        return n

    def prefix_summary(self, max_items: int = 512) -> list:
        """Bounded ``[[n_tokens, crc32], ...]`` summary of every
        prefix this replica could seed (device store + host tier,
        deduplicated) — shipped on the agent heartbeat so the
        gateway's prefix-affinity probe can score a REMOTE replica
        (``serve.prefix.summary_match_len``) instead of assuming 0."""
        out: list = []
        seen: set = set()
        for store in (self.prefix, self.host_tier):
            if store is None:
                continue
            for ln, crc in store.summary(max_items):
                if (ln, crc) not in seen:
                    seen.add((ln, crc))
                    out.append([ln, crc])
        return out[:max_items]

    # --------------------------------------------------------- scheduling

    def _admit_one(self, req: Request, finished: list) -> bool:
        """Prefill ``req`` into a free slot (prefill + slot copy +
        first-token sample fused into one dispatch) — or finish it on
        the spot when the FIRST token already ends it (EOS, or a budget
        of one): no slot is burned on a request with nothing to decode.
        Returns False (paged engines only) when the page pool cannot
        grant the request's reservation right now — the caller requeues
        it and stops admitting until pages free.

        With the prefix store on, the prompt's longest cached prefix is
        looked up first: an exact-prompt hit (stored logits available)
        skips prefill entirely — one row-copy + first-token-sample
        dispatch; a partial hit seeds the slot from the stored row and
        prefills only the bucketed SUFFIX at a position offset. Either
        way the freshly covered prompt is (re)inserted so the next
        sharer hits."""
        if self.paged:
            return self._admit_one_paged(req, finished)
        if self.fault_plan is not None:
            self.fault_plan.on_admit(req.id)
        s = self.slots
        p = np.asarray(req.prompt, np.int32)
        max_len = self.model.cfg.max_seq_len
        slot = self._free_slots()[0]
        t0 = time.monotonic()  # timeline: the whole admit (lookup +
        occ = s.n_active       # dispatch + first-token sync)
        off, entry = 0, None
        lookup_ms = None
        if self.prefix is not None:
            self.prefix_lookups += 1
            off, entry = self.prefix.acquire(p)
            lookup_ms = (time.monotonic() - t0) * 1e3
        full_bucket = bucket_len(len(p), max_len, self.min_bucket)
        hit_tokens = saved = 0
        d_kind, d_bucket = "prefill", full_bucket
        try:
            if entry is not None and off == len(p) \
                    and len(entry.tokens) == len(p) \
                    and entry.logits is not None:
                # exact hit: the entry covers EXACTLY this prompt, with
                # its last-position logits — zero prefill work. (A
                # LONGER entry can also match the full prompt, but its
                # logits sit at the wrong position — partial path.)
                cache, tok, key = _hit_admit(
                    s.cache, entry.row, jnp.int32(slot), entry.logits,
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jax.random.PRNGKey(req.seed))
                hit_tokens, saved = len(p), full_bucket
                d_kind, d_bucket = "hit_admit", 0
            else:
                if entry is not None:
                    # partial hit (or full-prompt match against a
                    # longer/logits-less entry): seed at most len(p)-1
                    # tokens so >= 1 real token remains to prefill the
                    # first-continuation logits from
                    off = _usable_prefix(min(off, len(p) - 1), len(p),
                                         max_len, self.min_bucket)
                    if off <= 0:
                        self.prefix.release(entry)
                        entry = None
                suffix = p[off:]
                if self.prefill_chunk \
                        and len(suffix) > self.prefill_chunk:
                    # chunked admission: dispatch only the FIRST chunk
                    # (a suffix prefill into a carried batch-1 row —
                    # the PR-3 offset machinery) and park the slot
                    # mid-prefill; step() advances one chunk per
                    # iteration between decode rounds
                    take = self.prefill_chunk  # == its own bucket
                    window = np.asarray(suffix[:take])[None, :]
                    row, _ = _prefill(
                        self.model, self.params, jnp.asarray(window),
                        jnp.int32(take),
                        jnp.int32(off) if self.prefix is not None
                        else None,
                        entry.row if entry is not None else None)
                    self.prefills += 1
                    self.prefill_chunk_dispatches += 1
                    if entry is not None:
                        hit_tokens = off
                        saved = full_bucket - bucket_len(
                            len(suffix), max_len, self.min_bucket)
                        self.prefix_hits += 1
                        self.prefix_hit_tokens += hit_tokens
                        self.prefill_tokens_saved += saved
                    if self.timeline is not None:
                        jax.block_until_ready(row)  # close the record
                        tags = {"prompt_len": len(p), "chunk": 1}
                        if off:
                            tags["offset"] = int(off)
                        self._record_dispatch(
                            "prefill_chunk", t0,
                            (time.monotonic() - t0) * 1e3, occ, take,
                            0, ("prefill_chunk", take),
                            request_id=req.id, tags=tags, work=take,
                            fed=take, est=self.cost.prefill(take, off))
                    self._prefilling[slot] = _PrefillState(
                        req, off + take, 1, hit_tokens, saved, row=row)
                    return True
                lb = bucket_len(len(suffix), max_len, self.min_bucket)
                padded = np.zeros((1, lb), np.int32)
                padded[0, :len(suffix)] = suffix
                out = _prefill_admit(
                    self.model, self.params, s.cache,
                    jnp.asarray(padded), jnp.int32(len(suffix)),
                    jnp.int32(slot), jnp.float32(req.temperature),
                    jnp.int32(req.top_k), jax.random.PRNGKey(req.seed),
                    jnp.int32(off) if self.prefix is not None else None,
                    entry.row if entry is not None else None,
                    with_row=self.prefix is not None)
                self.prefills += 1
                d_bucket = lb
                if self.prefix is not None:
                    cache, tok, key, row, last = out
                    self.prefix.insert(p, row, last)
                else:
                    cache, tok, key = out
                if entry is not None:
                    hit_tokens, saved = off, full_bucket - lb
        finally:
            if entry is not None:
                self.prefix.release(entry)
        if hit_tokens:
            self.prefix_hits += 1
            self.prefix_hit_tokens += hit_tokens
            self.prefill_tokens_saved += saved
        tok = int(tok)  # host sync: the admit dispatch is done here
        if self.timeline is not None:
            tags = {"prompt_len": len(p)}
            if lookup_ms is not None:
                tags["lookup_ms"] = round(lookup_ms, 3)
            if hit_tokens:
                tags["prefix_hit_tokens"] = hit_tokens
            if off:
                tags["offset"] = int(off)
            if d_kind == "hit_admit":
                work = fed = 1
                est = self.cost.hit_admit(self._row_nbytes)
            else:
                work, fed = d_bucket, len(p) - off
                est = self.cost.prefill(d_bucket, off)
            self._record_dispatch(
                d_kind, t0, (time.monotonic() - t0) * 1e3, occ,
                d_bucket, 1, (d_kind, d_bucket), request_id=req.id,
                tags=tags, work=work, fed=fed, est=est)
        chunks = 0 if d_kind == "hit_admit" else 1
        if tok in self.eos_ids or req.max_new_tokens == 1:
            # the slot row was written but never armed — the next admit
            # simply overwrites it
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason, hit_tokens, saved,
                                   prefill_chunks=chunks))
            s.cache = cache
            return True
        s.cache = cache
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0  # new tenant: drafting re-enabled
        self._live[slot] = _Live(req, [tok], hit_tokens, saved,
                                 prefill_chunks=chunks)
        return True

    def _admit_one_paged(self, req: Request, finished: list) -> bool:
        """The paged admission path. Ordering: (1) prefix lookup — the
        reservation size depends on how many pages the prompt can
        ALIAS; (2) reserve the worst-case PRIVATE page need (prompt +
        clamped budget, minus aliased pages, plus one for a
        copy-on-write fork when the seed boundary falls mid-page),
        squeezing LRU prefix-store entries when the pool is tight; on
        failure the request stays pending — no preemption, ever:
        ``free >= reserved`` means an admitted request can always
        allocate its way to its budget; (3) seed the slot's table by
        SHARING the entry's pages (refcount bumps; the boundary page is
        forked on device) — an exact hit's only other device work is
        sampling the first token from the stored logits (the
        ``cow_admit`` dispatch kind: NOT a prefill, and the timeline
        must not count it as one); a partial hit or miss prefills the
        bucketed suffix as one multi-token window writing straight
        into the slot's pages (no row copy — the unpaged path's
        ``write_slot_row`` admission copies are gone)."""
        if req.migrate is not None:
            return self._admit_migrate(req, finished)
        if req.handoff is not None:
            return self._admit_handoff(req, finished)
        s = self.slots
        pool = s.pool
        ps = pool.page_size
        p = np.asarray(req.prompt, np.int32)
        max_len = self.model.cfg.max_seq_len
        slot = self._free_slots()[0]
        t0 = time.monotonic()  # timeline: the whole admit
        occ = s.n_active
        off, entry = 0, None
        lookup_ms = None
        if self.prefix is not None:
            self.prefix_lookups += 1
            off, entry = self.prefix.acquire(p)
            if self.host_tier is not None:
                # the host tier may hold a LONGER prefix than the
                # device store: restore it into the pool + store so
                # the admission below hits it (host->device page-in)
                off, entry = self._maybe_page_in(p, off, entry)
            lookup_ms = (time.monotonic() - t0) * 1e3
        full_bucket = bucket_len(len(p), max_len, self.min_bucket)
        exact = (entry is not None and off == len(p)
                 and len(entry.tokens) == len(p)
                 and entry.logits is not None)
        if exact and req.prefill_only:
            # the fleet hot-prompt fast path: the whole prompt's pages
            # are already resident with their logits — no reservation,
            # no writes, no sampling: gather the content and hand off
            try:
                if self.fault_plan is not None:
                    self.fault_plan.on_admit(req.id)
                self._finish_handoff(req, entry.pages, len(p),
                                     entry.logits, finished,
                                     hit_tokens=len(p),
                                     saved=full_bucket, chunks=0)
            finally:
                self.prefix.release(entry)
            self.prefix_hits += 1
            self.prefix_hit_tokens += len(p)
            self.prefill_tokens_saved += full_bucket
            return True
        if not exact and entry is not None:
            # partial hit (or full-prompt match against a longer /
            # logits-less entry): seed at most len(p)-1 tokens so >= 1
            # real token remains to prefill the first-continuation
            # logits from. No bucket-overflow shrink needed here: the
            # paged window writes by absolute position and its padding
            # DROPS, so any offset alignment is safe.
            off = min(off, len(p) - 1)
            if off <= 0:
                self.prefix.release(entry)
                off, entry = 0, None
        seed = len(p) if exact else off
        # prefill_only reserves the PROMPT's pages only — the decode
        # pool pays for the generation budget (submit() sized the
        # PoolExhausted check the same way)
        budget_end = len(p) if req.prefill_only \
            else len(p) + req.max_new_tokens  # submit() clamped
        worst = -(-budget_end // ps)     # ceil: pages for the whole life
        n_alias = -(-seed // ps)         # pages the entry donates
        fork = 1 if seed % ps else 0     # mid-page boundary: CoW copy
        need = worst - n_alias + fork
        granted = pool.reserve(need)
        while not granted and self.prefix is not None \
                and self.prefix.evict_one():
            granted = pool.reserve(need)
        if not granted:
            # transient exhaustion: live slots still hold the pages.
            # Undo the lookup (the retry repeats it) and stay pending —
            # submit() guarantees need <= n_pages, so slots finishing
            # always unblocks this.
            if entry is not None:
                self.prefix.release(entry)
            if self.prefix is not None:
                self.prefix_lookups -= 1
            return False
        if self.fault_plan is not None:
            # after the capacity check: a requeued request must not
            # burn fault-injection triggers on every retry. Guarded:
            # the reservation is not yet attached to the slot (that
            # happens in seed_pages, after which reset()'s evicts
            # reclaim it), so an injected crash here must hand it back
            # or it leaks past the replica's recovery reset
            try:
                self.fault_plan.on_admit(req.id)
            except BaseException:
                pool.cancel(need)
                if entry is not None:
                    self.prefix.release(entry)
                raise
        hit_tokens = saved = 0
        d_kind, d_bucket = "prefill", full_bucket
        forked = False
        try:
            forked = s.seed_pages(
                slot, entry.pages if entry is not None else [], seed,
                need)
            if not exact and self.prefill_chunk \
                    and len(p) - off > self.prefill_chunk:
                # chunked admission: the reservation and any prefix
                # seed are in place; dispatch the FIRST chunk straight
                # into the slot's pages and park the slot mid-prefill
                # (step() advances one chunk per iteration between
                # decode rounds)
                if entry is not None:
                    hit_tokens = off
                    saved = full_bucket - bucket_len(
                        len(p) - off, max_len, self.min_bucket)
                    self.prefix_hits += 1
                    self.prefix_hit_tokens += hit_tokens
                    self.prefill_tokens_saved += saved
                st = _PrefillState(req, off, 0, hit_tokens, saved)
                self._prefilling[slot] = st
                self._prefill_chunk_paged(slot, st, t0=t0, occ=occ,
                                          forked=forked)
                return True
            if exact:
                # the aliasing admit: pages shared host-side, one
                # [1, V] sampling dispatch — near-free, and bytes
                # moved are the forked page (if any) instead of the
                # unpaged path's whole cache row
                tok, key = _sample_first(
                    entry.logits, jnp.float32(req.temperature),
                    jnp.int32(req.top_k), jax.random.PRNGKey(req.seed))
                hit_tokens, saved = len(p), full_bucket
                d_kind, d_bucket = "cow_admit", 0
                view_tokens = 0
            else:
                suffix = p[off:]
                lb = bucket_len(len(suffix), max_len, self.min_bucket)
                s.ensure_pages(slot, len(p))
                window = np.zeros((1, lb), np.int32)
                window[0, :len(suffix)] = suffix
                positions = np.full((1, lb), -1, np.int32)
                positions[0, :len(suffix)] = \
                    off + np.arange(len(suffix), dtype=np.int32)
                # column-sliced to the prompt's page bucket: the
                # prefill window's gather + attention span is O(prompt
                # bucket), not O(max_seq_len)
                cols = min(_bucket_pow2(-(-len(p) // ps)), s.max_pages)
                view_tokens = cols * ps
                # read-dispatch-reassign window on the (possibly
                # shared) tree — enqueue only; the host sync below
                # runs outside the lock
                with self._tree_lock:
                    cache, tok, key, last = _paged_prefill_admit(
                        self.model, self.params, s.cache,
                        jnp.asarray(window), jnp.asarray(positions),
                        jnp.int32(len(suffix)),
                        jnp.asarray(s.page_table[slot:slot + 1, :cols]),
                        jnp.float32(req.temperature),
                        jnp.int32(req.top_k),
                        jax.random.PRNGKey(req.seed))
                    s.cache = cache
                self.prefills += 1
                d_bucket = lb
                if self.prefix is not None:
                    # pin the freshly covered prompt: a refcount bump
                    # on the slot's own pages plus the stored logits —
                    # the next exact sharer pays the cow_admit path
                    self.prefix.insert(p, pages=s.slot_pages(slot,
                                                             len(p)),
                                       logits=last)
                if entry is not None:
                    hit_tokens, saved = off, full_bucket - lb
        finally:
            if entry is not None:
                self.prefix.release(entry)
        if hit_tokens:
            self.prefix_hits += 1
            self.prefix_hit_tokens += hit_tokens
            self.prefill_tokens_saved += saved
        tok = int(tok)  # host sync: the admit dispatch is done here
        if self.timeline is not None:
            tags = {"prompt_len": len(p)}
            if lookup_ms is not None:
                tags["lookup_ms"] = round(lookup_ms, 3)
            if hit_tokens:
                tags["prefix_hit_tokens"] = hit_tokens
            if off and not exact:
                tags["offset"] = int(off)
            if forked:
                tags["cow_fork"] = True
            if view_tokens:
                tags["view_tokens"] = view_tokens
            if d_kind == "cow_admit":
                work = fed = 1
                est = self.cost.cow_admit(
                    pool.page_nbytes if forked else 0)
            else:
                work, fed = d_bucket, len(p) - off
                est = self.cost.prefill(d_bucket, off, view_tokens)
            # the view span is a second program-shape knob in paged
            # mode: the compile key must carry it or a recompile at a
            # new span would be mislabeled steady
            self._record_dispatch(
                d_kind, t0, (time.monotonic() - t0) * 1e3, occ,
                d_bucket, 1, (d_kind, d_bucket, view_tokens),
                request_id=req.id, tags=tags, work=work, fed=fed,
                est=est)
        chunks = 0 if d_kind == "cow_admit" else 1
        if req.prefill_only:
            # the prefill pool's exit: pages + last-position logits
            # hand off to a decode replica instead of arming the slot
            self._finish_handoff(req, s.slot_pages(slot, len(p)),
                                 len(p), last, finished,
                                 hit_tokens=hit_tokens, saved=saved,
                                 chunks=chunks)
            s.release_pages(slot)
            return True
        if tok in self.eos_ids or req.max_new_tokens == 1:
            # finished before ever decoding: the slot was never armed —
            # hand its page references straight back
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason, hit_tokens, saved,
                                   prefill_chunks=chunks))
            s.release_pages(slot)
            return True
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0  # new tenant: drafting re-enabled
        self._live[slot] = _Live(req, [tok], hit_tokens, saved,
                                 prefill_chunks=chunks)
        return True

    # ------------------------------------------------- chunked prefill

    def _advance_prefills(self, finished: list) -> None:
        """One chunk per mid-prefill slot per scheduler iteration —
        the starvation cap: between any two chunks of a long prompt,
        every live slot gets a full decode round, so a 30k-token
        prompt costs co-tenants one bounded chunk dispatch per round
        instead of one monolithic prefill."""
        for slot in sorted(self._prefilling):
            st = self._prefilling[slot]
            remaining = len(st.request.prompt) - st.done
            if remaining > self.prefill_chunk:
                if self.paged:
                    self._prefill_chunk_paged(slot, st)
                else:
                    self._prefill_chunk_unpaged(slot, st)
                continue
            # final chunk: the fused suffix-prefill admit samples the
            # first token (or hands off) and un-parks the slot
            del self._prefilling[slot]
            if self.paged:
                self._finalize_prefill_paged(slot, st, finished)
            else:
                self._finalize_prefill_unpaged(slot, st, finished)

    def _prefill_chunk_paged(self, slot: int, st: _PrefillState, *,
                             t0: float | None = None, occ: int = 0,
                             forked: bool = False) -> None:
        """One INTERMEDIATE chunk straight into the slot's pages:
        ``prefill_chunk`` tokens at absolute positions from
        ``st.done`` — a window write with no sampling (only the final
        chunk holds the prompt's last position)."""
        s = self.slots
        ps = s.pool.page_size
        req = st.request
        p = np.asarray(req.prompt, np.int32)
        take = self.prefill_chunk
        if t0 is None:
            t0 = time.monotonic()
            occ = s.n_active
        s.ensure_pages(slot, st.done + take)
        window = np.asarray(p[st.done:st.done + take])[None, :]
        positions = (st.done
                     + np.arange(take, dtype=np.int32))[None, :]
        cols = min(_bucket_pow2(-(-(st.done + take) // ps)),
                   s.max_pages)
        view_tokens = cols * ps
        with self._tree_lock:
            cache = _paged_prefill_chunk(
                self.model, self.params, s.cache, jnp.asarray(window),
                jnp.asarray(positions),
                jnp.asarray(s.page_table[slot:slot + 1, :cols]))
            s.cache = cache
        self.prefills += 1
        self.prefill_chunk_dispatches += 1
        st.done += take
        st.chunks += 1
        if self.timeline is not None:
            # close the record at a real sync: without it the chunk
            # would bill its device time to whatever syncs next
            jax.block_until_ready(cache)
            tags = {"prompt_len": len(p), "chunk": st.chunks,
                    "view_tokens": view_tokens}
            if forked:
                tags["cow_fork"] = True
            self._record_dispatch(
                "prefill_chunk", t0, (time.monotonic() - t0) * 1e3,
                occ, take, 0, ("prefill_chunk", take, view_tokens),
                request_id=req.id, tags=tags, work=take, fed=take,
                est=self.cost.prefill(take, st.done - take,
                                      view_tokens))

    def _prefill_chunk_unpaged(self, slot: int,
                               st: _PrefillState) -> None:
        """The unpaged intermediate chunk: a suffix prefill into the
        CARRIED batch-1 row (PR-3 offset machinery) — the row only
        lands in the slot on the final fused admit."""
        req = st.request
        p = np.asarray(req.prompt, np.int32)
        take = self.prefill_chunk
        t0 = time.monotonic()
        occ = self.slots.n_active
        window = np.asarray(p[st.done:st.done + take])[None, :]
        row, _ = _prefill(self.model, self.params, jnp.asarray(window),
                          jnp.int32(take), jnp.int32(st.done), st.row)
        st.row = row
        self.prefills += 1
        self.prefill_chunk_dispatches += 1
        st.done += take
        st.chunks += 1
        if self.timeline is not None:
            jax.block_until_ready(row)
            self._record_dispatch(
                "prefill_chunk", t0, (time.monotonic() - t0) * 1e3,
                occ, take, 0, ("prefill_chunk", take),
                request_id=req.id,
                tags={"prompt_len": len(p), "chunk": st.chunks},
                work=take, fed=take,
                est=self.cost.prefill(take, st.done - take))

    def _finalize_prefill_paged(self, slot: int, st: _PrefillState,
                                finished: list) -> None:
        """The final chunk: the standard fused suffix-prefill admit at
        offset ``st.done`` — position-exact continuation of the chunks
        before it, so the armed slot is bit-identical to a monolithic
        prefill's (the chunked-parity tests pin the token stream)."""
        s = self.slots
        ps = s.pool.page_size
        req = st.request
        p = np.asarray(req.prompt, np.int32)
        max_len = self.model.cfg.max_seq_len
        t0 = time.monotonic()
        occ = s.n_active
        off = st.done
        suffix = p[off:]
        lb = bucket_len(len(suffix), max_len, self.min_bucket)
        s.ensure_pages(slot, len(p))
        window = np.zeros((1, lb), np.int32)
        window[0, :len(suffix)] = suffix
        positions = np.full((1, lb), -1, np.int32)
        positions[0, :len(suffix)] = \
            off + np.arange(len(suffix), dtype=np.int32)
        cols = min(_bucket_pow2(-(-len(p) // ps)), s.max_pages)
        view_tokens = cols * ps
        with self._tree_lock:
            cache, tok, key, last = _paged_prefill_admit(
                self.model, self.params, s.cache, jnp.asarray(window),
                jnp.asarray(positions), jnp.int32(len(suffix)),
                jnp.asarray(s.page_table[slot:slot + 1, :cols]),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jax.random.PRNGKey(req.seed))
            s.cache = cache
        self.prefills += 1
        self.prefill_chunk_dispatches += 1
        st.chunks += 1
        self.prefill_chunked += 1
        if self.prefix is not None:
            self.prefix.insert(p, pages=s.slot_pages(slot, len(p)),
                               logits=last)
        tok = int(tok)
        if self.timeline is not None:
            self._record_dispatch(
                "prefill", t0, (time.monotonic() - t0) * 1e3, occ, lb,
                1, ("prefill", lb, view_tokens), request_id=req.id,
                tags={"prompt_len": len(p), "chunk": st.chunks,
                      "offset": int(off), "view_tokens": view_tokens},
                work=lb, fed=len(suffix),
                est=self.cost.prefill(lb, off, view_tokens))
        if req.prefill_only:
            self._finish_handoff(req, s.slot_pages(slot, len(p)),
                                 len(p), last, finished,
                                 hit_tokens=st.hit_tokens,
                                 saved=st.saved, chunks=st.chunks)
            s.release_pages(slot)
            return
        if tok in self.eos_ids or req.max_new_tokens == 1:
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason, st.hit_tokens, st.saved,
                                   prefill_chunks=st.chunks))
            s.release_pages(slot)
            return
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0
        self._live[slot] = _Live(req, [tok], st.hit_tokens, st.saved,
                                 prefill_chunks=st.chunks)

    def _finalize_prefill_unpaged(self, slot: int, st: _PrefillState,
                                  finished: list) -> None:
        s = self.slots
        req = st.request
        p = np.asarray(req.prompt, np.int32)
        max_len = self.model.cfg.max_seq_len
        t0 = time.monotonic()
        occ = s.n_active
        # the final window's bucket must still fit the cache row
        # (dynamic_update_slice would clamp and corrupt positions);
        # shrinking re-prefills a tail of already-written tokens —
        # identical values at identical positions, position-exact
        off = _usable_prefix(st.done, len(p), max_len, self.min_bucket)
        suffix = p[off:]
        lb = bucket_len(len(suffix), max_len, self.min_bucket)
        padded = np.zeros((1, lb), np.int32)
        padded[0, :len(suffix)] = suffix
        out = _prefill_admit(
            self.model, self.params, s.cache, jnp.asarray(padded),
            jnp.int32(len(suffix)), jnp.int32(slot),
            jnp.float32(req.temperature), jnp.int32(req.top_k),
            jax.random.PRNGKey(req.seed), jnp.int32(off), st.row,
            with_row=self.prefix is not None)
        self.prefills += 1
        self.prefill_chunk_dispatches += 1
        st.chunks += 1
        self.prefill_chunked += 1
        if self.prefix is not None:
            cache, tok, key, row, last = out
            self.prefix.insert(p, row, last)
        else:
            cache, tok, key = out
        tok = int(tok)
        if self.timeline is not None:
            self._record_dispatch(
                "prefill", t0, (time.monotonic() - t0) * 1e3, occ, lb,
                1, ("prefill", lb), request_id=req.id,
                tags={"prompt_len": len(p), "chunk": st.chunks,
                      "offset": int(off)},
                work=lb, fed=len(suffix),
                est=self.cost.prefill(lb, off))
        s.cache = cache
        if tok in self.eos_ids or req.max_new_tokens == 1:
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason, st.hit_tokens, st.saved,
                                   prefill_chunks=st.chunks))
            return
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0
        self._live[slot] = _Live(req, [tok], st.hit_tokens, st.saved,
                                 prefill_chunks=st.chunks)

    # ------------------------------------------------ role-split handoff

    def _finish_handoff(self, req: Request, pages: list, n_tok: int,
                        logits, finished: list, *, hit_tokens: int = 0,
                        saved: int = 0, chunks: int = 0) -> None:
        """The prefill pool's exit: stack the prompt's page CONTENT
        into a portable payload (pow2-padded gather — the padding
        duplicates the last page and the receiving scatter drops it)
        plus the last-position logits, and finish the request
        ``finish_reason="handoff"``. The payload is an immutable
        device pytree: local decode replicas scatter it straight into
        their own pool (device->device, no host hop); the agent wire
        encodes it via serve/tier.py.

        On a SHARED pool (ISSUE-18) there is nothing to gather: the
        consumer reads the same device tree, so the payload is the
        page-ID list itself — pinned by one extra refcount that
        TRANSFERS to whoever consumes the doc (a co-located decode
        engine's owner-swap admit, or the remote stub's late gather)
        — and the local prefill->decode handoff becomes a pure
        pointer move."""
        pool = self.slots.pool
        n = len(pages)
        t0 = time.monotonic()
        occ = self.slots.n_active
        res = Result(req.id, list(req.prompt), [], "handoff",
                     hit_tokens, saved, prefill_chunks=chunks)
        if pool.shared:
            pool.share(pages)  # the doc's own ref; its consumer unrefs
            res.handoff = {"n_tokens": int(n_tok),
                           "page_ids": [int(pg) for pg in pages],
                           "pool": pool, "logits": jnp.asarray(logits)}
            finished.append(res)
            self.handoffs_out += 1
            if self.timeline is not None:
                self._record_dispatch(
                    "handoff_out", t0, (time.monotonic() - t0) * 1e3,
                    occ, n, 0, ("handoff_out", 0), request_id=req.id,
                    tags={"pages": n, "n_tokens": int(n_tok),
                          "owner_swap": True}, work=1, fed=1)
            return
        idx = _padded_pages(pages)
        n_pad = len(idx)
        payload = _gather_pages(self.slots.cache,
                                jnp.asarray(idx, jnp.int32))
        res.handoff = {"n_tokens": int(n_tok), "pages": payload,
                       "logits": jnp.asarray(logits)}
        finished.append(res)
        self.handoffs_out += 1
        if self.timeline is not None:
            jax.block_until_ready(payload)
            self._record_dispatch(
                "handoff_out", t0, (time.monotonic() - t0) * 1e3, occ,
                n_pad, 0, ("handoff_out", n_pad), request_id=req.id,
                tags={"pages": n, "n_tokens": int(n_tok)}, work=1,
                fed=1, est=self.cost.host_move(n * pool.page_nbytes))

    def _handoff_page_count(self, doc: dict) -> int:
        """Page-axis length of a handoff payload, for ALL forms —
        shared-pool page ids, wire (shapes carried per leaf), and
        device pytree — without decoding anything."""
        if "page_ids" in doc:
            return len(doc["page_ids"])
        pages = doc["pages"]
        if isinstance(pages, dict) and "leaves" in pages:
            if len(pages["leaves"]) != self._cache_treedef.num_leaves:
                raise ValueError(
                    f"handoff payload carries {len(pages['leaves'])} "
                    f"leaves, this engine's cache has "
                    f"{self._cache_treedef.num_leaves} — mismatched "
                    "model configs between the prefill and decode "
                    "pools")
            i, ax = self._payload_leaf_spec
            return int(pages["leaves"][i]["shape"][ax])
        return payload_pages(pages)

    def _check_handoff_geometry(self, doc: dict, n_tok: int) -> None:
        ps = self.slots.pool.page_size
        need = -(-n_tok // ps)
        have = self._handoff_page_count(doc)
        if have < need:
            raise ValueError(
                f"handoff payload holds {have} pages, the prompt "
                f"needs {need} at page_size {ps} — mismatched page "
                "geometry between the prefill and decode pools")

    def _decode_handoff(self, doc: dict) -> tuple:
        """A handoff payload's two forms: a device/numpy pytree (local
        handoff — used as-is) or the agent wire form (base64 leaves —
        rebuilt against THIS engine's cache treedef)."""
        pages, logits = doc["pages"], doc["logits"]
        if isinstance(pages, dict) and "leaves" in pages:
            pages = decode_payload(pages, self._cache_treedef)
        if isinstance(logits, dict) and "b64" in logits:
            logits = decode_array(logits)
        return pages, logits

    def _admit_handoff(self, req: Request, finished: list) -> bool:
        """The decode pool's entry: reserve the request's whole-life
        worst case, scatter the payload into fresh pages, sample the
        first token from the carried logits with THIS request's
        knobs/seed, arm the slot. Token-exact vs one engine doing
        prefill + decode itself: the pages round-trip bitwise and the
        first-token draw uses the same PRNGKey the fused admit would
        have.

        Shared-pool form (``page_ids``): no scatter at all — the pages
        are already resident, so the admit aliases them CoW-style via
        ``seed_pages`` (the fork matters: many decode requests can
        adopt the same hot prompt concurrently, and each needs its own
        writable tail page) and drops the doc's transfer ref."""
        s = self.slots
        pool = s.pool
        ps = pool.page_size
        p = np.asarray(req.prompt, np.int32)
        n_tok = int(req.handoff["n_tokens"])
        worst = -(-(len(p) + req.max_new_tokens) // ps)
        if "page_ids" in req.handoff:
            return self._admit_handoff_shared(req, finished, p, n_tok,
                                              worst)
        granted = pool.reserve(worst)
        while not granted and self.prefix is not None \
                and self.prefix.evict_one():
            granted = pool.reserve(worst)
        if not granted:
            return False  # transient: stays pending until pages free
        if self.fault_plan is not None:
            try:
                self.fault_plan.on_admit(req.id)
            except BaseException:
                pool.cancel(worst)
                raise
        slot = self._free_slots()[0]
        t0 = time.monotonic()
        occ = s.n_active
        pages_tree, logits = self._decode_handoff(req.handoff)
        s.seed_pages(slot, [], 0, worst)
        s.ensure_pages(slot, n_tok)
        n = -(-n_tok // ps)
        n_pad = payload_pages(pages_tree)
        # submit() already validated the geometry; this guards the
        # invariant without killing the replica over a caller bug
        if n_pad < n:
            s.release_pages(slot)
            raise ValueError(
                f"handoff payload holds {n_pad} pages, prompt needs "
                f"{n} at page_size {ps}")
        dst = s.page_table[slot, :n].tolist() \
            + [pool.n_pages] * (n_pad - n)
        with self._tree_lock:
            s.cache = _scatter_pages(s.cache, pages_tree,
                                     jnp.asarray(dst, jnp.int32))
        tok, key = _sample_first(
            jnp.asarray(logits), jnp.float32(req.temperature),
            jnp.int32(req.top_k), jax.random.PRNGKey(req.seed))
        if self.prefix is not None:
            # the decode pool learns the prompt too: the next sharer
            # routed here hits without another handoff
            self.prefix.insert(p, pages=s.slot_pages(slot, n_tok),
                               logits=jnp.asarray(logits))
        self.handoffs_in += 1
        tok = int(tok)
        if self.timeline is not None:
            self._record_dispatch(
                "handoff_admit", t0, (time.monotonic() - t0) * 1e3,
                occ, n_pad, 1, ("handoff_admit", n_pad),
                request_id=req.id,
                tags={"prompt_len": len(p), "pages": n}, work=1, fed=1,
                est=self.cost.host_move(n * pool.page_nbytes))
        if tok in self.eos_ids or req.max_new_tokens == 1:
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason))
            s.release_pages(slot)
            return True
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0
        self._live[slot] = _Live(req, [tok])
        return True

    def _admit_handoff_shared(self, req: Request, finished: list,
                              p: np.ndarray, n_tok: int,
                              worst: int) -> bool:
        """Owner-swap admit: the handoff pages already live in THIS
        engine's pool, so admission is ``seed_pages`` aliasing — share
        each full page, fork the partial tail (many decode requests
        can adopt the same hot prompt concurrently, and each needs its
        own writable tail) — then drop the doc's transfer ref. KV
        bytes moved: one page when the prompt ends mid-page, else
        zero."""
        s = self.slots
        pool = s.pool
        ps = pool.page_size
        page_ids = [int(pg) for pg in req.handoff["page_ids"]]
        n_alias = -(-n_tok // ps)
        fork = 1 if n_tok % ps else 0
        need = worst - n_alias + fork
        granted = pool.reserve(need)
        while not granted and self.prefix is not None \
                and self.prefix.evict_one():
            granted = pool.reserve(need)
        if not granted:
            return False  # transient; the doc's ref keeps pages alive
        if self.fault_plan is not None:
            try:
                self.fault_plan.on_admit(req.id)
            except BaseException:
                pool.cancel(need)
                raise
        slot = self._free_slots()[0]
        t0 = time.monotonic()
        occ = s.n_active
        s.seed_pages(slot, page_ids[:n_alias], n_tok, need)
        pool.unref(page_ids)  # the transfer ref moves to the slot
        logits = req.handoff["logits"]
        tok, key = _sample_first(
            jnp.asarray(logits), jnp.float32(req.temperature),
            jnp.int32(req.top_k), jax.random.PRNGKey(req.seed))
        if self.prefix is not None:
            self.prefix.insert(p, pages=s.slot_pages(slot, n_tok),
                               logits=jnp.asarray(logits))
        self.handoffs_in += 1
        self.migrate_bytes_avoided += \
            (n_alias - fork) * pool.page_nbytes
        tok = int(tok)
        if self.timeline is not None:
            self._record_dispatch(
                "handoff_admit", t0, (time.monotonic() - t0) * 1e3,
                occ, n_alias, 1, ("handoff_admit", 0),
                request_id=req.id,
                tags={"prompt_len": len(p), "pages": n_alias,
                      "owner_swap": True}, work=1, fed=1,
                est=self.cost.host_move(fork * pool.page_nbytes))
        if tok in self.eos_ids or req.max_new_tokens == 1:
            reason = "eos" if tok in self.eos_ids else "length"
            finished.append(Result(req.id, list(req.prompt), [tok],
                                   reason))
            s.release_pages(slot)
            return True
        s.admit(slot, len(p), tok, req.temperature, req.top_k, key)
        self._spec_ema[slot] = 1.0
        self._live[slot] = _Live(req, [tok])
        return True

    # ------------------------------------------------- live migration

    def _check_migrate(self, req: Request, p: list) -> None:
        """Continuity + geometry of a migrate payload at submit time —
        a mismatch is one request's clean refusal (400 at the
        gateway), not a whole-replica admission crash (the handoff
        precedent). Accepts both forms: a ``SessionSnapshot`` (local
        owner swap or in-process remote) and the agent wire doc.

        A DELTA doc (suffix-only pages + ``delta.prefix_tokens``,
        ISSUE-19) is additionally checked against this engine's OWN
        prefix store: the covering entry is acquired and PINNED in
        ``_migrate_pins`` so eviction between this check (any thread)
        and admission (the scheduler thread) cannot free the prefix
        pages the adopt will alias. A store that no longer covers the
        assumed prefix raises ``StaleDelta`` — the sender's contract
        is to re-ship the full payload. The probe is device-store-only
        (no host-tier page-in: that dispatches device work, and this
        runs on the HTTP thread)."""
        snap = req.migrate
        delta = None
        if isinstance(snap, dict):
            gen = snap.get("generated") or []
            n_tok = int(snap.get("n_tokens", -1))
            prompt = [int(t) for t in snap.get("prompt", ())]
            pages = snap.get("pages")
            delta = snap.get("delta")
            if not (isinstance(pages, dict) and "leaves" in pages):
                raise ValueError(
                    "a wire migrate doc carries base64 leaf pages")
            have = self._handoff_page_count({"pages": pages})
        else:
            gen = list(snap.generated)
            n_tok = int(snap.n_tokens)
            prompt = [int(t) for t in snap.prompt]
            if snap.local:
                if snap.pool is not self.slots.pool:
                    raise ValueError(
                        "a local (owner-swap) snapshot holds page ids "
                        "in a pool this engine does not share — "
                        "extract with wire=True to cross pools")
                have = len(snap.pages)
            else:
                have = self._handoff_page_count({"pages": snap.pages})
        if not gen:
            raise ValueError(
                "a migrated session carries at least one generated "
                "token (pre-first-token sessions re-run as ordinary "
                "requests)")
        if prompt != [int(t) for t in p]:
            raise ValueError(
                "migrate snapshot prompt differs from the request "
                "prompt — the stream would not be continuous")
        if n_tok != len(p) + len(gen) - 1:
            raise ValueError(
                f"migrate snapshot holds {n_tok} KV positions, "
                f"prompt + generated - 1 is {len(p) + len(gen) - 1} "
                "— the final sampled token is never fed, so its K/V "
                "must not be present")
        ps = self.slots.pool.page_size
        need = -(-n_tok // ps)
        if delta is None:
            if have < need:
                raise ValueError(
                    f"migrate snapshot holds {have} pages, the "
                    f"session needs {need} at page_size {ps} — "
                    "mismatched page geometry between source and "
                    "target")
            return
        # ---- delta form: suffix pages only + an assumed prefix
        pt = int(delta.get("prefix_tokens", 0))
        if pt <= 0 or pt % ps:
            raise ValueError(
                f"delta prefix_tokens ({pt}) must be a positive "
                f"multiple of page_size {ps}")
        k = pt // ps
        if k > need - 1:
            raise ValueError(
                f"delta prefix covers {k} pages of a {need}-page "
                "session — at least one page always ships")
        if have < need - k:
            raise ValueError(
                f"delta payload holds {have} pages, the suffix needs "
                f"{need - k} at page_size {ps}")
        if self.prefix is None:
            raise StaleDelta(
                "delta migrate doc arrived but this engine runs no "
                "prefix store — nothing can cover the prefix")
        # the context whose KV the prefix pages must hold: prompt +
        # generated minus the never-fed-back final token (the
        # snapshot invariant checked above)
        ctx = prompt + [int(t) for t in gen][:-1]
        match, entry = self.prefix.acquire(ctx)
        if entry is None or match < pt or entry.pages is None \
                or len(entry.pages) < k:
            if entry is not None:
                self.prefix.release(entry)
            raise StaleDelta(
                f"adopter covers {match} prefix tokens on-device, the "
                f"delta assumed {pt} — the sender's radix summary was "
                "stale; re-ship the full payload")
        # consumed at admission; released on post-check submit
        # failure and reset(). A re-sent submit (the agent's
        # idempotency contract) must not leak the first pin.
        self._release_migrate_pin(req.id)
        self._migrate_pins[req.id] = entry

    def extract_session(self, request_id, *, wire: bool = False):
        """Freeze a live decode slot into a ``SessionSnapshot`` and
        evict it — the source half of a migration, called between
        dispatches by the replica's own driver thread.

        Returns None when ``request_id`` is not in a live decode slot
        (still pending or mid-prefill) — those carry no per-slot state
        worth moving, so the caller re-runs them as ordinary requests.

        ``wire=False`` (local owner swap): the snapshot holds page IDS
        pinned by one ``share()`` ref that transfers with it — zero KV
        bytes move, and adopt is a page-table install. ``wire=True``:
        the snapshot holds gathered page CONTENT (a device pytree) fit
        for ``snapshot_to_doc`` and the agent wire."""
        with self._dispatch_lock:
            s = self.slots
            pool = s.pool
            if wire is False and not pool.shared:
                raise ValueError(
                    "a local owner-swap snapshot needs a shared pool "
                    "— extract with wire=True")
            slot = None
            for i, live in enumerate(self._live):
                if live is not None and live.request.id == request_id:
                    slot = i
                    break
            if slot is None:
                return None
            live = self._live[slot]
            req = live.request
            t0 = time.monotonic()
            occ = s.n_active
            n_tok = int(s.lengths[slot])
            n = -(-n_tok // pool.page_size)
            pages = [int(pg) for pg in s.page_table[slot, :n]]
            if wire:
                idx = _padded_pages(pages)
                payload = _gather_pages(self.slots.cache,
                                        jnp.asarray(idx, jnp.int32))
                jax.block_until_ready(payload)
                self.migrations_remote += 1
                self.migrate_pages_moved += n
            else:
                pool.share(pages)  # the snapshot's transfer ref
                payload = pages
                self.migrations_local += 1
                self.migrate_bytes_avoided += n * pool.page_nbytes
            snap = SessionSnapshot(
                prompt=list(req.prompt),
                generated=list(live.generated),
                max_new_tokens=int(req.max_new_tokens),
                temperature=float(s.temperature[slot]),
                top_k=int(s.top_k[slot]),
                seed=int(req.seed),
                rng=np.array(s.rng[slot], np.uint32),
                spec_ema=float(self._spec_ema[slot]),
                n_tokens=n_tok,
                pages=payload,
                local=not wire,
                t_freeze=time.time(),
                pool=pool if not wire else None,
                page_size=pool.page_size)
            self._live[slot] = None
            s.evict(slot)
            self.migrations_out += 1
            if self.timeline is not None:
                est = self.cost.host_move(n * pool.page_nbytes) \
                    if wire else (0.0, 0.0)
                self._record_dispatch(
                    "migrate_out", t0, (time.monotonic() - t0) * 1e3,
                    occ, n, 0, ("migrate_out", n if wire else 0),
                    request_id=req.id,
                    tags={"pages": n, "n_tokens": n_tok,
                          "local": not wire}, work=1, fed=1, est=est)
            return snap

    def _admit_migrate(self, req: Request, finished: list) -> bool:
        """Adopt a frozen session: restore its pages (owner swap or
        scatter), then arm the slot DIRECTLY with the carried sampler
        state — no prefill, no first-token draw; every token of this
        stream so far was already sampled, and the PRNG key resumes at
        its exact chain position. The next decode round continues as
        if the slot had lived here all along."""
        snap = req.migrate
        delta_pt = 0
        if isinstance(snap, dict):
            delta_pt = int((snap.get("delta") or {})
                           .get("prefix_tokens", 0))
            snap = snapshot_from_doc(snap)
        s = self.slots
        pool = s.pool
        ps = pool.page_size
        p = np.asarray(req.prompt, np.int32)
        n_tok = int(snap.n_tokens)
        n = -(-n_tok // ps)
        worst = -(-(len(p) + req.max_new_tokens) // ps)
        t0 = time.monotonic()
        occ = s.n_active
        if snap.local:
            # owner swap: the snapshot's share() ref transfers to the
            # slot via a direct page-table install. No CoW fork — a
            # migration has exactly one writer (move semantics), and
            # the tail page's written extent stops where every other
            # holder's read extent does.
            if snap.pool is not pool:
                raise ValueError(
                    "local migrate snapshot is from a different pool")
            need = worst - n
            granted = pool.reserve(need)
            while not granted and self.prefix is not None \
                    and self.prefix.evict_one():
                granted = pool.reserve(need)
            if not granted:
                return False  # transient; snapshot ref pins the pages
            if self.fault_plan is not None:
                try:
                    self.fault_plan.on_admit(req.id)
                except BaseException:
                    pool.cancel(need)
                    raise
            slot = self._free_slots()[0]
            s.reserve_left[slot] = need
            s.n_slot_pages[slot] = n
            s.page_table[slot, :n] = np.asarray(snap.pages, np.int32)
            s.page_table[slot, n:] = pool.n_pages
            self.migrate_bytes_avoided += n * pool.page_nbytes
        else:
            # delta (ISSUE-19): pages [0, k) alias this engine's own
            # store pages instead of shipping — they need no fresh
            # allocation, so the reservation shrinks by k
            k = delta_pt // ps
            need = worst - k
            granted = pool.reserve(need)
            while not granted and self.prefix is not None \
                    and self.prefix.evict_one():
                # evict_one can never free the pinned covering entry
                # (its refcount is held by _migrate_pins)
                granted = pool.reserve(need)
            if not granted:
                return False  # transient; the pin keeps the prefix
            if self.fault_plan is not None:
                try:
                    self.fault_plan.on_admit(req.id)
                except BaseException:
                    pool.cancel(need)
                    raise
            slot = self._free_slots()[0]
            pages_tree = snap.pages
            if isinstance(pages_tree, dict) and "leaves" in pages_tree:
                pages_tree = decode_payload(pages_tree,
                                            self._cache_treedef)
            if k:
                # reconstruct the prefix by refcount-sharing the
                # entry pinned at _check_migrate time — the same
                # alias accounting local adoptions use. The seed is
                # page-aligned by the delta contract, so no CoW fork;
                # the slot's write positions live in shipped pages.
                entry = self._migrate_pins.pop(req.id)
                s.seed_pages(slot, [int(pg) for pg in entry.pages[:k]],
                             k * ps, need)
                self.prefix.release(entry)
            else:
                s.seed_pages(slot, [], 0, need)
            s.ensure_pages(slot, n_tok)
            n_ship = payload_pages(pages_tree)
            if n_ship < n - k:
                s.release_pages(slot)
                raise ValueError(
                    f"migrate payload holds {n_ship} pages, the "
                    f"session needs {n - k} at page_size {ps}")
            # delta payloads arrive trimmed pad-free; re-pad to the
            # pow2 scatter bucket so migrations compile one scatter
            # program per bucket, not one per page count
            n_pad = _bucket_pow2(max(1, n_ship))
            if n_pad > n_ship:
                pages_tree = pad_host_pages(pages_tree, n_pad)
            dst = s.page_table[slot, k:n].tolist() \
                + [pool.n_pages] * (n_pad - (n - k))
            with self._tree_lock:
                s.cache = _scatter_pages(s.cache, pages_tree,
                                         jnp.asarray(dst, jnp.int32))
            self.migrate_pages_moved += n - k
            self.migrate_bytes_wire += (n - k) * pool.page_nbytes
            if k:
                self.migrate_bytes_avoided += k * pool.page_nbytes
                self.migrate_delta_in += 1
        gen = [int(t) for t in snap.generated]
        s.admit(slot, n_tok, gen[-1], snap.temperature, snap.top_k,
                snap.rng)
        self._spec_ema[slot] = float(snap.spec_ema)
        self._live[slot] = _Live(req, gen)
        self.migrations_in += 1
        if snap.local:
            self.migrations_local += 1
        else:
            self.migrations_remote += 1
        self.migrate_freeze_resume_ms += \
            max(0.0, (time.time() - snap.t_freeze) * 1e3)
        if self.timeline is not None:
            moved = 0 if snap.local else n - (delta_pt // ps)
            est = (0.0, 0.0) if snap.local \
                else self.cost.host_move(moved * pool.page_nbytes)
            self._record_dispatch(
                "migrate_in", t0, (time.monotonic() - t0) * 1e3, occ,
                n, 0, ("migrate_in", 0 if snap.local else moved),
                request_id=req.id,
                tags={"pages": n, "n_tokens": n_tok,
                      "generated": len(gen), "local": snap.local,
                      "delta_prefix_pages": delta_pt // ps},
                work=1, fed=1, est=est)
        return True

    # --------------------------------------------------- host page tier

    def _spill_entry(self, entry) -> None:
        """``PrefixStore.on_evict`` hook: before a dying entry's pages
        are unpinned, copy their content device->host into the tier —
        eviction stops meaning re-prefill. Entries already resident in
        the tier only refresh LRU (zero device work)."""
        if entry.pages is None:
            return  # unpaged store entry: the tier is paged-only
        tier = self.host_tier
        tokens = entry.tokens
        if tier.has(tokens):
            tier.touch(tokens)
            return
        pool = self.slots.pool
        n = -(-int(tokens.size) // pool.page_size)
        pages = list(entry.pages[:n])
        idx = _padded_pages(pages)
        n_pad = len(idx)
        t0 = time.monotonic()
        payload = _gather_pages(self.slots.cache,
                                jnp.asarray(idx, jnp.int32))
        # DISPATCH only: the gather snapshots the pre-eviction cache
        # value (cache buffers are never donated, so later page reuse
        # cannot touch it), and the device->host sync runs on the
        # tier's copy thread — decode rounds proceed during the spill
        tier.spill_async(tokens, payload, n, entry.logits)
        if self.timeline is not None:
            self._record_dispatch(
                "host_spill", t0, (time.monotonic() - t0) * 1e3,
                self.slots.n_active, n_pad, 0, ("host_spill", n_pad),
                tags={"pages": n, "tokens": int(tokens.size),
                      "async": True},
                work=1, fed=1,
                est=self.cost.host_move(n * pool.page_nbytes))

    def _maybe_page_in(self, p: np.ndarray, off: int, entry):
        """When the host tier holds a strictly longer prefix of ``p``
        than the device store matched, restore that tier entry into
        the pool + device store (host->device scatter) and re-run the
        device lookup — the admission that follows then hits it like
        it never left. Degrades silently when the pool cannot afford
        the pages (after squeezing the device store's LRU)."""
        tier = self.host_tier
        t_off, t_entry = tier.acquire(p)
        if t_entry is None or t_off <= off:
            if t_entry is not None:
                tier.release(t_entry)
            return off, entry
        pool = self.slots.pool
        n = -(-int(t_entry.tokens.size) // pool.page_size)
        try:
            while pool.available() < n and self.prefix.evict_one():
                pass
            if pool.available() < n:
                return off, entry
            t0 = time.monotonic()
            pages = pool.alloc(n)
            idx = _padded_pages(pages, sentinel=pool.n_pages)
            n_pad = len(idx)
            payload = pad_host_pages(t_entry.row, n_pad)
            with self._tree_lock:
                self.slots.cache = _scatter_pages(
                    self.slots.cache, payload,
                    jnp.asarray(idx, jnp.int32))
            logits = jnp.asarray(t_entry.logits) \
                if t_entry.logits is not None else None
            ok = self.prefix.insert(t_entry.tokens, pages=pages,
                                    logits=logits)
            # the store holds its own pins now (or, refused, nobody
            # does and the pages go straight back to the free list)
            pool.unref(pages)
            tier.note_page_in(n * pool.page_nbytes)
            if self.timeline is not None:
                self._record_dispatch(
                    "host_page_in", t0,
                    (time.monotonic() - t0) * 1e3,
                    self.slots.n_active, n_pad, 0,
                    ("host_page_in", n_pad),
                    tags={"pages": n,
                          "tokens": int(t_entry.tokens.size)},
                    work=1, fed=1,
                    est=self.cost.host_move(n * pool.page_nbytes))
            if not ok:
                return off, entry
        finally:
            tier.release(t_entry)
        if entry is not None:
            self.prefix.release(entry)
        return self.prefix.acquire(p)

    def _chunk_size(self) -> int:
        """Decode micro-steps for this iteration: enough for the
        longest-remaining live slot but never past ``chunk_steps``,
        quantized DOWN to a power of two (bounded compile count). Slots
        finishing mid-chunk overshoot and are trimmed — overshoot
        slot-steps are free (the batched step runs every row
        regardless); a too-long chunk would only waste WHOLE-batch
        steps at the very tail, which the max-remaining bound prevents."""
        rem = max(live.request.max_new_tokens - len(live.generated)
                  for live in self._live if live is not None)
        k = 1
        while k * 2 <= min(self.chunk_steps, rem):
            k *= 2
        return k

    def step(self) -> list[Result]:
        """One scheduler iteration; returns requests that finished.
        The iteration holds this ENGINE's ``_dispatch_lock`` (its own
        scheduler state: slots, _live, pending). Co-located engines on
        a shared pool step CONCURRENTLY (ISSUE-19): the shared device
        tree is guarded per dispatch by ``_tree_lock`` around each
        read-dispatch-reassign window, and allocator state by the
        pool's fine ``_mu`` — unless ``serialize_dispatch=True`` pins
        the old pool-wide single-writer discipline as the A/B
        control."""
        with self._dispatch_lock:
            return self._step_locked()

    def _step_locked(self) -> list[Result]:
        if self.fault_plan is not None:
            self.fault_plan.on_dispatch()
        finished: list[Result] = []
        while self._free_slots():
            with self._pending_lock:
                if not self.pending:
                    break
                req = self.pending.popleft()
            if not self._admit_one(req, finished):
                # paged pool cannot grant the reservation right now:
                # requeue at the FRONT (FIFO order preserved) and stop
                # admitting — live slots finishing will free pages
                with self._pending_lock:
                    self.pending.appendleft(req)
                break
        # mid-prefill slots advance ONE chunk, then every live slot
        # gets its decode round — the interleave that keeps a long
        # prompt from starving co-tenants' TPOT
        self._advance_prefills(finished)
        if self.slots.n_active:
            finished.extend(self._decode_round())
        return finished

    def _decode_round(self) -> list[Result]:
        """One batched decode round over the live slots + EOS/evict —
        ``step()`` minus admission (``drain()`` runs it alone). With
        speculation on, a round where any slot drafts runs ONE verify
        dispatch (``_verify_round``); otherwise the plain chunk path."""
        if self.speculate_k > 0:
            drafts = self._collect_drafts()
            if drafts is not None:
                return self._verify_round(drafts)
        finished: list[Result] = []
        s = self.slots
        k = self._chunk_size()
        table = None
        if self.paged:
            # the table is frozen across the chunk: pre-extend every
            # live slot to cover the positions this chunk will write
            # (capped at the slot's own budget — overshoot past a
            # finish writes through the sentinel and drops). The table
            # ships COLUMN-SLICED to a power-of-two bucket of the live
            # extent: the gathered view — and every micro-step's
            # attention read over it — is O(actual tokens), not
            # O(max_seq_len); the dropped columns held junk whose
            # masked softmax weight is exactly 0.0, so outputs are
            # bit-identical (at most log2(max_pages) programs per
            # chunk depth, the prefill-bucket discipline)
            hi = 0
            for slot, live in enumerate(self._live):
                if live is not None:
                    s.ensure_pages(slot, min(
                        int(s.lengths[slot]) + k,
                        len(live.request.prompt)
                        + live.request.max_new_tokens))
                    hi = max(hi, int(s.lengths[slot]) + k)
            cols = min(_bucket_pow2(-(-hi // s.pool.page_size)),
                       s.max_pages)
            table = jnp.asarray(s.page_table[:, :cols])
        view_tokens = cols * s.pool.page_size if self.paged else 0
        freeze = self.in_dispatch_eos
        rem = None
        if freeze:
            # per-slot remaining budgets: the device freezes a slot the
            # moment it samples EOS or exhausts this, so every emitted
            # (non-frozen) position is a token the request keeps
            rem = np.zeros(s.batch_size, np.int32)
            for slot, live in enumerate(self._live):
                if live is not None:
                    rem[slot] = live.request.max_new_tokens \
                        - len(live.generated)
        if self.timeline is not None:
            t0 = time.monotonic()
            occ = s.n_active
            riders = [lv.request.id for lv in self._live if lv is not None]
        # the read-dispatch-reassign window on the (possibly shared)
        # tree: enqueue ONE dispatch against the current version and
        # reassign — the host sync (np.asarray below) runs OUTSIDE the
        # lock, so co-located engines' device work overlaps
        with self._tree_lock:
            cache, toks, rng = _decode_chunk(
                self.model, self.params, s.cache,
                jnp.asarray(s.last_token), jnp.asarray(s.positions()),
                jnp.asarray(s.temperature), jnp.asarray(s.top_k),
                jnp.asarray(s.rng),
                jnp.asarray(rem) if rem is not None else None, table,
                n_steps=k, eos_ids=self.eos_ids if freeze else (),
                freeze=freeze)
            s.cache = cache
        self.steps += k
        self.dispatches += 1
        toks = np.asarray(toks)  # [b, k]
        # np.array, not asarray: device arrays view as read-only and the
        # next admit writes its slot's key in place
        s.rng = np.array(rng, np.uint32)
        if self.timeline is not None:
            # duration closes at the host sync (np.asarray above), the
            # latency a request actually experienced; tokens landed are
            # counted below once the EOS/budget walk trims overshoot
            dur_ms = (time.monotonic() - t0) * 1e3
        landed = 0

        for slot in range(s.batch_size):
            live = self._live[slot]
            if live is None:
                continue
            req = live.request
            reason = None
            for j in range(k):
                tok = int(toks[slot, j])
                live.generated.append(tok)
                if tok in self.eos_ids:
                    reason = "eos"
                elif len(live.generated) >= req.max_new_tokens:
                    reason = "length"
                if reason:
                    # tokens past this point were frozen in-dispatch
                    # (re-emitted finals, no KV writes) — or, with
                    # freeze off, chunk overshoot: decoded garbage the
                    # host trims. Either way never reported.
                    break
            if reason is None:
                # the chunk wrote k tokens at advancing positions; the
                # slot's visible cache grew by k
                s.lengths[slot] += k
                s.last_token[slot] = int(toks[slot, k - 1])
                landed += k
                continue
            if freeze:
                # in-dispatch EOS: the trailing positions were frozen
                # re-emits, not overshoot — the trim is a consistency
                # check now, and the waste counter stays put
                self.frozen_steps += k - (j + 1)
                if j + 1 < k and not (toks[slot, j + 1:]
                                      == toks[slot, j]).all():
                    self.freeze_faults += 1
                    log.warning(
                        "frozen slot %d re-emitted a different token "
                        "(%s after %d) — in-dispatch EOS consistency "
                        "violation", slot, toks[slot, j + 1:].tolist(),
                        int(toks[slot, j]))
            else:
                # tokens past the finish are chunk overshoot the host
                # trimmed: decoded, paid for, never reported
                self.wasted_steps += k - (j + 1)
            landed += j + 1
            finished.append(Result(req.id, list(req.prompt),
                                   live.generated, reason,
                                   live.prefix_hit_tokens,
                                   live.prefill_tokens_saved,
                                   live.drafted, live.accepted,
                                   live.prefill_chunks))
            if self.prefix is not None and self.prefix_donate:
                self._donate(live, slot)
            self._live[slot] = None
            s.evict(slot)
        if self.timeline is not None:
            tags = {"requests": riders}
            if view_tokens:
                tags["view_tokens"] = view_tokens
            view = view_tokens or self.model.cfg.max_seq_len
            # position accounting: with freeze on, every fed position
            # landed a kept token (fed == landed -> the ledger's
            # overshoot bucket is structurally 0; frozen tails join
            # the empty-slot positions in padding). Freeze off keeps
            # the old fed = depth x occupancy, whose excess over
            # landed IS the overshoot bucket.
            fed = landed if freeze else k * occ
            if freeze:
                tags["frozen"] = k * occ - landed
            self._record_dispatch(
                "decode", t0, dur_ms, occ, k, landed,
                ("decode", k, view_tokens), tags=tags,
                work=k * s.batch_size, fed=fed,
                est=self.cost.decode(k, s.batch_size, view))
        return finished

    # ------------------------------------------------- speculative decode

    def _collect_drafts(self) -> list | None:
        """Host-side prompt-lookup proposals, one per slot — or None
        when NOBODY drafts (the round then takes the plain chunk path,
        so a fleet of lookup misses costs one numpy scan per slot and
        zero extra device work). A slot drafts only when: greedy (the
        acceptance rule is argmax equality; sampled requests keep the
        chunked semantics), its acceptance EMA is above the disable
        floor, and >= 2 budget tokens remain (a draft of d can land
        d+1 tokens, so d is clamped to remaining-1 — which also keeps
        every window write inside max_seq_len).

        A verify round advances every NON-drafting live slot by exactly
        one token, where a chunk round would advance it ``chunk_steps``
        — so a lone hot drafter in a mixed batch could drag the rest of
        the batch to 1 token/dispatch indefinitely. The batch-drag gate
        refuses the verify round when both hold: (a) some live slot is
        not drafting, and (b) the round's expected token yield (one per
        live slot + EMA-weighted draft lengths) is below what the chunk
        dispatch would land — keeping the worst case at today's cost +
        the host-side lookups, the speculation contract. A solo drafter
        (no one to drag) always speculates: its verify is 1 step deep
        where the chunk is chunk_steps deep. The gate is prechecked on
        an UPPER bound (full draft caps, before any lookup) so rounds
        it is provably going to refuse skip the n-gram scans
        altogether — an ineligible slot can't start drafting and the
        EMA only moves in verify rounds, so a permanently gated batch
        pays nothing per round, not one scan per greedy slot.

        With in-dispatch EOS on, the verify round FUSES its follow-up
        chunk (``_verify_chunk(n_steps=...)``): every live slot —
        drafting or not — decodes the full chunk depth inside the same
        dispatch, so there is no batch to drag and the gate is
        structurally unnecessary; any proposed draft is pure upside
        (accepted tokens on top of the chunk's) minus one window pass.
        The EMA still silences hopeless drafters."""
        out: list = [None] * self.slots.batch_size
        n_live = 0
        all_eligible = True
        bound = 0.0  # upper bound on the verify round's token yield
        eligible: list = []  # (slot, live, d_cap)
        fused = self.in_dispatch_eos
        for slot, live in enumerate(self._live):
            if live is None:
                continue
            n_live += 1
            bound += 1.0
            req = live.request
            if req.temperature != 0.0 \
                    or self._spec_ema[slot] < self.SPEC_EMA_DISABLE:
                all_eligible = False
                continue
            d_cap = min(self.speculate_k,
                        req.max_new_tokens - len(live.generated) - 1)
            if d_cap <= 0:
                all_eligible = False
                continue
            eligible.append((slot, live, d_cap))
            bound += self._spec_ema[slot] * d_cap
        if not eligible:
            return None
        if not fused and not all_eligible \
                and bound < self._chunk_size() * n_live:
            return None  # gate precheck: refuses before any lookup
        any_draft = False
        expected = float(n_live)  # actual-proposal yield estimate
        for slot, live, d_cap in eligible:
            req = live.request
            ctx = np.asarray(list(req.prompt) + live.generated, np.int32)
            draft = _propose_draft(ctx, d_cap)
            if draft.size:
                out[slot] = draft
                any_draft = True
                expected += self._spec_ema[slot] * draft.size
        if not any_draft:
            return None
        drafting = sum(d is not None for d in out)
        if not fused and drafting < n_live and \
                expected < self._chunk_size() * n_live:
            return None  # batch-drag gate: the chunk dispatch yields more
        return out

    def _verify_round(self, drafts: list) -> list[Result]:
        """One speculative verify dispatch + acceptance/evict. Every
        live slot rides: drafting slots lay out [last_token, draft...]
        at their own positions, non-drafting slots just [last_token]
        (their padding writes drop), and acceptance advances each slot
        by accepted+1 tokens — the rewind for rejected drafts is
        POINTER ARITHMETIC ONLY: their K/V stays in the cache beyond
        the slot's length, invisible to every later query and
        overwritten as the slot decodes on (the prefix-store masked-
        visibility exactness argument). Mid-window EOS/budget trims
        exactly like chunk overshoot; donation reads the row whose
        [0, len) span covers only fed, accepted tokens.

        With in-dispatch EOS on this is the FUSED speculation round:
        the dispatch continues every row ``chunk_size`` frozen-body
        micro-steps past its own bonus verdict, so the chunk dispatch
        that used to follow each verify round is gone — a speculating
        round lands accepted + 1 + chunk tokens per slot in ONE
        dispatch, and non-drafting co-tenants keep their full chunk
        cadence (no batch drag, no gate)."""
        finished: list[Result] = []
        s = self.slots
        b = s.batch_size
        fused = self.in_dispatch_eos
        k_cont = self._chunk_size() if fused else 0
        window = _bucket_pow2(max(d.size for d in drafts
                                  if d is not None)) + 1
        toks = np.zeros((b, window), np.int32)
        positions = np.full((b, window), -1, np.int32)
        draft_len = np.zeros(b, np.int32)
        rem = np.zeros(b, np.int32)
        for slot, live in enumerate(self._live):
            if live is None:
                continue
            toks[slot, 0] = s.last_token[slot]
            positions[slot, 0] = s.lengths[slot]
            rem[slot] = live.request.max_new_tokens \
                - len(live.generated)
            d = drafts[slot]
            if d is not None:
                toks[slot, 1:1 + d.size] = d
                positions[slot, 1:1 + d.size] = \
                    s.lengths[slot] + 1 + np.arange(d.size)
                draft_len[slot] = d.size
        table = None
        if self.paged:
            # window row i writes positions [lengths, lengths + d_i]
            # (last_token + its drafts) — always within the slot's
            # budget (drafts are clamped to remaining - 1) — plus, in
            # the fused round, up to k_cont continuation positions
            # (budget overshoot there writes through the sentinel and
            # drops; ensure_pages never grows past the reservation).
            # Column-sliced like the chunk path: the verify gather
            # reads O(live extent)
            hi = 0
            for slot, live in enumerate(self._live):
                if live is not None:
                    upto = int(s.lengths[slot]) \
                        + int(draft_len[slot]) + 1 + k_cont
                    s.ensure_pages(slot, upto)
                    hi = max(hi, upto)
            cols = min(_bucket_pow2(-(-hi // s.pool.page_size)),
                       s.max_pages)
            table = jnp.asarray(s.page_table[:, :cols])
        view_tokens = cols * s.pool.page_size if self.paged else 0
        if self.timeline is not None:
            t0 = time.monotonic()
            occ = s.n_active
            riders = [lv.request.id for lv in self._live
                      if lv is not None]
        with self._tree_lock:
            out = _verify_chunk(
                self.model, self.params, s.cache, jnp.asarray(toks),
                jnp.asarray(positions), jnp.asarray(draft_len),
                jnp.asarray(s.temperature), jnp.asarray(s.top_k),
                jnp.asarray(s.rng),
                jnp.asarray(rem) if fused else None,
                table, window=window, n_steps=k_cont,
                eos_ids=self.eos_ids if fused else ())
            s.cache = out[0]
        if fused:
            _, emit, accepted, cont, rng = out
            cont = np.asarray(cont)
        else:
            _, emit, accepted, rng = out
            cont = None
        self.steps += window + k_cont
        self.dispatches += 1
        self.spec_rounds += 1
        emit = np.asarray(emit)
        accepted = np.asarray(accepted)
        s.rng = np.array(rng, np.uint32)
        if self.timeline is not None:
            dur_ms = (time.monotonic() - t0) * 1e3  # closes at the sync
        landed = 0
        cont_fed = 0  # live (non-frozen) continuation positions

        for slot in range(b):
            live = self._live[slot]
            if live is None:
                continue
            req = live.request
            d = int(draft_len[slot])
            a = int(accepted[slot])
            if d:
                live.drafted += d
                live.accepted += a
                self.spec_drafted += d
                self.spec_accepted += a
                # rejected drafts were scored and thrown away — the
                # speculation-side waste the utilization counter reports
                # next to chunk overshoot (in the fused round the EOS
                # cap folds accepted-but-discarded drafts past a stop
                # token in here too)
                self.wasted_steps += d - a
                self._spec_ema[slot] = (
                    self.SPEC_EMA_DECAY * self._spec_ema[slot]
                    + (1.0 - self.SPEC_EMA_DECAY) * a / d)
            reason = None
            consumed = 0
            # emit[:a] are the accepted drafts, emit[a] the bonus
            # verdict after them — appended in order with the same
            # EOS/budget walk as the chunk path
            for jj in range(a + 1):
                tok = int(emit[slot, jj])
                live.generated.append(tok)
                consumed += 1
                if tok in self.eos_ids:
                    reason = "eos"
                elif len(live.generated) >= req.max_new_tokens:
                    reason = "length"
                if reason:
                    break
            landed += consumed
            cont_consumed = 0
            if fused:
                if reason is None:
                    # the fused continuation: this slot's chunk
                    # tokens, same EOS/budget walk; frozen tails
                    # re-emit
                    for jj in range(k_cont):
                        tok = int(cont[slot, jj])
                        live.generated.append(tok)
                        cont_consumed += 1
                        if tok in self.eos_ids:
                            reason = "eos"
                        elif len(live.generated) >= req.max_new_tokens:
                            reason = "length"
                        if reason:
                            break
                    if reason is not None \
                            and cont_consumed < k_cont \
                            and not (cont[slot, cont_consumed:]
                                     == cont[slot,
                                             cont_consumed - 1]).all():
                        self.freeze_faults += 1
                        log.warning(
                            "frozen slot %d re-emitted a different "
                            "token in a fused verify round — "
                            "in-dispatch EOS consistency violation",
                            slot)
                # a slot that finished inside the window froze for the
                # whole continuation; mid-continuation finishes freeze
                # the tail — either way those positions are padding
                self.frozen_steps += k_cont - cont_consumed
                landed += cont_consumed
                cont_fed += cont_consumed
            if reason is None:
                # fed last_token + a accepted drafts (+ the fused
                # continuation): the slot's position-exact span grew
                # by accepted + 1 + cont_consumed
                s.lengths[slot] += a + 1 + cont_consumed
                s.last_token[slot] = int(cont[slot, k_cont - 1]) \
                    if fused else int(emit[slot, a])
                continue
            self.wasted_steps += (a + 1) - consumed
            finished.append(Result(req.id, list(req.prompt),
                                   live.generated, reason,
                                   live.prefix_hit_tokens,
                                   live.prefill_tokens_saved,
                                   live.drafted, live.accepted,
                                   live.prefill_chunks))
            if self.prefix is not None and self.prefix_donate:
                # the donated sequence prompt+generated[:-1] spans
                # [0, len(prompt) + consumed - 1 + generated_prev)
                # positions, all of them fed accepted tokens; junk
                # from rejected drafts sits beyond that span, where
                # prefix consumers mask or overwrite it
                self._donate(live, slot)
            self._live[slot] = None
            s.evict(slot)
        if self.timeline is not None:
            drafted_n = int(draft_len.sum())
            accepted_n = int(accepted.sum())
            tags = {"requests": riders, "drafted": drafted_n,
                    "accepted": accepted_n}
            if view_tokens:
                tags["view_tokens"] = view_tokens
            if fused:
                tags["cont_steps"] = k_cont
            view = view_tokens or self.model.cfg.max_seq_len
            # fused round: fed = one seed token per live slot + every
            # draft + the live continuation positions; landed is the
            # same minus the rejected drafts, so fed - landed ==
            # rejected and the ledger's overshoot bucket stays 0
            fed = occ + drafted_n + cont_fed
            est = self.cost.verify(window, b, view)
            if fused:
                dec = self.cost.decode(k_cont, b, view)
                est = (est[0] + dec[0], est[1] + dec[1])
            self._record_dispatch(
                "verify", t0, dur_ms, occ, window, landed,
                ("verify", window, k_cont, view_tokens), tags=tags,
                work=(window + k_cont) * b, fed=fed,
                rejected=drafted_n - accepted_n,
                est=est)
        return finished

    def _donate(self, live: _Live, slot: int) -> None:
        """Give a finished slot's sequence back to the prefix store:
        its cache row is position-exact over prompt + generated[:-1]
        (the final token was sampled but never fed, so its K/V was
        never written). The multi-turn win — the next turn's prompt
        extends this sequence and seeds from it instead of
        re-prefilling the whole conversation. ``wants()`` gates the
        row-extraction dispatch: already-stored or won't-fit sequences
        cost zero device work.

        Paged engines donate by REFERENCE: the store pins the slot's
        own pages (refcount bump, zero device work — the
        ``read_slot_row`` extraction dispatch is gone), so there is
        nothing to gate."""
        seq = np.asarray(list(live.request.prompt)
                         + live.generated[:-1], np.int32)
        if seq.size == 0:
            return
        if self.paged:
            self.prefix.insert(
                seq, pages=self.slots.slot_pages(slot, int(seq.size)))
            return
        if not self.prefix.wants(seq, self._row_nbytes):
            return
        row = _read_slot(self.slots.cache, jnp.int32(slot))
        self.prefix.insert(seq, row)

    def drain(self) -> list[Result]:
        """Finish every IN-FLIGHT slot (no new admissions) and return
        their results. Pending requests stay queued — the caller
        decides whether to reject them, hand them to another replica,
        or resume stepping. The graceful-shutdown hook: a front door
        stops feeding, calls drain(), and every request that already
        holds a slot completes instead of being dropped mid-decode."""
        finished: list[Result] = []
        while self.slots.n_active or self._prefilling:
            # lock PER ITERATION: on a shared pool, co-tenant engines
            # keep stepping between this engine's drain rounds
            with self._dispatch_lock:
                self._advance_prefills(finished)
                if self.slots.n_active:
                    finished.extend(self._decode_round())
        return finished

    def live_progress(self, since: dict | None = None) -> dict:
        """{request_id: tokens generated so far} for every in-flight
        request — the streaming hook: the loop owner snapshots it after
        each ``step()`` and emits the delta per request. ``since``
        (request_id -> count already seen) returns only each request's
        TAIL, keeping a long generation's repeated snapshots O(new
        tokens) instead of O(length^2). Copies, so the caller can hold
        them across the next step."""
        out = {}
        for live in self._live:
            if live is not None:
                start = since.get(live.request.id, 0) if since else 0
                out[live.request.id] = live.generated[start:]
        return out

    def counters(self) -> dict:
        """Engine-level counters for observability surfaces (gateway
        /stats, MetricsStore, bench): flat numeric dict. Prefix-store
        state rides along when the store is on."""
        out = {
            "prefills": self.prefills,
            "decode_steps": self.steps,
            "dispatches": self.dispatches,
            "wasted_steps": self.wasted_steps,
            # in-dispatch EOS (ISSUE-13): positions a finished slot
            # spent frozen (re-emits, no KV writes — padding, not
            # overshoot) and the trim-walk consistency violations
            # (must stay 0)
            "frozen_steps": self.frozen_steps,
            "freeze_faults": self.freeze_faults,
            "spec_rounds": self.spec_rounds,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefill_chunk_dispatches": self.prefill_chunk_dispatches,
            "prefill_chunked_requests": self.prefill_chunked,
            "handoffs_out": self.handoffs_out,
            "handoffs_in": self.handoffs_in,
            "migrations_out": self.migrations_out,
            "migrations_in": self.migrations_in,
            "migrations_local": self.migrations_local,
            "migrations_remote": self.migrations_remote,
            "migrate_pages_moved": self.migrate_pages_moved,
            "migrate_bytes_avoided": self.migrate_bytes_avoided,
            "migrate_bytes_wire": self.migrate_bytes_wire,
            "migrate_delta_in": self.migrate_delta_in,
            "migrate_freeze_resume_ms": round(
                self.migrate_freeze_resume_ms, 3),
        }
        if self.mesh is not None:
            # flat numeric twins of mesh_info() so MetricsStore and
            # the remote agent's counters wire carry the topology
            out["mesh_devices"] = int(self.mesh.size)
            out["mesh_kv_shards"] = int(self.kv_shards)
            out["mesh_param_bytes_per_chip"] = int(self._param_bytes_chip)
            out["mesh_kv_bytes_per_chip"] = int(self._kv_bytes_chip)
        if self.host_tier is not None:
            hs = self.host_tier.stats()
            out["kv_host_entries"] = hs["entries"]
            out["kv_host_bytes"] = hs["bytes"]
            out["kv_host_budget_bytes"] = hs["budget_bytes"]
            out["kv_host_tokens"] = hs["tokens"]
            out["kv_host_spills"] = hs["spills"]
            out["kv_host_page_ins"] = hs["page_ins"]
            out["kv_host_spill_bytes"] = hs["bytes_spilled"]
            out["kv_host_page_in_bytes"] = hs["bytes_paged_in"]
            out["kv_host_evictions"] = hs["evictions"]
        if self.prefix is not None:
            st = self.prefix.stats()
            out["prefix_entries"] = st["entries"]
            out["prefix_bytes"] = st["bytes"]
            out["prefix_budget_bytes"] = st["budget_bytes"]
            out["prefix_evictions"] = st["evictions"]
        if self.paged:
            # the kv_pages block: the fixed-shape-waste sensor. The
            # unpaged cache is ALWAYS batch * max_seq_len resident;
            # here bytes_resident tracks allocated pages only, and
            # tokens_resident / bytes_resident says how much of that
            # is real tokens (live slots + pinned prefix entries;
            # positions shared copy-on-write count once per holder, so
            # treat the ratio as an upper bound under heavy sharing)
            ps = self.slots.pool.stats()
            s = self.slots
            tokens = int(s.lengths[s.active].sum())
            if self.prefix is not None:
                tokens += self.prefix.stats()["tokens"]
            out["kv_pages_total"] = ps["total"]
            out["kv_pages_used"] = ps["used"]
            out["kv_pages_free"] = ps["free"]
            out["kv_pages_reserved"] = ps["reserved"]
            out["kv_cow_shared"] = ps["cow_shared"]
            out["kv_cow_forks"] = ps["forks"]
            out["kv_page_size"] = ps["page_size"]
            out["kv_bytes_resident"] = ps["bytes_resident"]
            out["kv_tokens_resident"] = tokens
        return out

    def reset(self) -> None:
        """Hard reset after a failed ``step()``: drop pending and
        in-flight bookkeeping and free every slot (pure host work — the
        next admit overwrites device rows). Dropped requests never get
        a Result; the caller sheds them. ``slots.reset()`` alone leaves
        the engine inconsistent (``_live`` ghosts would decode garbage
        and emit phantom results), so external callers use this."""
        with self._dispatch_lock:
            with self._pending_lock:
                self.pending.clear()
            self._live = [None] * self.slots.batch_size
            # mid-chunked-prefill slots drop with their requests;
            # their page reservations are returned by slots.reset()'s
            # evicts
            self._prefilling.clear()
            # dropped migrate requests never reach admission — their
            # pinned prefix entries must not stay refcounted forever
            for rid in list(self._migrate_pins):
                self._release_migrate_pin(rid)
            self.slots.reset()

    def run(self, requests: Iterable[Request] = ()) -> Iterator[Result]:
        """Submit ``requests`` and drive the loop until everything
        (including anything submitted earlier) finishes."""
        for r in requests:
            self.submit(r)
        while not self.done:
            yield from self.step()
