"""Task launchers: how the coordinator places agent processes on hosts.

Reference split: YARN RM allocates containers (TaskScheduler ->
amRMClient.addContainerRequest) and the AM's ContainerLauncher starts the
TaskExecutor on the NM (ApplicationMaster.ContainerLauncher.run :1154-1222).
On TPU there is no incremental container negotiation — a slice's hosts are
created *together* (SURVEY.md section 7.9a) — so a Launcher simply places
one agent process per task instance:

- ``LocalProcessLauncher``: agents as local subprocesses (MiniCluster-style
  in-process cluster; also the single-TPU-VM mode where every task shares
  the host and gets a device subset).
- ``SshLauncher``: agents on remote TPU-VM hosts over ssh, one host per
  task round-robin (the gcloud `tpu-vm ssh --worker=all` shape).

Launchers also watch for process exit so a task that dies before
registering its result is still detected (the onContainersCompleted
backup path, ApplicationMaster.java:1050-1068).
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import sys
import threading
from typing import Callable

from tony_tpu import constants as C
from tony_tpu.session import Task

log = logging.getLogger(__name__)

OnExit = Callable[[str, int], None]  # (task_id, exit_code)

# agent argv; module-level so launcher tests can swap in a stand-in
AGENT_ARGV = [sys.executable, "-m", "tony_tpu.agent"]


def parse_memory_bytes(spec: str) -> int:
    """'2g' / '512m' / '1024k' / plain bytes -> int bytes; 0 when blank or
    unparseable (caller skips enforcement)."""
    s = str(spec or "").strip().lower()
    if not s:
        return 0
    try:
        if s[-1] in "kmgt":
            mult = {"k": 1024, "m": 1024 ** 2, "g": 1024 ** 3,
                    "t": 1024 ** 4}[s[-1]]
            return int(float(s[:-1]) * mult)
        return int(s)
    except ValueError:
        log.warning("unparseable memory spec %r; not enforcing", spec)
        return 0


def _memory_preexec(env: dict[str, str]):
    """preexec_fn applying the role's memory as RLIMIT_AS, when (and only
    when) the coordinator exported TONY_TASK_MEMORY — i.e. the user set
    tony.<role>.memory explicitly (ref: YARN enforces the container
    resource; TonyClient.java:788-857 validates it at submit). Address-
    space rlimit is the strictest portable analog: jax maps large arenas,
    which is exactly why the schema default never reaches here."""
    limit = parse_memory_bytes(env.get(C.TASK_MEMORY, ""))
    if limit <= 0:
        return None

    def preexec():
        import resource

        resource.setrlimit(resource.RLIMIT_AS, (limit, limit))

    return preexec


class Launcher:
    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        raise NotImplementedError

    def stop_all(self) -> None:
        raise NotImplementedError

    def kill_task(self, task_id: str) -> bool:
        raise NotImplementedError


class LocalProcessLauncher(Launcher):
    """Spawn ``python -m tony_tpu.agent`` per task on this host."""

    def __init__(self, on_exit: OnExit, workdir: str | None = None):
        self.on_exit = on_exit
        self.workdir = workdir
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        # stop_all bumps the generation: exits from a torn-down generation
        # never reach on_exit, while relaunches (coordinator retry, elastic
        # resize) keep working exit detection
        self._gen = 0

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        full_env = dict(os.environ)
        full_env.update(env)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(
                AGENT_ARGV,
                env=full_env,
                cwd=self.workdir,
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
                preexec_fn=_memory_preexec(env),
            )
        finally:
            out.close()
        with self._lock:
            self._procs[task.id] = proc
            gen = self._gen
        threading.Thread(
            target=self._wait, args=(task.id, proc, gen), daemon=True,
            name=f"wait-{task.id}",
        ).start()
        log.info("launched %s as pid %d (log: %s)", task.id, proc.pid, log_path)

    def pause_exits(self) -> None:
        """Bump the generation so in-flight process exits never reach
        on_exit — wrapper launchers (docker) call this before their own
        teardown kills complete the attached processes."""
        with self._lock:
            self._gen += 1

    def attach(self, task_id: str, proc: subprocess.Popen) -> None:
        """Register an externally-spawned process (ssh/docker wrapper) for
        exit detection under this launcher's generation handshake."""
        with self._lock:
            self._procs[task_id] = proc
            gen = self._gen
        threading.Thread(target=self._wait, args=(task_id, proc, gen),
                         daemon=True, name=f"wait-{task_id}").start()

    def _wait(self, task_id: str, proc: subprocess.Popen, gen: int) -> None:
        code = proc.wait()
        with self._lock:
            if self._procs.get(task_id) is proc:
                self._procs.pop(task_id)
            if gen != self._gen:
                return
        self.on_exit(task_id, code)

    def kill_task(self, task_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(task_id)
        if proc is None:
            return False
        _kill_tree(proc)
        return True

    def stop_all(self) -> None:
        with self._lock:
            self._gen += 1
            procs = list(self._procs.values())
        for proc in procs:
            _kill_tree(proc)


def _kill_tree(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def docker_container_name(task: Task) -> str:
    """Epoch-qualified name: a relaunch after resize/retry must not race
    the async ``--rm`` cleanup of the previous epoch's same-id container."""
    return f"tony-s{task.session_id}-{task.id.replace(':', '-')}"


def build_docker_command(task: Task, env: dict[str, str], image: str,
                         mounts: list[str] | None = None,
                         extra_args: list[str] | None = None,
                         docker_bin: str = "docker",
                         workdir: str = "") -> list[str]:
    """Build the ``docker run`` argv that hosts one agent.

    Reference analog: YARN docker containers via env injection
    (HadoopCompatibleAdapter.getContainerEnvForDocker — ENV_CONTAINER_TYPE,
    image, mounts). On TPU-VMs the accelerator needs ``--privileged`` +
    host networking so the container sees /dev/accel* and the ICI NICs;
    mounts use docker's ``host:container[:ro]`` syntax directly.
    """
    argv = [docker_bin, "run", "--rm", "--name", docker_container_name(task),
            "--net=host", "--privileged"]
    # container paths already covered by user mounts — docker rejects
    # duplicate mount points, so the implicit workdir mount must yield
    user_targets = {m.split(":")[1] for m in mounts or [] if ":" in m}
    if workdir and workdir not in user_targets:
        # the job dir carries the payload script, localized resources, and
        # venv — mount it at the same path and start there, mirroring
        # LocalProcessLauncher's workdir=job_dir
        argv += ["-v", f"{workdir}:{workdir}"]
    if workdir:
        argv += ["-w", workdir]
    for mount in mounts or []:
        argv += ["-v", mount]
    # role resources become docker's enforced limits (ref: YARN enforces
    # the container resource; docker accepts the same '2g' spelling)
    if env.get(C.TASK_MEMORY):
        argv += ["--memory", str(env[C.TASK_MEMORY])]
    if env.get(C.TASK_VCORES):
        argv += ["--cpus", str(env[C.TASK_VCORES])]
    for k, v in env.items():
        argv += ["-e", f"{k}={v}"]
    argv += extra_args or []
    argv += [image, "python3", "-m", "tony_tpu.agent"]
    return argv


class DockerLauncher(Launcher):
    """Run each agent inside a docker container on this host.

    Reference: tony.docker.enabled/tony.docker.containers.image keys +
    docker env injection (TonyConfigurationKeys DOCKER_*,
    HadoopCompatibleAdapter.getContainerEnvForDocker). Exit detection rides
    the local ``docker run`` process (it stays attached); kill goes through
    ``docker kill`` so the in-container process group dies with it.
    """

    def __init__(self, image: str, on_exit: OnExit,
                 mounts: list[str] | None = None,
                 extra_args: list[str] | None = None,
                 docker_bin: str = "docker", workdir: str = ""):
        if not image:
            raise ValueError("DockerLauncher needs an image")
        self.image = image
        self.mounts = mounts or []
        self.extra_args = extra_args or []
        self.docker_bin = docker_bin
        self.workdir = workdir
        self._local = LocalProcessLauncher(on_exit)
        self._names: dict[str, str] = {}
        self._names_lock = threading.Lock()

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        argv = build_docker_command(task, env, self.image, self.mounts,
                                    self.extra_args, self.docker_bin,
                                    workdir=self.workdir)
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(argv, stdout=out,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
        finally:
            out.close()
        with self._names_lock:
            self._names[task.id] = docker_container_name(task)
        self._local.attach(task.id, proc)
        log.info("launched %s in docker image %s (pid %d)", task.id,
                 self.image, proc.pid)

    def _docker_kill(self, name: str) -> None:
        subprocess.run([self.docker_bin, "kill", name],
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                       check=False)

    def kill_task(self, task_id: str) -> bool:
        with self._names_lock:
            name = self._names.get(task_id)
        if name:
            self._docker_kill(name)
        return self._local.kill_task(task_id)

    def stop_all(self) -> None:
        # bump the generation FIRST so teardown exits never reach on_exit
        # (the docker kills below complete each attached `docker run`)
        self._local.pause_exits()
        with self._names_lock:
            names = list(self._names.values())
            self._names.clear()
        for name in names:
            self._docker_kill(name)
        self._local.stop_all()


# the remote agent entrypoint; module-level so launcher tests can swap in a
# long-running stand-in (env-contract pattern, see tests/test_launcher.py)
REMOTE_AGENT_CMD = "python3 -m tony_tpu.agent"


def remote_pgid_file(task: Task, app_id: str = "") -> str:
    """Job- and epoch-qualified pgid path on the REMOTE host (same
    rationale as docker_container_name, plus the app id: two jobs sharing
    a static host list must never read each other's pgid records)."""
    app = f"-{app_id}" if app_id else ""
    return f"/tmp/tony{app}-s{task.session_id}-{task.id.replace(':', '-')}.pgid"


class SshLauncher(Launcher):
    """Place agents on remote hosts over ssh, round-robin per task.

    The remote host needs the same repo importable at ``remote_pythonpath``
    (TPU-VM images share a disk image). Exit detection rides the local ssh
    process's exit code.

    **Job-file distribution** (``ship_job_dir``): before the first launch
    on each host, the job dir — the client already staged src, venv,
    resources, and tony-final.json into it — is tar-piped over ssh to
    the host. The reference uploads zipped src/venv/confs to HDFS and
    every container downloads + extracts them (TonyClient.java:229-310,
    util/Utils.java:750 extractResources); tony-tpu's client stages the
    EXTRACTED tree, so the per-host analog is one stream of that tree
    over the same ssh channel the launch uses — no DFS round trip, no
    per-container unzip, and a host that already sees the job dir (NFS /
    GCS-fuse shared mount) is detected and skipped. With
    ``remote_job_root`` set, the tree lands under
    ``<root>/<basename(job_dir)>`` instead of the identical absolute
    path, and every job-dir path in the task env (TONY_JOB_DIR, conf
    path, venv interpreter in the task command, compile cache,
    checkpoint dir) is rewritten for the remote side.

    Kill is REMOTE-first: the agent runs as a ``setsid`` session leader
    whose pgid is written to a per-task file on the remote host, and
    ``kill_task``/``stop_all`` ssh back in to ``kill -- -PGID`` the whole
    tree (ref analog: the NM kills the container cgroup,
    ApplicationMaster.java:735-777). Killing only the local ssh client
    would orphan the remote tree until its coordinator-lost horizon —
    leaving a window where two gangs overlap after elastic resize/retry.
    """

    def __init__(self, hosts: list[str], on_exit: OnExit,
                 remote_pythonpath: str = "",
                 ssh_opts: list[str] | None = None, ssh_bin: str = "ssh",
                 app_id: str = "", chips_per_host: int = 0,
                 ship_job_dir: str = "", remote_job_root: str = ""):
        if not hosts:
            raise ValueError("SshLauncher needs at least one host")
        self.hosts = hosts
        self.on_exit = on_exit
        self.remote_pythonpath = remote_pythonpath
        self.ssh_opts = ssh_opts or ["-o", "StrictHostKeyChecking=no",
                                     "-o", "BatchMode=yes"]
        self.ssh_bin = ssh_bin
        self.app_id = app_id
        self.ship_job_dir = os.path.abspath(ship_job_dir) if ship_job_dir \
            else ""
        self.remote_job_dir = ""
        if self.ship_job_dir:
            self.remote_job_dir = os.path.join(
                remote_job_root, os.path.basename(self.ship_job_dir)) \
                if remote_job_root else self.ship_job_dir
        self._shipped: set[str] = set()
        # one lock per host: ships to different hosts run concurrently,
        # and a launch headed to an already-shipped host never waits on
        # an in-flight multi-GB stream to another host
        self._ship_locks = {h: threading.Lock() for h in hosts}
        self._shipped_lock = threading.Lock()
        self._next = 0
        self._local = LocalProcessLauncher(self._on_local_exit)
        self._remote: dict[str, tuple[str, str]] = {}  # task -> (host, pgid file)
        self._remote_lock = threading.Lock()
        # capacity-aware packing: when tasks declare a chip demand
        # (TONY_TASK_CHIPS) and hosts have a known chip count, place each
        # task on the host with the most free chips and hand it a disjoint
        # TPU_VISIBLE_DEVICES subset (the pod-wide analog of the
        # coordinator-host ChipAllocator; ref: per-container GPU sets,
        # util/Utils.java:393-419). chips_per_host=0 -> plain round-robin.
        self._pools: dict[str, "ChipAllocator"] | None = None
        if chips_per_host > 0:
            from tony_tpu.coordinator.chips import ChipAllocator

            self._pools = {h: ChipAllocator(chips_per_host) for h in hosts}

    def _on_local_exit(self, task_id: str, code: int) -> None:
        """Natural exit: retire the remote record BEFORE reporting, so a
        later kill_task/stop_all can never fire a stale pgid at a recycled
        pid on the shared host. The remote pgid-file removal is async —
        an unreachable host must not delay completion detection (gang
        finish, DAG release) by the ssh timeout."""
        with self._remote_lock:
            info = self._remote.pop(task_id, None)
        if info and self._pools:
            self._pools[info[0]].release(task_id)
        self.on_exit(task_id, code)
        if info:
            threading.Thread(target=self._rm_pgid_file, args=info,
                             daemon=True,
                             name=f"pgid-cleanup-{task_id}").start()

    def _rm_pgid_file(self, host: str, pgid_file: str) -> None:
        try:
            subprocess.run(
                [self.ssh_bin, *self.ssh_opts, host,
                 f"rm -f {shlex.quote(pgid_file)}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=20, check=False)
        except subprocess.SubprocessError:
            log.debug("stale pgid file cleanup on %s failed", host)

    def _place(self, task: Task, env: dict[str, str]) -> tuple[str, dict]:
        """Pick the host (and chip subset) for a task. With pools + a chip
        demand: most-free-chips host (packing); else round-robin."""
        chips = int(env.get(C.TASK_CHIPS, "0") or 0)
        if self._pools and chips > 0:
            host = max(self.hosts,
                       key=lambda h: self._pools[h].free_count)
            ids = self._pools[host].allocate(task.id, chips)
            env = dict(env)
            env[C.TPU_VISIBLE_DEVICES] = ",".join(str(i) for i in ids)
            return host, env
        host = self.hosts[self._next % len(self.hosts)]
        self._next += 1
        return host, env

    def launch(self, task: Task, env: dict[str, str], log_path: str) -> None:
        host, env = self._place(task, env)
        try:
            self._launch_on(host, task, env, log_path)
        except BaseException:
            # the task never registered in _remote, so no exit path would
            # ever return its chips — release the placement hold here
            if self._pools:
                self._pools[host].release(task.id)
            raise

    def _ensure_shipped(self, host: str) -> None:
        """Ship the job dir to ``host`` exactly once per launcher (probe
        first: a shared mount already carrying the files is skipped). A
        failed ship raises, failing the task launch — the same contract
        as the reference's failed resource localization, which fails the
        container (ApplicationMaster onStartContainerError)."""
        if not self.ship_job_dir:
            return
        with self._shipped_lock:
            if host in self._shipped:
                return
            lock = self._ship_locks.setdefault(host, threading.Lock())
        with lock:
            with self._shipped_lock:
                if host in self._shipped:
                    return
            marker = os.path.join(self.remote_job_dir, C.TONY_FINAL_CONF)
            try:
                probe = subprocess.run(
                    [self.ssh_bin, *self.ssh_opts, host,
                     f"test -e {shlex.quote(marker)}"],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    timeout=30, check=False)
            except subprocess.SubprocessError as e:
                # an unreachable probe must FAIL the launch, not default
                # to shipping: on a shared mount (the case the probe
                # detects) a blind tar would overwrite the live job dir
                # this coordinator is reading
                raise RuntimeError(
                    f"job-dir probe on {host} failed; refusing to ship "
                    f"blindly over a possibly-shared mount: {e}") from e
            if probe.returncode not in (0, 1):
                # `test -e` answers only 0/1; 255 etc. is ssh transport
                # failure — same blind-ship hazard as the timeout above
                raise RuntimeError(
                    f"job-dir probe on {host} exited {probe.returncode} "
                    "(ssh transport error); refusing to ship blindly")
            if probe.returncode == 1:
                self._ship(host)
            with self._shipped_lock:
                self._shipped.add(host)

    def _ship(self, host: str) -> None:
        qd = shlex.quote(self.remote_job_dir)
        # logs/ is excluded: already-launched tasks' ssh clients append to
        # coordinator-side log files while this tar reads the dir (each
        # host writes its own logs anyway). GNU tar rc 1 = "file changed
        # as we read it" (status/event files churn) — the snapshot of the
        # static payload (src/venv/conf/resources) is still complete;
        # only rc >= 2 is a real failure.
        tar = subprocess.Popen(
            ["tar", "-C", self.ship_job_dir, "--exclude=./logs",
             "--exclude=./compile-cache", "-czf", "-", "."],
            stdout=subprocess.PIPE)
        try:
            recv = subprocess.run(
                [self.ssh_bin, *self.ssh_opts, host,
                 f"mkdir -p {qd} && tar -C {qd} -xzf -"],
                stdin=tar.stdout, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
                timeout=float(os.environ.get("TONY_SHIP_TIMEOUT_S", "600")),
                check=False)
        finally:
            if tar.stdout:
                tar.stdout.close()
            tar_rc = tar.wait()
        if recv.returncode or tar_rc > 1:
            raise RuntimeError(
                f"shipping job dir to {host}:{self.remote_job_dir} failed "
                f"(tar rc {tar_rc}, ssh rc {recv.returncode}): "
                f"{recv.stderr.decode(errors='replace')[-500:]}")
        log.info("shipped job dir %s -> %s:%s", self.ship_job_dir, host,
                 self.remote_job_dir)

    def _remote_env(self, env: dict[str, str]) -> dict[str, str]:
        """Rewrite job-dir paths in env values for a remote placement that
        does NOT mirror the local absolute path (remote_job_root mode).
        Covers TONY_JOB_DIR, the conf path, the venv interpreter inside
        TONY_TASK_COMMAND, compile-cache and checkpoint dirs — every
        value the coordinator derived from its own job dir."""
        if not self.remote_job_dir or self.remote_job_dir == self.ship_job_dir:
            return env
        return {k: str(v).replace(self.ship_job_dir, self.remote_job_dir)
                for k, v in env.items()}

    def _launch_on(self, host: str, task: Task, env: dict[str, str],
                   log_path: str) -> None:
        self._ensure_shipped(host)
        env = self._remote_env(env)
        exports = " ".join(
            f"export {k}={shlex.quote(str(v))};" for k, v in env.items()
        )
        pp = f"export PYTHONPATH={shlex.quote(self.remote_pythonpath)}:$PYTHONPATH;" \
            if self.remote_pythonpath else ""
        pgid_file = remote_pgid_file(task, self.app_id)
        # setsid makes the wrapper sh the session/group leader; it records
        # its pid (== the agent's after exec, == the remote pgid) then
        # becomes the agent, so kill -- -PGID reaps agent + user process.
        # -w: setsid forks when already a group leader (always, under sshd)
        # and would otherwise exit 0 instantly — the local ssh client must
        # stay attached and carry the agent's real exit code
        mem_kb = parse_memory_bytes(env.get(C.TASK_MEMORY, "")) // 1024
        ulimit = f"ulimit -v {mem_kb} 2>/dev/null; " if mem_kb > 0 else ""
        inner = (f"echo $$ > {shlex.quote(pgid_file)}; {ulimit}{exports} "
                 f"{pp} exec {REMOTE_AGENT_CMD}")
        remote_cmd = f"exec setsid -w sh -c {shlex.quote(inner)}"
        os.makedirs(os.path.dirname(log_path) or ".", exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            proc = subprocess.Popen(
                [self.ssh_bin, *self.ssh_opts, host, remote_cmd],
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            out.close()
        with self._remote_lock:
            self._remote[task.id] = (host, pgid_file)
        self._local.attach(task.id, proc)
        log.info("launched %s on %s via ssh (pid %d)", task.id, host, proc.pid)

    def _remote_kill(self, host: str, pgid_file: str) -> None:
        qf = shlex.quote(pgid_file)
        cmd = (f'p=$(cat {qf} 2>/dev/null); if [ -n "$p" ]; then '
               f'kill -KILL -- -"$p" 2>/dev/null || kill -KILL "$p" '
               f'2>/dev/null; fi; rm -f {qf}')
        try:
            subprocess.run([self.ssh_bin, *self.ssh_opts, host, cmd],
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, timeout=20, check=False)
        except subprocess.SubprocessError:
            log.warning("remote kill on %s timed out/failed (pgid file %s); "
                        "the agent's coordinator-lost horizon is the backstop",
                        host, pgid_file)

    def kill_task(self, task_id: str) -> bool:
        # keep the _remote record: the chip hold is released only by
        # _on_local_exit once the ssh client confirms the remote tree is
        # gone — releasing here would let a relaunch share devices with a
        # kill that timed out (unreachable host keeps its agent until the
        # coordinator-lost horizon)
        with self._remote_lock:
            info = self._remote.get(task_id)
        if info:
            self._remote_kill(*info)
        # the remote kill usually completes the local ssh client before
        # the local kill runs — a vanished local proc is still a kill
        killed_local = self._local.kill_task(task_id)
        return killed_local or info is not None

    def stop_all(self) -> None:
        # silence local exit detection FIRST: the remote kills below
        # complete each attached ssh client, which must not re-enter on_exit
        self._local.pause_exits()
        with self._remote_lock:
            remote = list(self._remote.values())
            self._remote.clear()
        if self._pools:
            for pool in self._pools.values():
                pool.reset()
        for host, pgid_file in remote:
            self._remote_kill(host, pgid_file)
        self._local.stop_all()
