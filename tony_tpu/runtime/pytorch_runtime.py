"""PyTorch runtime: DDP env rendezvous.

Reference: runtime/PyTorchRuntime.java:45-57 + Utils.parseClusterSpecForPytorch
(util/Utils.java:598-609): INIT_METHOD = tcp://<worker:0 host:port>, RANK =
this task's flat index, WORLD = total task count.
"""

from __future__ import annotations

from tony_tpu import constants as C
from tony_tpu.runtime.base import Runtime, TaskAdapter, TaskContext


class PyTorchTaskAdapter(TaskAdapter):
    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_task_env(ctx)
        worker0 = None
        slots = ctx.cluster_spec.get(C.WORKER_JOB_NAME)
        if slots and slots[0]:
            worker0 = slots[0]
        else:  # single-role jobs under other names
            for s in ctx.cluster_spec.values():
                if s and s[0]:
                    worker0 = s[0]
                    break
        if worker0:
            env[C.PT_INIT_METHOD] = f"tcp://{worker0}"
            # torchrun-style aliases for scripts using MASTER_ADDR/PORT
            host, _, port = worker0.rpartition(":")
            env["MASTER_ADDR"] = host
            env["MASTER_PORT"] = port
        env[C.PT_RANK] = str(ctx.flat_index())
        env[C.PT_WORLD] = str(ctx.total_tasks())
        env["WORLD_SIZE"] = str(ctx.total_tasks())
        return env


class PyTorchRuntime(Runtime):
    name = "pytorch"
    task_adapter_cls = PyTorchTaskAdapter
