"""Framework runtime SPI: the two-sided plugin interface.

Reference: Framework.java:33-67 — an AM-side adapter (cluster-spec
construction, start gating, config validation, callback-info sink) and an
executor-side adapter (env building + user-process exec). MLGenericRuntime
(runtime/MLGenericRuntime.java) supplies the shared GANG/FCFS gating and
exec logic; concrete runtimes mostly override ``build_task_env``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Callable

from tony_tpu import constants as C
from tony_tpu.config import TonyConf
from tony_tpu.session import Session
from tony_tpu.utils import execute_shell

log = logging.getLogger(__name__)


@dataclass
class TaskContext:
    """Everything an executor-side adapter needs to build env + exec
    (ref: TaskExecutor fields handed to Framework.TaskExecutorAdapter)."""

    conf: TonyConf
    role: str
    index: int
    task_num: int
    is_chief: bool
    cluster_spec: dict[str, list[str]]  # role -> ["host:port", ...]
    command: str
    app_id: str = ""
    session_id: int = 0
    rdzv_port: int = -1
    tb_port: int = -1
    log_path: str | None = None
    workdir: str | None = None
    extra_env: dict[str, str] = field(default_factory=dict)
    # runtime-private payload the AM adapter attached to the cluster spec
    # under "__aux__" (ref: HorovodClusterSpec carried alongside the task
    # spec, runtime/HorovodRuntime.java:87-120)
    aux: dict = field(default_factory=dict)
    # channel back to the coordinator's receive_task_callback_info (ref:
    # TaskExecutor.callbackInfoToAM -> rpc registerCallbackInfo)
    callback_to_am: Callable[[str], None] | None = None

    def flat_index(self) -> int:
        """Global process index: offset of this role in config order + local
        index. Deterministic across hosts because cluster_spec preserves the
        conf's role order (the rendezvous contract)."""
        offset = 0
        for role, slots in self.cluster_spec.items():
            if role == self.role:
                return offset + self.index
            offset += len(slots)
        return self.index

    def total_tasks(self) -> int:
        return sum(len(s) for s in self.cluster_spec.values())


class AMAdapter:
    """Coordinator-side adapter (ref: Framework.ApplicationMasterAdapter +
    MLGenericRuntime.AM :57-144)."""

    def __init__(self) -> None:
        self.session: Session | None = None

    def set_session(self, session: Session) -> None:
        self.session = session

    def validate_and_update_config(self, conf: TonyConf) -> None:
        """Raise ConfError on illegal conf; may inject hidden roles
        (ref: validateAndUpdateConfig :100-124)."""

    def can_start_task(self, mode: str, task_id: str) -> bool:
        """GANG: gate until every task registered; FCFS: start immediately
        (ref: MLGenericRuntime.AM.canStartTask :79-99)."""
        assert self.session is not None
        if mode == C.FCFS:
            return True
        return self.session.all_registered()

    def construct_cluster_spec(self, task_id: str) -> str:
        """JSON spec handed to a ready task (ref: :57-62)."""
        assert self.session is not None
        return json.dumps(self.session.cluster_spec())

    def receive_task_callback_info(self, task_id: str, info: str) -> None:
        """Ref: HorovodRuntime's driver callback; generic runtimes ignore."""

    def destroy(self) -> None:
        pass


class TaskAdapter:
    """Executor-side adapter (ref: Framework.TaskExecutorAdapter +
    MLGenericRuntime.Task :180-186)."""

    def need_reserve_rdzv_port(self, ctx_role: str, conf: TonyConf) -> bool:
        """Whether the agent should reserve a rendezvous port before
        registering (ref: rpcPort always reserved, TaskExecutor.java:89)."""
        return True

    def need_reserve_tb_port(self, ctx_role: str, is_chief: bool, conf: TonyConf) -> bool:
        """TensorBoard port policy: reserve on the chief, or on a sidecar
        ``tensorboard`` role's executor (ref: MLGenericRuntime :161-178)."""
        if ctx_role == C.TENSORBOARD_JOB_NAME:
            return True
        sidecars = conf.get_list("tony.application.sidecar.jobtypes")
        has_tb_role = C.TENSORBOARD_JOB_NAME in conf.roles()
        return is_chief and not (has_tb_role and C.TENSORBOARD_JOB_NAME in sidecars)

    def build_task_env(self, ctx: TaskContext) -> dict[str, str]:
        """Framework-specific rendezvous env. Base provides the common
        contract every runtime shares (ref: MLGenericRuntime base env:
        JOB_NAME/TASK_INDEX/TASK_NUM/CLUSTER_SPEC)."""
        env = {
            C.JOB_NAME: ctx.role,
            C.TASK_INDEX: str(ctx.index),
            C.TASK_NUM: str(ctx.task_num),
            C.IS_CHIEF: "true" if ctx.is_chief else "false",
            C.CLUSTER_SPEC: json.dumps(ctx.cluster_spec),
        }
        for pair in str(ctx.conf.get("tony.application.shell-env", "")).split(","):
            if "=" in pair:
                k, _, v = pair.partition("=")
                env[k.strip()] = v
        if ctx.workdir:
            env["TONY_PROFILE_DIR"] = os.path.join(
                ctx.workdir, "profiles", f"{ctx.role}-{ctx.index}")
        profiler_base = ctx.conf.get_int("tony.task.profiler-port", 0)
        if profiler_base > 0:  # unique per task on a shared host
            env["TONY_PROFILER_PORT"] = str(profiler_base + ctx.flat_index())
        if ctx.tb_port > 0:
            env[C.TB_PORT] = str(ctx.tb_port)
        tb_log_dir = str(ctx.conf.get("tony.application.tensorboard-log-dir", ""))
        if tb_log_dir:
            env[C.TB_LOG_DIR] = tb_log_dir
        return env

    def run(self, ctx: TaskContext) -> int:
        """Build env + exec the user process (ref: MLGenericRuntime.Task.run
        = buildTaskEnv + executorPythonShell -> Utils.executeShell)."""
        env = dict(ctx.extra_env)
        env.update(self.build_task_env(ctx))
        timeout_ms = ctx.conf.get_int("tony.task.executor.execution-timeout-ms", 0)
        log.info("exec [%s:%d]: %s", ctx.role, ctx.index, ctx.command)
        start = time.time()
        code = execute_shell(ctx.command, timeout_ms, env, ctx.log_path, ctx.workdir)
        log.info("[%s:%d] exited %d after %.1fs", ctx.role, ctx.index, code,
                 time.time() - start)
        return code


class Runtime:
    """One pluggable framework runtime (ref: AbstractFrameworkRuntime)."""

    name = "abstract"
    am_adapter_cls: type[AMAdapter] = AMAdapter
    task_adapter_cls: type[TaskAdapter] = TaskAdapter

    @classmethod
    def get_am_adapter(cls) -> AMAdapter:
        return cls.am_adapter_cls()

    @classmethod
    def get_task_adapter(cls) -> TaskAdapter:
        return cls.task_adapter_cls()
