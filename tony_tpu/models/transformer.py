"""GPT-style decoder-only transformer — the long-context flagship.

No reference analog (TonY has no model code); built TPU-first:

- logical-axis param annotations ("embed", "heads", "mlp", "vocab") so the
  parallel.sharding presets (dp/fsdp/tp/fsdp_tp) apply unchanged
- attention backend selectable: "reference" (O(L^2)), "blockwise"
  (chunked online-softmax), "ring" (sequence-parallel over the seq mesh
  axis), or "pallas" (fused TPU kernel, tony_tpu.ops.attention)
- bfloat16 activations / float32 params + optimizer, MXU-sized dims
- optional remat (jax.checkpoint) per block to trade FLOPs for HBM
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tony_tpu.parallel.moe import moe_logical_axes
from tony_tpu.parallel.ring_attention import (
    blockwise_attention,
    reference_attention,
    ring_attention,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int | None = None  # GQA: fewer K/V heads; None = MHA
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_backend: str = "blockwise"  # reference|blockwise|ring|ulysses|pallas
    attention_block_size: int = 512
    # pallas backend only: kv-block size when it should differ from the
    # q-block size (0 = same). Measured on v5e at (b4, seq 2048, 8x128):
    # block 512x1024 runs the fwd+bwd kernels 15% faster than 512x512 —
    # half the kv-loop steps means half the per-body fixed VPU work.
    attention_block_k: int = 0
    remat: bool = False
    # what the remat pass may KEEP from the forward instead of
    # recomputing it for backward:
    #   "nothing" — full per-block remat: minimum memory, but the whole
    #     forward (~2N FLOPs) re-executes, capping model-FLOPs MFU at
    #     6/8 of hardware utilization;
    #   "dots" — keep matmul outputs, recompute only elementwise ops:
    #     recompute FLOPs ~0 at O(tokens * (5*d + d_ff)) bytes/layer —
    #     the right trade whenever it fits HBM (docs/PERF.md). The flash
    #     attention call is a pallas custom_vjp, NOT a dot: its forward
    #     still re-executes for backward under this policy;
    #   "attn_saved" — the attention sublayer runs OUTSIDE the remat
    #     region (its residuals, ~8 KB/token/layer in bf16, are saved,
    #     so the flash forward never re-runs) and only the MLP is
    #     rematted with dots kept. Fastest; costs the most HBM.
    remat_policy: str = "nothing"  # nothing | dots | attn_saved
    mesh: Any = None  # required for the ring backend
    # architecture family knobs: the defaults are the Llama-style TPU
    # flagship (RMSNorm + RoPE + no biases + gelu); flipping them to
    # ("layer", "learned", True, "gelu_tanh") gives GPT-2 exactly —
    # models/hf.py imports HF GPT-2 checkpoints into that configuration
    norm: str = "rms"  # rms | layer
    positional: str = "rope"  # rope | learned
    use_bias: bool = False
    # biases on the q/k/v projections ONLY (Qwen2 family: biased qkv, bias-
    # free o/mlp). Independent of use_bias, which biases every dense.
    qkv_bias: bool = False
    # sliding-window attention (Mistral family): each query sees only the
    # last `sliding_window` keys. 0 = full causal. Supported by the
    # reference and blockwise backends, the KV-cache decode path, and the
    # pallas backend (banded kernel: O(L*window) compute and HBM traffic).
    sliding_window: int = 0
    activation: str = "gelu"  # gelu (erf) | gelu_tanh | silu
    norm_eps: float = 1e-6
    rope_theta: float = 10_000.0
    # long-context RoPE rescaling (Llama-3 family); None = plain RoPE
    rope_scaling: RopeScaling | None = None
    # SwiGLU-style gated FFN (Llama family): wo(act(wg(x)) * wi(x));
    # False = classic 2-matmul MLP (GPT-2 family)
    gated_mlp: bool = False
    # per-head width when it differs from d_model // n_heads (Gemma-7B:
    # 16 heads x 256 > d_model 3072); 0 = derived
    explicit_head_dim: int = 0
    # GPT-NeoX/Pythia family: rotate only the first rotary_dims of each
    # head (rotary_pct; 0 = full head_dim), and compute attention + MLP
    # from the SAME block input in parallel (x + attn(ln1 x) + mlp(ln2 x))
    rotary_dims: int = 0
    parallel_residual: bool = False
    # SERVING-ONLY int8 weight-only mode: dense kernels are stored as
    # {kernel_q8, scale} and run through the pallas dequant-matmul
    # (ops/quant.py) — use models.quantize.quantize_for_serving to
    # convert a trained/imported model; training this config is
    # unsupported (int8 weights have no useful gradients)
    quantized: bool = False
    # SERVING int8 KV cache: cache buffers store int8 with per-(position,
    # head) fp32 scales, quantized on write after RoPE — HALF the decode
    # cache HBM traffic (the dominant decode bytes at long context,
    # docs/PERF.md). Read back through the flash-decode kernel (int8
    # tiles dequantized in VMEM) or dequantized for the einsum path.
    kv_cache_quant: bool = False
    # decode-step attention implementation for single-token steps:
    # "einsum" = XLA path (default; exact reference), "flash" = pallas
    # flash-decode kernel (ops/decode.py: fused online-softmax over the
    # cache, int8-aware). Prefill (multi-token decode) always uses the
    # einsum path.
    decode_attention: str = "einsum"
    # multiply token embeddings by sqrt(d_model), in activation dtype
    # (Gemma's normalizer)
    embed_scale: bool = False
    # RMSNorm computes x_norm * (1 + scale) with zero-init scale (Gemma's
    # parameterization; checkpoints store the offset-from-one weight)
    norm_unit_offset: bool = False
    # False adds a separate lm_head param instead of reusing the input
    # embedding for output logits (Llama unties; GPT-2 ties)
    tied_embeddings: bool = True
    # Phi family: the untied output projection carries a bias
    lm_head_bias: bool = False
    # MoE (expert-parallel FFN): 0 = dense MLP everywhere; k > 0 replaces the
    # MLP of every k-th block with a mixture-of-experts layer
    moe_every: int = 0
    moe_num_experts: int = 8
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Mixtral-family MoE: SwiGLU experts + renormalized top-k gates +
    # dropless (exact dense) evaluation; moe_d_ff sizes the experts when
    # it differs from the dense d_ff (0 = same). moe_activation is
    # separate from the dense-MLP activation knob.
    moe_gated: bool = False
    moe_renormalize: bool = False
    moe_dropless: bool = False
    moe_activation: str = "gelu"
    moe_d_ff: int = 0
    # scan the layer stack with nn.scan: one traced/compiled block instead
    # of n_layers copies — XLA compile time and HBM for code stay O(1) in
    # depth (the standard TPU deep-stack idiom). Params gain a leading
    # stacked "layers" dim (shardable over the pipe axis). Uniform layers
    # only (incompatible with moe_every, which alternates block types).
    scan_layers: bool = False
    # SHARDED SERVING (ISSUE-14; needs cfg.mesh): pin activations
    # replicated at the row-parallel boundaries — the attention output
    # entering the o projection, o's output, the MLP hidden entering
    # wo, and wo's output. Under the parallel.sharding "serve" preset
    # (weights sharded on OUTPUT dims only) these four constraints
    # force GSPMD to all-gather activations BEFORE any matmul whose
    # contraction dim they shard, so every float reduction runs whole
    # on one chip in the single-chip order and all cross-chip ICI
    # traffic is pure data movement — the structural argument behind
    # the serving engine's mesh=1 == mesh=N byte-identical-streams
    # contract. Training presets (dp/fsdp/tp) must leave this False:
    # a replicate pin would all-gather batch-sharded activations.
    shard_activations: bool = False

    def __post_init__(self):
        # invalid knob combinations fail at construction, not first apply
        if self.gated_mlp and self.moe_every:
            raise ValueError("gated_mlp is not implemented for MoE expert "
                             "FFNs; use moe_every with gated_mlp=False")
        if self.scan_layers and self.moe_every:
            raise ValueError("scan_layers needs uniform layers "
                             "(moe_every alternates block types)")
        if self.remat_policy not in ("nothing", "dots", "attn_saved"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                "expected one of: nothing, dots, attn_saved")

    @property
    def head_dim(self) -> int:
        return self.explicit_head_dim or self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        if kv <= 0 or self.n_heads % kv:
            raise ValueError(
                f"n_kv_heads={kv} must be positive and divide "
                f"n_heads={self.n_heads}")
        return kv


def _serve_replicate(cfg: TransformerConfig, x):
    """The sharded-serving replicate pin (``cfg.shard_activations``):
    constrain ``x`` fully replicated so the matmul consuming it next
    contracts over whole operands (see the config field comment). A
    no-op without a mesh or with the flag off — training paths never
    pay the gather."""
    if cfg.mesh is None or not cfg.shard_activations:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(cfg.mesh, PartitionSpec()))


def _attention(cfg: TransformerConfig, q, k, v, segment_ids=None):
    if cfg.attention_backend == "reference":
        return reference_attention(q, k, v, causal=True,
                                   window=cfg.sliding_window,
                                   segment_ids=segment_ids)
    if cfg.attention_backend == "blockwise":
        return blockwise_attention(q, k, v, block_size=cfg.attention_block_size,
                                   causal=True, window=cfg.sliding_window,
                                   segment_ids=segment_ids)
    if cfg.attention_backend == "ring":
        if cfg.mesh is None:
            raise ValueError("ring attention needs cfg.mesh")
        return ring_attention(q, k, v, cfg.mesh, causal=True,
                              window=cfg.sliding_window,
                              segment_ids=segment_ids)
    if cfg.attention_backend == "ulysses":
        if cfg.mesh is None:
            raise ValueError("ulysses attention needs cfg.mesh")
        from tony_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, cfg.mesh, causal=True,
                                 block_size=cfg.attention_block_size,
                                 window=cfg.sliding_window,
                                 segment_ids=segment_ids)
    if cfg.attention_backend == "pallas":
        from tony_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True,
                               block_q=cfg.attention_block_size,
                               block_k=(cfg.attention_block_k
                                        or cfg.attention_block_size),
                               window=cfg.sliding_window,
                               segment_ids=segment_ids)
    raise ValueError(f"unknown attention backend {cfg.attention_backend}")


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16
    eps: float = 1e-6
    # Gemma parameterization: scale is zero-init and applied as
    # (1 + scale) — checkpoints store the offset-from-one weight
    unit_offset: bool = False

    @nn.compact
    def __call__(self, x):
        init = nn.initializers.zeros_init() if self.unit_offset \
            else nn.initializers.ones_init()
        scale = self.param("scale", init, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                                   + self.eps)
        mult = 1.0 + scale if self.unit_offset else scale
        return (norm * mult).astype(self.dtype)


class LayerNorm(nn.Module):
    """Mean-subtracting norm with bias (GPT-2 family); fp32 math."""

    dtype: Any = jnp.bfloat16
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones_init(), (d,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (d,),
                          jnp.float32)
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
        return (((x32 - mu) * jax.lax.rsqrt(var + self.eps)) * scale
                + bias).astype(self.dtype)


def make_norm(cfg: TransformerConfig, name: str):
    if cfg.norm == "layer":
        if cfg.norm_unit_offset:
            raise ValueError("norm_unit_offset is an RMSNorm (Gemma) knob")
        return LayerNorm(cfg.dtype, cfg.norm_eps, name=name)
    if cfg.norm == "rms":
        return RMSNorm(cfg.dtype, cfg.norm_eps, cfg.norm_unit_offset,
                       name=name)
    raise ValueError(f"unknown norm {cfg.norm}")


def _activation(cfg: TransformerConfig):
    if cfg.activation == "gelu":
        return lambda x: nn.gelu(x, approximate=False)
    if cfg.activation == "gelu_tanh":
        return lambda x: nn.gelu(x, approximate=True)
    if cfg.activation == "silu":
        return nn.silu
    raise ValueError(f"unknown activation {cfg.activation}")


@dataclass(frozen=True)
class RopeScaling:
    """Long-context RoPE frequency rescaling (hashable so configs stay
    valid jit static args).

    kind="linear": every frequency divided by ``factor`` (position
    interpolation). kind="llama3": HF's Llama-3 rule — low-frequency
    (long-wavelength) components are divided by ``factor``, high-frequency
    ones kept, with a smooth ramp between the two wavelength thresholds
    derived from ``low_freq_factor``/``high_freq_factor`` and the
    pre-extension ``original_max_len``.
    """

    kind: str = "llama3"  # llama3 | linear
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_len: int = 8192

    def apply(self, freq):
        if self.kind == "linear":
            return freq / self.factor
        if self.kind != "llama3":
            raise ValueError(f"unknown rope scaling kind {self.kind!r}")
        two_pi = 2.0 * jnp.pi
        wavelen = two_pi / freq
        low_wl = self.original_max_len / self.low_freq_factor
        high_wl = self.original_max_len / self.high_freq_factor
        smooth = (self.original_max_len / wavelen - self.low_freq_factor) / (
            self.high_freq_factor - self.low_freq_factor)
        mid = (1.0 - smooth) * freq / self.factor + smooth * freq
        scaled = jnp.where(wavelen > low_wl, freq / self.factor, mid)
        return jnp.where(wavelen < high_wl, freq, scaled)


def rotary_embedding(x, positions, theta: float = 10_000.0,
                     scaling: RopeScaling | None = None,
                     rotary_dims: int = 0):
    """RoPE over head_dim (TPU-friendly: pure elementwise, fuses away).
    Half-split rotation convention (matches HF Llama's rotate_half).
    ``rotary_dims`` < head_dim rotates only the leading slice and passes
    the rest through (GPT-NeoX/Pythia rotary_pct). ``positions`` is [L]
    (shared across the batch) or [B, L] (per-row — the continuous-batching
    decode step, where every cache slot sits at its own position)."""
    d = x.shape[-1]
    if rotary_dims and rotary_dims < d:
        rotated = rotary_embedding(x[..., :rotary_dims], positions, theta,
                                   scaling)
        return jnp.concatenate([rotated, x[..., rotary_dims:]], axis=-1)
    half = d // 2
    freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    if scaling is not None:
        freq = scaling.apply(freq)
    angles = positions[..., None].astype(jnp.float32) * freq  # [..., half]
    if angles.ndim == 3:  # per-row positions [B, L, half]
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    else:  # shared positions [L, half]
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, decode: bool = False, segment_ids=None,
                 positions=None, page_table=None):
        cfg = self.cfg
        b, l, _ = x.shape
        # logical sharding axes for these kernels come from path-name
        # matching in logical_axis_rules_tree, not from annotations here
        if cfg.quantized:
            dense = lambda name, feats, bias: QuantDense(  # noqa: E731
                feats, in_axes=1, use_bias=bias, dtype=cfg.dtype, name=name,
                mesh=cfg.mesh, shard_axes=_q8_shard_axes(cfg, name))
        else:
            dense = lambda name, feats, bias: nn.DenseGeneral(  # noqa: E731
                feats, axis=-1, use_bias=bias, dtype=cfg.dtype,
                param_dtype=jnp.float32, name=name,
                kernel_init=nn.initializers.normal(0.02))
        qkv_bias = cfg.use_bias or cfg.qkv_bias
        q = dense("q", (cfg.n_heads, cfg.head_dim), qkv_bias)(x)
        k = dense("k", (cfg.kv_heads, cfg.head_dim), qkv_bias)(x)
        v = dense("v", (cfg.kv_heads, cfg.head_dim), qkv_bias)(x)
        if decode:
            out = self._decode_attention(q, k, v, positions, page_table)
            # serve-shard pin: attn out is kv-head-sharded (it read the
            # sharded KV pools locally); the o projection contracts
            # over heads, so gather it whole first — exact data
            # movement, not a partial-sum psum
            out = _serve_replicate(cfg, out)
        else:
            if cfg.positional == "rope":
                positions = jnp.arange(l)
                q = rotary_embedding(q, positions, cfg.rope_theta,
                                     cfg.rope_scaling, cfg.rotary_dims)
                k = rotary_embedding(k, positions, cfg.rope_theta,
                                     cfg.rope_scaling, cfg.rotary_dims)
            if cfg.kv_heads != cfg.n_heads and \
                    cfg.attention_backend != "pallas":
                # GQA: broadcast K/V head groups up to n_heads for the
                # backend. XLA fuses the repeat into the score einsum, so
                # nothing is materialized; the HBM win (small KV) is kept
                # where it matters — the decode cache below. The pallas
                # kernel takes grouped K/V natively (its kv BlockSpec
                # indexes the group row per q head), so it skips this.
                group = cfg.n_heads // cfg.kv_heads
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            out = _attention(cfg, q, k, v, segment_ids)
            # serving never takes this branch (every engine dispatch
            # runs decode=True), but the pin completes the contract
            # for any non-decode apply of a shard_activations model
            out = _serve_replicate(cfg, out)
        if cfg.quantized:
            out = QuantDense((cfg.d_model,), in_axes=2,
                             use_bias=cfg.use_bias, dtype=cfg.dtype,
                             name="o", mesh=cfg.mesh,
                             shard_axes=_q8_shard_axes(cfg, "o"))(out)
        else:
            out = nn.DenseGeneral(
                cfg.d_model, axis=(-2, -1), use_bias=cfg.use_bias,
                dtype=cfg.dtype, param_dtype=jnp.float32, name="o",
                kernel_init=nn.initializers.normal(0.02))(out)
        # serve-shard pin: o's output is embed-sharded (the serve
        # preset's row-parallel flip); the residual add and the next
        # norm's mean/rsqrt must see it whole
        return _serve_replicate(cfg, out)

    def _decode_attention(self, q, k, v, positions=None, page_table=None):
        """Incremental attention over a fixed-size KV cache.

        ``page_table`` [b, max_pages] int32 switches the per-slot modes
        to the PAGED cache layout (serve/slots.PagePool): the cache
        leaves are page POOLS ``[n_pages, page_size, kvh, dh]`` (scales
        ``[n_pages, page_size, kvh]``) with no batch dim — row i's
        token at position p writes pool page ``page_table[i, p //
        page_size]`` at offset ``p % page_size``, and row i attends
        over the GATHER of its own pages, reshaped back to the
        ``[max_pages * page_size]`` position-ordered view the unpaged
        buffer would hold — same values at the same logical positions,
        so the attention reduction (and greedy outputs) are identical
        to the unpaged path. Table entries >= n_pages are UNALLOCATED
        sentinels: writes through them drop (scatter mode="drop"),
        gathers clamp to an arbitrary page whose junk the per-row
        position-visibility mask hides — exactly the bucket-padding
        argument. Positions at or past ``max_pages * page_size`` also
        drop (a chunk overshooting a finished slot's budget must not
        wrap into the slot's own live pages). The host allocator
        guarantees every position that must LAND maps to an allocated,
        unshared page (copy-on-write forks happen at admission,
        serve/engine.py).

        Flax "cache" collection, the standard jittable decode shape: the
        cache is a static [b, max_seq_len, kv_heads, dh] buffer (GQA: only
        n_kv_heads are cached — the decode-path HBM bound) updated with
        lax.dynamic_update_slice at the current index, so every decode
        step compiles to the same static-shape program (no growing
        tensors, no recompiles — the XLA-friendly way to autoregress).

        ``positions`` [b] int32 switches to PER-SLOT decode (the
        continuous-batching serving step, serve/): every batch row is an
        independent cache slot sitting at its own position — the new
        token is scatter-written at ``positions[i]`` and row i attends
        over ``[0, positions[i]]`` only. The shared ``cache_index``
        scalar is meaningless across mixed-length slots and is neither
        read nor advanced; a row with ``positions[i] < 0`` is an EMPTY
        slot (no visible keys — its output is garbage by construction
        and the serving scheduler ignores it).

        ``positions`` [b, l] int32 is the MULTI-TOKEN per-slot window
        (speculative verify, serve/engine._verify_chunk): row i's token
        j is written and rotated at ``positions[i, j]`` and attends
        over everything at-or-before it — which includes the window's
        own earlier tokens, so the intra-window mask is causal by
        position arithmetic alone. Entries with ``positions[i, j] < 0``
        are PADDING (a slot drafting fewer tokens than the batch
        window): their cache writes are dropped outright (scatter
        mode="drop" on an out-of-range index) and their logits are
        garbage the scheduler never reads. Draft tokens past the
        accepted prefix DO write their K/V — junk beyond a slot's
        accepted length is invisible under the same per-row visibility
        mask and overwritten as the slot advances (the prefix-store
        exactness argument, serve/prefix.py).
        """
        cfg = self.cfg
        b, l, h, dh = q.shape
        kvh = cfg.kv_heads
        group = h // kvh
        max_len = cfg.max_seq_len
        is_init = self.has_variable("cache", "cached_key")
        quant = cfg.kv_cache_quant
        # cache holds only kv_heads — the GQA HBM saving that makes long
        # batched decode fit (cache is the decode-path memory bound).
        # kv_cache_quant stores int8 + per-(pos, head) scales: half the
        # bytes again (docs/PERF.md decode roofline next lever).
        cache_dtype = jnp.int8 if quant else k.dtype
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (b, max_len, kvh, dh), cache_dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (b, max_len, kvh, dh), cache_dtype)
        if quant:
            k_scales = self.variable("cache", "cached_key_scale", jnp.zeros,
                                     (b, max_len, kvh), jnp.float32)
            v_scales = self.variable("cache", "cached_value_scale",
                                     jnp.zeros, (b, max_len, kvh),
                                     jnp.float32)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.array(0, jnp.int32))
        if not is_init:  # shape-only init pass
            return jnp.zeros((b, l, h, dh), q.dtype)
        per_slot = positions is not None
        paged = page_table is not None
        if paged and not per_slot:
            raise ValueError("page_table requires per-slot positions")
        if per_slot:
            # normalize to the [b, l] window form: [b] is the classic
            # single-token step, [b, l] the speculative verify window
            if positions.ndim == 1:
                if l != 1:
                    raise ValueError(
                        "per-slot decode with positions=[b] is a "
                        "single-token step; got l=%d (pass [b, l] "
                        "positions for a multi-token window)" % l)
                pos2d = positions[:, None]
            elif positions.shape == (b, l):
                pos2d = positions
            else:
                raise ValueError(
                    f"positions shape {positions.shape} does not match "
                    f"the token window ({b}, {l})")
        cur = cache_index.value
        if cfg.positional == "rope":
            # per-slot mode rotates row i's token j at its own position
            # (2-D positions ride a per-row cos/sin in rotary_embedding;
            # padding rows rotate at -1 — junk nothing reads)
            rope_pos = pos2d if per_slot else cur + jnp.arange(l)
            q = rotary_embedding(q, rope_pos, cfg.rope_theta,
                                 cfg.rope_scaling, cfg.rotary_dims)
            k = rotary_embedding(k, rope_pos, cfg.rope_theta,
                                 cfg.rope_scaling, cfg.rotary_dims)
        if quant:
            from tony_tpu.ops.decode import quantize_kv

            k, k_sc = quantize_kv(k)  # quantize-on-write, after RoPE
            v, v_sc = quantize_kv(v)
        if paged:
            # paged scatter: token (i, j) lands in pool page
            # page_table[i, pos // page_size] at offset pos % page_size.
            # Invalid entries — padding (pos < 0), positions past the
            # table's span (budget overshoot), unallocated sentinel
            # table entries (>= n_pages) — are redirected to the
            # explicit out-of-range page index and DROPPED, never
            # clamped: a clamp would overwrite a LIVE page (possibly a
            # copy-on-write page another slot shares).
            pool_k, pool_v = cached_k.value, cached_v.value
            n_pages, ps = pool_k.shape[-4], pool_k.shape[-3]
            span = page_table.shape[1] * ps
            valid = (pos2d >= 0) & (pos2d < span)
            safe = jnp.where(valid, pos2d, 0)
            page = jnp.take_along_axis(page_table, safe // ps, axis=1)
            page = jnp.where(valid, page, n_pages)  # drop via OOB
            off = safe % ps
            if quant:
                k_scales.value = k_scales.value.at[page, off].set(
                    k_sc, mode="drop")
                v_scales.value = v_scales.value.at[page, off].set(
                    v_sc, mode="drop")
            pool_k = pool_k.at[page, off].set(k, mode="drop")
            pool_v = pool_v.at[page, off].set(v, mode="drop")
            cached_k.value = pool_k
            cached_v.value = pool_v
            # gather each row's pages back into the position-ordered
            # [span] view the unpaged buffer would hold (position p =
            # gather index p — identical values, identical reduction).
            # Sentinel entries clamp to page n_pages-1: junk the
            # visibility mask hides, same as bucket padding.
            tab = jnp.clip(page_table, 0, n_pages - 1)
            keys = jnp.take(pool_k, tab, axis=0).reshape(
                b, span, kvh, dh)
            values = jnp.take(pool_v, tab, axis=0).reshape(
                b, span, kvh, dh)
            if quant:
                ksc = jnp.take(k_scales.value, tab, axis=0).reshape(
                    b, span, kvh)
                vsc = jnp.take(v_scales.value, tab, axis=0).reshape(
                    b, span, kvh)
        elif per_slot:
            # scatter each row's tokens at that row's own cache
            # positions (one batched scatter — no per-slot dispatch).
            # Invalid entries (empty slots, window padding: position
            # < 0) are redirected to max_len and DROPPED by the scatter
            # — never clamped: a clamp would overwrite a live position
            # (negative indices wrap in lax scatter, so the redirect
            # must be an explicit positive out-of-range index).
            rows = jnp.arange(b)[:, None]
            write = jnp.where(pos2d >= 0, pos2d, max_len)
            if quant:
                k_scales.value = k_scales.value.at[rows, write].set(
                    k_sc, mode="drop")
                v_scales.value = v_scales.value.at[rows, write].set(
                    v_sc, mode="drop")
            keys = cached_k.value.at[rows, write].set(k, mode="drop")
            values = cached_v.value.at[rows, write].set(v, mode="drop")
            cached_k.value = keys
            cached_v.value = values
            # cache_index stays untouched: per-slot lengths live with the
            # caller (serve.SlotCache), not in the shared scalar
        else:
            if quant:
                k_scales.value = jax.lax.dynamic_update_slice(
                    k_scales.value, k_sc, (0, cur, 0))
                v_scales.value = jax.lax.dynamic_update_slice(
                    v_scales.value, v_sc, (0, cur, 0))
            keys = jax.lax.dynamic_update_slice(
                cached_k.value, k, (0, cur, 0, 0))
            values = jax.lax.dynamic_update_slice(
                cached_v.value, v, (0, cur, 0, 0))
            cached_k.value = keys
            cached_v.value = values
            cache_index.value = cur + l
        # query positions, [rows, l]: one broadcast row in scalar mode,
        # one row per slot in per-slot mode — the visibility mask below
        # is written once against this shape. In the multi-token window
        # this mask IS the intra-window causal mask: window token j's
        # key sits at pos2d[i, j], visible only to queries at-or-after
        # it; padding queries (pos -1) see nothing.
        q_pos = pos2d if per_slot else (cur + jnp.arange(l))[None, :]
        win = cfg.sliding_window
        if l == 1 and cfg.decode_attention == "flash":
            # the decode hot loop: fused pallas kernel over the (possibly
            # int8) FULL cache buffer — online softmax in VMEM, GQA tiles
            # read once. The kernel masks window/length itself and skips
            # out-of-range blocks' FLOPs via predication, so the einsum
            # path's static window slice (whose odd win+1 span has no
            # legal TPU tile divisor) is neither needed nor wanted here.
            # Per-slot lengths feed straight through: flash_decode takes
            # a [B] length vector and zero-length rows emit exact zeros.
            from tony_tpu.ops.decode import flash_decode

            length = jnp.maximum(pos2d[:, 0] + 1, 0) if per_slot \
                else cur + 1
            out = flash_decode(
                q[:, 0], keys, values, length, window=win,
                k_scale=(ksc if paged else k_scales.value)
                if quant else None,
                v_scale=(vsc if paged else v_scales.value)
                if quant else None)
            return out[:, None].astype(q.dtype)
        if not per_slot and win > 0 and win + l <= max_len:
            # windowed decode: attend over a STATIC (window+l)-sized slice
            # ending at the newest token instead of the whole max_len
            # buffer — per-step attention work drops from O(max_len) to
            # O(window), the same static-shape/no-recompile properties
            # (dynamic_slice start is traced, its size is not)
            span = win + l
            start = jnp.clip(cur + l - span, 0, max_len - span)
            keys_att = jax.lax.dynamic_slice(keys, (0, start, 0, 0),
                                             (b, span, kvh, dh))
            values_att = jax.lax.dynamic_slice(values, (0, start, 0, 0),
                                               (b, span, kvh, dh))
            if quant:
                ks_att = jax.lax.dynamic_slice(k_scales.value, (0, start, 0),
                                               (b, span, kvh))
                vs_att = jax.lax.dynamic_slice(v_scales.value, (0, start, 0),
                                               (b, span, kvh))
            kv_pos = start + jnp.arange(span)
        else:
            keys_att, values_att = keys, values
            if quant:
                ks_att, vs_att = (ksc, vsc) if paged else \
                    (k_scales.value, v_scales.value)
            # size by the BUFFER, not cfg.max_seq_len: the paged
            # engine's bucketed views run this branch with a cache
            # shorter than max_len (every dropped column would have
            # contributed exactly-0.0 softmax weight, so outputs are
            # bit-identical — and the attention read is O(live extent))
            kv_pos = jnp.arange(keys.shape[1])
        # grouped attention: q [b, l, kvh, group, dh] against kv [b, m, kvh, dh]
        qg = q.astype(jnp.float32).reshape(b, l, kvh, group, dh)
        # int8 cache: convert to bf16, not fp32 — int8 magnitudes
        # (<=127) are exact in bf16, the MXU eats bf16 natively, and a
        # convert the scan fails to fuse then materializes HALF the
        # bytes; accumulation stays fp32 via the fp32 q operand
        k_op = keys_att.astype(jnp.bfloat16 if quant else jnp.float32)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k_op) / jnp.sqrt(dh)
        if quant:
            # int8 cache: the per-(pos, head) scale distributes over the
            # d-contraction, so apply it to the SMALL score tensor
            # instead of dequantizing the cache — a materialized fp32
            # dequant of the whole cache inside the token scan measured
            # 2.5x per-token slowdown at cache 3584; with this fold the
            # einsum reads the int8 buffer through a FUSED convert
            # (trace-verified: s8 operands feed the score fusion
            # directly). Residual cost at long cache: XLA lowers the
            # single-query contraction as a VPU multiply-reduce (never
            # MXU), and the inline convert slows that VPU loop — see
            # docs/PERF.md's context-dependent --kv-int8 guidance.
            s = s * ks_att.transpose(0, 2, 1)[:, :, None, None, :]
        # [rows, l, span]: rows == 1 (shared positions) broadcasts over
        # the batch; rows == b is the per-slot mask
        visible = kv_pos[None, None, :] <= q_pos[:, :, None]
        if win > 0:
            visible = visible & (q_pos[:, :, None] - kv_pos[None, None, :]
                                 < win)
        s = jnp.where(visible[:, None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        if quant:
            # likewise fold the value scale into the probabilities
            p = p * vs_att.transpose(0, 2, 1)[:, :, None, None, :]
        v_op = values_att.astype(jnp.bfloat16 if quant else jnp.float32)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", p, v_op)
        return out.reshape(b, l, h, dh).astype(q.dtype)


def _q8_shard_axes(cfg: TransformerConfig, name: str) -> tuple:
    """(in_axis, out_axis) mesh axes for a QuantDense, mirroring the
    'tp' preset's logical rules in logical_axis_rules_tree: q/wi/wg
    column-parallel on heads/mlp, o/wo row-parallel, GQA k/v replicated
    (kv_heads must never split over a bigger tensor axis). Falls back to
    replication when the dim does not divide the axis."""
    from tony_tpu.parallel.mesh import TENSOR

    mesh = cfg.mesh
    if mesh is None or mesh.shape.get(TENSOR, 1) <= 1:
        return (None, None)
    t = mesh.shape[TENSOR]
    heads_ok = cfg.n_heads % t == 0
    ff_ok = cfg.d_ff % t == 0
    if name == "q":
        return (None, TENSOR) if heads_ok else (None, None)
    if name in ("k", "v"):
        grouped = cfg.kv_heads != cfg.n_heads
        return (None, TENSOR) if (not grouped and heads_ok) \
            else (None, None)
    if name == "o":
        return (TENSOR, None) if heads_ok else (None, None)
    if name in ("wi", "wg"):
        return (None, TENSOR) if ff_ok else (None, None)
    if name == "wo":
        return (TENSOR, None) if ff_ok else (None, None)
    return (None, None)


class QuantDense(nn.Module):
    """int8 weight-only dense for SERVING (``cfg.quantized``): parameters
    are the converter's ``{kernel_q8 int8 [in_flat, out_flat], scale
    [out_flat], bias?}`` (see ``models.quantize``); the matmul runs
    through the pallas dequant kernel, so HBM traffic for weights is
    int8 — the decode-path bandwidth win (docs/PERF.md). Multi-dim
    in/out axes (head projections) flatten around the 2-D kernel.

    Tensor parallelism: GSPMD cannot see inside a pallas call, so a
    tensor-sharded q8 kernel would be silently all-gathered. When
    ``mesh`` is set, ``shard_axes=(in_axis, out_axis)`` runs the kernel
    under shard_map manual ONLY over those mesh axes (everything else —
    data/fsdp batch sharding — stays under automatic propagation):
    column-parallel (out_axis) shards are independent; row-parallel
    (in_axis, the Megatron o/wo layout) psums partial products — the
    per-output-channel scale distributes over the contraction sum."""

    features: tuple
    in_axes: int = 1
    use_bias: bool = False
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    shard_axes: tuple = (None, None)

    @nn.compact
    def __call__(self, x):
        from tony_tpu.ops.quant import q8_matmul

        feats = self.features if isinstance(self.features, tuple) \
            else (self.features,)
        in_flat = 1
        for s in x.shape[-self.in_axes:]:
            in_flat *= s
        out_flat = 1
        for s in feats:
            out_flat *= s
        w_q = self.param("kernel_q8", nn.initializers.zeros,
                         (in_flat, out_flat), jnp.int8)
        scale = self.param("scale", nn.initializers.ones, (out_flat,),
                           jnp.float32)
        lead = x.shape[:-self.in_axes]
        x2 = x.reshape(-1, in_flat).astype(self.dtype)
        in_ax, out_ax = self.shard_axes
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from tony_tpu.parallel.mesh import DATA, FSDP
            from tony_tpu.utils.compat import shard_map

            # manual over the WHOLE mesh (partial-manual shard_map needs
            # explicit-type meshes): batch rows ride the data/fsdp axes
            # when they divide, so dp x tp serving keeps its batch shards
            import math

            baxes = tuple(a for a in (DATA, FSDP)
                          if self.mesh.shape.get(a, 1) > 1)
            bsize = math.prod(self.mesh.shape[a] for a in baxes) \
                if baxes else 1
            bspec = baxes if baxes and x2.shape[0] % bsize == 0 else None

            def local(xl, wl, sl):
                y = q8_matmul(xl, wl, sl)
                return jax.lax.psum(y, in_ax) if in_ax else y

            y = shard_map(
                local, mesh=self.mesh,
                in_specs=(P(bspec, in_ax), P(in_ax, out_ax), P(out_ax)),
                out_specs=P(bspec, out_ax),
                check_vma=False,
            )(x2, w_q, scale)
        else:
            y = q8_matmul(x2, w_q, scale)
        y = y.reshape(*lead, *feats)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, feats,
                              jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        if cfg.quantized:
            dense = lambda name, feats: QuantDense(  # noqa: E731
                (feats,), use_bias=cfg.use_bias, dtype=cfg.dtype, name=name,
                mesh=cfg.mesh, shard_axes=_q8_shard_axes(cfg, name))
        else:
            dense = lambda name, feats: nn.Dense(  # noqa: E731
                feats, use_bias=cfg.use_bias, dtype=cfg.dtype,
                param_dtype=jnp.float32, name=name,
                kernel_init=nn.initializers.normal(0.02))
        h = _activation(cfg)(dense("wi" if not cfg.gated_mlp else "wg",
                                   cfg.d_ff)(x))
        if cfg.gated_mlp:
            # SwiGLU: the gate rides the same [B,L,ff] tile as wi's output,
            # so XLA fuses the elementwise product into the matmul epilogue
            h = h * dense("wi", cfg.d_ff)(x)
        # serve-shard pins: wo contracts over the mlp dim h is sharded
        # on — gather h whole first; wo's output is embed-sharded (the
        # row-parallel flip) — gather it before the residual/norm
        h = _serve_replicate(cfg, h)
        return _serve_replicate(cfg, dense("wo", cfg.d_model)(h))


class MoEMLP(nn.Module):
    """Expert-parallel FFN: router + per-expert wi/wo with a leading expert
    dim (sharded on the ``expert`` mesh axis under pjit — the dispatch and
    combine einsums lower to all-to-all over ICI, see parallel/moe.py).

    The load-balancing auxiliary loss is sown into the ``losses`` collection.
    It is NOT applied automatically: your ``apply_fn`` must run
    ``logits, mut = model.apply(params, tokens, mutable=["losses"])`` and add
    ``moe_aux_loss(mut["losses"])`` to the objective, or the router trains
    unregularized and can collapse onto a few experts. Plain
    ``model.apply(params, tokens)`` still works for inference (sow no-ops).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from tony_tpu.parallel.moe import MoEConfig, moe_layer

        cfg = self.cfg
        d_ff = cfg.moe_d_ff or cfg.d_ff
        moe_cfg = MoEConfig(
            num_experts=cfg.moe_num_experts,
            capacity_factor=cfg.moe_capacity_factor,
            top_k=cfg.moe_top_k,
            d_model=cfg.d_model,
            d_ff=d_ff,
            gated=cfg.moe_gated,
            activation=cfg.moe_activation,
            renormalize_top_k=cfg.moe_renormalize,
            dropless=cfg.moe_dropless,
            # int8 + EP serving: with cfg.mesh carrying an expert axis,
            # the q8 expert FFN runs shard-mapped over it so quantized
            # expert weights SHARD instead of replicating (the 47B-
            # Mixtral-on-a-slice requirement; see parallel/moe.py)
            mesh=cfg.mesh,
        )
        init = nn.initializers.normal(0.02)
        e = cfg.moe_num_experts
        params = {"router": self.param("router", init,
                                       (cfg.d_model, e), jnp.float32)}
        names = ("wi", "wg", "wo") if cfg.moe_gated else ("wi", "wo")
        for nm in names:
            shp = (e, d_ff, cfg.d_model) if nm == "wo" \
                else (e, cfg.d_model, d_ff)
            if cfg.quantized:
                # int8 expert weights + per-(expert, out-channel) scales
                # (models/quantize.py Mixtral conversion)
                params[nm + "_q8"] = self.param(
                    nm + "_q8", nn.initializers.zeros, shp, jnp.int8)
                params[nm + "_scale"] = self.param(
                    nm + "_scale", nn.initializers.ones, (shp[0], shp[2]),
                    jnp.float32)
            else:
                params[nm] = self.param(nm, init, shp, jnp.float32)
        # experts compute in cfg.dtype (bf16 on TPU); the router stays fp32 —
        # bf16 routing logits quantize near-tied gate probabilities and flip
        # top-k choices step to step, destabilizing load balancing. int8
        # leaves and their fp32 scales pass through untouched (the pallas
        # dequant matmul owns the cast).
        cast = {k: (v if k == "router" or v.dtype == jnp.int8
                    or k.endswith("_scale") else v.astype(cfg.dtype))
                for k, v in params.items()}
        out, aux = moe_layer(cast, x, moe_cfg)
        if not self.is_initializing():
            # sowing during init would put a "losses" collection into the
            # init() output, which callers then pass around as if it were
            # params (and would double-count: apply(mutable=["losses"])
            # seeds the collection from the input before sow appends)
            self.sow("losses", "moe_aux", aux.astype(jnp.float32))
        # serve-shard pin (the dense-MLP wo rule, MoE flavor). NOTE:
        # the expert-parallel combine itself sums expert outputs across
        # the expert axis, so MoE serving under expert>1 is exact-
        # correct but NOT pinned bitwise vs single-chip — the dense
        # transformer is (docs/SERVING.md).
        return _serve_replicate(cfg, out.astype(cfg.dtype))


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, decode: bool = False, segment_ids=None,
                 positions=None, page_table=None):
        attn_out = Attention(self.cfg, name="attn")(
            make_norm(self.cfg, "ln1")(x), decode=decode,
            segment_ids=segment_ids, positions=positions,
            page_table=page_table)
        ffn_cls = MoEMLP if self.use_moe else MLP
        if (self.cfg.remat and not decode
                and self.cfg.remat_policy == "attn_saved"):
            # attn_saved: attention (above) stays un-rematted — its
            # custom-vjp residuals are saved, the flash forward never
            # re-runs — and only the FFN pays the remat pass, with its
            # dot outputs kept
            ffn_cls = nn.remat(
                ffn_cls, policy=jax.checkpoint_policies.dots_saveable)
        ffn = ffn_cls(self.cfg, name="moe" if self.use_moe else "mlp")
        if self.cfg.parallel_residual:
            # GPT-NeoX: both sublayers read the block INPUT; one residual
            # add (fuses into a single elementwise epilogue on TPU)
            return x + attn_out + ffn(make_norm(self.cfg, "ln2")(x))
        x = x + attn_out
        return x + ffn(make_norm(self.cfg, "ln2")(x))


_STRUCTURAL = "structural"  # attn_saved: remat applied inside Block


def _remat_policy(cfg: TransformerConfig):
    """Map cfg.remat_policy to a jax.checkpoint policy, or _STRUCTURAL
    for attn_saved (see the TransformerConfig field comment)."""
    try:
        return {
            "nothing": jax.checkpoint_policies.nothing_saveable,
            "dots": jax.checkpoint_policies.dots_saveable,
            "attn_saved": _STRUCTURAL,
        }[cfg.remat_policy]
    except KeyError:
        raise ValueError(
            f"unknown remat_policy {cfg.remat_policy!r}; "
            "expected 'nothing', 'dots' or 'attn_saved'") from None


class _ScanBody(nn.Module):
    """Block adapted to nn.scan's (carry, out) body signature."""

    cfg: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, segment_ids, positions, page_table):
        return Block(self.cfg, name="block")(
            x, self.decode, segment_ids=segment_ids,
            positions=positions, page_table=page_table), None


class Transformer(nn.Module):
    cfg: TransformerConfig

    def _learned_positions(self, l: int, decode: bool, positions=None):
        """GPT-2-style absolute position embeddings. In decode mode a
        top-level cache counter tracks the current offset (the per-layer
        attention cache keeps its own; they advance in lockstep). Per-slot
        decode (``positions`` [b]) reads each row's own offset and leaves
        the shared counter untouched — slot lengths live with the caller."""
        cfg = self.cfg
        pos_emb = self.param("pos_embedding", nn.initializers.normal(0.02),
                             (cfg.max_seq_len, cfg.d_model), jnp.float32)
        if decode:
            is_init = self.has_variable("cache", "pos_index")
            pos_index = self.variable("cache", "pos_index",
                                      lambda: jnp.array(0, jnp.int32))
            if positions is not None:
                # declared-but-unchanged pos_index keeps the mutated cache
                # tree congruent with the carried one across serve steps.
                # [b] = single-token step -> [b, 1, d]; [b, l] = multi-
                # token verify window -> [b, l, d] (clipped padding rows
                # read a junk embedding nothing consumes)
                rows = jnp.clip(positions, 0, cfg.max_seq_len - 1)
                emb = pos_emb[rows]
                if positions.ndim == 1:
                    emb = emb[:, None]
                return emb.astype(cfg.dtype)
            if is_init:
                pos = pos_index.value + jnp.arange(l)
                pos_index.value = pos_index.value + l
            else:
                pos = jnp.arange(l)
        else:
            pos = jnp.arange(l)
        return pos_emb[pos][None].astype(cfg.dtype)

    def _scan_blocks(self, x, decode: bool, segment_ids=None,
                     positions=None, page_table=None):
        cfg = self.cfg
        body = _ScanBody
        if cfg.remat and not decode:
            policy = _remat_policy(cfg)
            if policy is not _STRUCTURAL:  # attn_saved remats inside Block
                body = nn.remat(_ScanBody, policy=policy)
        scanned = nn.scan(
            body,
            variable_axes={"params": 0, "cache": 0},
            split_rngs={"params": True},
            in_axes=nn.broadcast,  # segment_ids/positions/page_table:
            length=cfg.n_layers,   # same every layer
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        x, _ = scanned(cfg, decode, name="layers")(x, segment_ids,
                                                   positions, page_table)
        return x

    @nn.compact
    def __call__(self, tokens, decode: bool = False,
                 return_hidden: bool = False, segment_ids=None,
                 positions=None, page_table=None):
        """return_hidden=True yields the final [B, L, D] activations
        (post ln_f) instead of logits, for the chunked large-vocab loss
        (ops.xent.chunked_cross_entropy with params["embedding"]) — the
        [B, L, V] logits tensor is never materialized.

        segment_ids [B, L] (packed-document training): attention is
        restricted to same-segment keys, so documents packed into one
        window never leak into each other. Training-path only (decode
        caches have no segment notion); reference/blockwise/pallas
        backends (the pallas kernels stream the ids as blocked operands).

        positions [B] or [B, L] int32 (decode-only): PER-SLOT decode
        for the continuous-batching server (serve/) — each batch row is
        an independent cache slot at its own position; negative = empty
        slot. [B, L] is the multi-token window (speculative verify):
        row i's token j sits at positions[i, j]; negative entries are
        dropped padding. See Attention._decode_attention.

        page_table [B, max_pages] int32 (decode + positions only):
        the PAGED cache layout — cache leaves are page pools
        [n_pages, page_size, kvh, dh] (serve/slots.PagePool) and row
        i's positions map through its page table; see
        Attention._decode_attention."""
        if segment_ids is not None and decode:
            raise ValueError("segment_ids are a training-path feature; "
                             "decode has no segment notion")
        if positions is not None and not decode:
            raise ValueError("positions (per-slot decode) requires "
                             "decode=True")
        if page_table is not None and positions is None:
            raise ValueError("page_table (paged KV cache) requires "
                             "per-slot positions")
        cfg = self.cfg
        embed = self.param("embedding", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.d_model), jnp.float32)
        x = embed[tokens].astype(cfg.dtype)
        if cfg.embed_scale:
            # in activation dtype, matching HF Gemma's normalizer cast
            x = x * jnp.asarray(cfg.d_model ** 0.5, cfg.dtype)
        if cfg.positional == "learned":
            x = x + self._learned_positions(tokens.shape[1], decode,
                                            positions)
        if cfg.scan_layers:
            x = self._scan_blocks(x, decode, segment_ids, positions,
                                  page_table)
        else:
            block = Block
            if cfg.remat and not decode:
                policy = _remat_policy(cfg)
                if policy is not _STRUCTURAL:
                    block = nn.remat(Block, static_argnums=(2,),
                                     policy=policy)
            for i in range(cfg.n_layers):
                use_moe = cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
                x = block(cfg, use_moe=use_moe, name=f"block_{i}")(
                    x, decode, segment_ids=segment_ids, positions=positions,
                    page_table=page_table)
        x = make_norm(cfg, "ln_f")(x)
        if not cfg.tied_embeddings:
            head = self.param("lm_head", nn.initializers.normal(0.02),
                              (cfg.vocab_size, cfg.d_model), jnp.float32)
        # created BEFORE the return_hidden branch (like lm_head) so init
        # yields the full param set regardless of mode
        head_bias = self.param(
            "lm_head_bias", nn.initializers.zeros, (cfg.vocab_size,),
            jnp.float32) if cfg.lm_head_bias else None
        if return_hidden:
            # chunked large-vocab loss: pair with params["lm_head"] when
            # untied, params["embedding"] when tied (ops.xent) — and pass
            # params["lm_head_bias"] as its bias= when configured.
            return x.astype(jnp.float32)
        head = embed if cfg.tied_embeddings else head
        logits = jnp.einsum("bld,vd->blv", x.astype(jnp.float32), head)
        if head_bias is not None:
            logits = logits + head_bias
        return logits


def logical_axis_rules_tree(params: Any) -> Any:
    """Best-effort logical axes for the transformer param tree, consumed by
    parallel.sharding.tree_shardings. Derived from param path names."""
    # Pre-scan head counts: a GQA K/V kernel has fewer heads (dim 1) than
    # its sibling q kernel and must get the always-replicated "kv_heads"
    # axis (splitting n_kv_heads over a larger tensor axis would fail);
    # full-MHA K/V keeps "heads" and stays tensor-shardable.
    def is_stacked(joined: str) -> bool:
        # scan_layers params live under ".../layers/block/..." with a
        # leading stacked dim (one slice per layer)
        return "/layers/" in joined

    head_counts: dict[str, int] = {}
    q8_out: dict[str, int] = {}  # attn parent -> q kernel_q8 out_flat
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        joined = "/" + "/".join(getattr(p, "key", str(p)) for p in path)
        off = 1 if is_stacked(joined) else 0
        if "/q/" in joined and getattr(leaf, "ndim", 0) == 3 + off:
            head_counts[joined.rsplit("/q/", 1)[0]] = leaf.shape[1 + off]
        if joined.endswith("/q/kernel/b") and \
                getattr(leaf, "ndim", 0) == 3 + off:
            # LoRA trees carry no bare q kernel; B [r, h, dh] has the count
            head_counts[joined.rsplit("/q/", 1)[0]] = leaf.shape[1 + off]
        if joined.endswith("/q/kernel_q8"):
            q8_out[joined.rsplit("/q/", 1)[0]] = leaf.shape[-1]

    def bias_axes(joined: str, x, off: int, leaf_dims: int) -> tuple:
        # use_bias=True (GPT-2 family): biases shard like their kernel's
        # OUTPUT dims — q/k/v [h, dh], o/wo [d_model], wi [d_ff]
        if "/q/" in joined:
            return ("heads", "kv")[:leaf_dims]
        for s in ("/k/", "/v/"):
            if s in joined:
                parent = joined.rsplit(s, 1)[0]
                grouped = (leaf_dims == 2 and x.shape[off] !=
                           head_counts.get(parent, x.shape[off]))
                return ("kv_heads" if grouped else "heads",
                        "kv")[:leaf_dims]
        if "/o/" in joined or "/wo/" in joined:
            return ("embed",)
        if "/wi/" in joined or "/wg/" in joined:
            return ("mlp",)
        return tuple([None] * leaf_dims)  # norm biases etc: replicated

    def axes_for(path: tuple, x) -> tuple:
        joined = "/" + "/".join(getattr(p, "key", str(p)) for p in path)
        off = 1 if is_stacked(joined) else 0
        leaf_dims = x.ndim - off
        base: tuple
        def _q8_dense_name() -> str | None:
            # QuantDense leaves: .../<dense>/kernel_q8 and .../<dense>/scale
            # (norm layers also own a "scale" param — only dense parents
            # count). Returns the dense module name or None.
            parts = joined.rsplit("/", 2)
            if len(parts) == 3 and parts[2] in ("kernel_q8", "scale") \
                    and parts[1] in ("q", "k", "v", "o", "wi", "wg", "wo"):
                return parts[1]
            return None

        q8name = _q8_dense_name()
        if q8name is not None:
            # int8 serving leaves shard on the SAME logical axes as their
            # bf16 kernels, on the flattened dims: out_flat carries the
            # kernel's leading output axis ("heads"/"mlp"/"embed"), which
            # QuantDense's shard_map branch runs as shard-local
            # column-parallel pallas calls; o/wo in_flat carries the
            # row-parallel axis (psum over partial products).
            # GQA k/v (smaller out_flat than q) keep the always-replicated
            # "kv_heads" so a big tensor axis never splits n_kv_heads.
            parent = joined.rsplit("/", 2)[0]
            if q8name in ("k", "v"):
                q_out = q8_out.get(parent)
                grouped = q_out is not None and x.shape[-1] != q_out
                out_ax = "kv_heads" if grouped else "heads"
            else:
                out_ax = {"q": "heads", "o": "embed", "wi": "mlp",
                          "wg": "mlp", "wo": "embed"}[q8name]
            in_ax = {"q": "embed", "k": "embed", "v": "embed",
                     "o": "heads", "wi": "embed", "wg": "embed",
                     "wo": "mlp"}[q8name]
            base = (in_ax, out_ax) if joined.endswith("/kernel_q8") \
                else (out_ax,)
            return ("layers",) + base if off else base
        if joined.endswith(("/kernel/a", "/kernel/b")):
            # LoRA adapters: A [in, r] shards its input dim like the host
            # kernel's input; B [r, *out] carries the kernel's output axes
            # (rank stays replicated — it is tiny)
            kj = joined[: -2]  # .../kernel
            if "/q/" in kj:
                kin, kout = "embed", ("heads", "kv")
            elif "/k/" in kj or "/v/" in kj:
                s2 = "/k/" if "/k/" in kj else "/v/"
                parent = kj.rsplit(s2, 1)[0]
                grouped = (joined.endswith("/b") and x.ndim >= 2 + off
                           and x.shape[1 + off] != head_counts.get(
                               parent, x.shape[1 + off]))
                kin, kout = "embed", ("kv_heads" if grouped else "heads",
                                      "kv")
            elif "/wi/" in kj or "/wg/" in kj:
                kin, kout = "embed", ("mlp",)
            elif "/wo/" in kj:
                kin, kout = "mlp", ("embed",)
            else:  # o (two contracted input dims) and anything exotic
                base = (None,) * leaf_dims
                return ("layers",) + base if off else base
            base = (kin, None) if joined.endswith("/a") \
                else ((None,) + kout)[:leaf_dims]
            return ("layers",) + tuple(base) if off else tuple(base)
        if joined.endswith("/bias"):
            base = bias_axes(joined, x, off, leaf_dims)
        elif "pos_embedding" in joined:
            base = (None, "embed")
        elif "embedding" in joined or "lm_head" in joined:
            # truncation matters: lm_head_bias is rank-1 ("vocab",)
            base = ("vocab", "embed")[:leaf_dims]
        elif "/q/" in joined:
            base = ("embed", "heads", "kv")[:leaf_dims]
        elif any(s in joined for s in ("/k/", "/v/")):
            s = "/k/" if "/k/" in joined else "/v/"
            parent = joined.rsplit(s, 1)[0]
            grouped = (leaf_dims == 3 and x.shape[1 + off] !=
                       head_counts.get(parent, x.shape[1 + off]))
            base = ("embed", "kv_heads" if grouped else "heads",
                    "kv")[:leaf_dims]
        elif "/o/" in joined:
            # note: NOT endswith("o/kernel") — that would also capture
            # the MLP's "wo/kernel"
            base = ("heads", "kv", "embed")[:leaf_dims]
        elif "router" in joined:
            base = (None, None)
        # MoE expert weights: must match parallel.moe.moe_logical_axes()
        # (single source of truth for 3-dim expert params). Dense MLP
        # kernels live at .../wi/kernel; MoE expert arrays are the leaf
        # .../moe/wi itself
        elif "/wi/" in joined or "/wg/" in joined \
                or joined.endswith(("/wi", "/wg")):
            base = moe_logical_axes()["wi"] if leaf_dims == 3 \
                else ("embed", "mlp")
        elif "/wo/" in joined or joined.endswith("/wo"):
            base = moe_logical_axes()["wo"] if leaf_dims == 3 \
                else ("mlp", "embed")
        else:
            base = tuple([None] * leaf_dims)
        return ("layers",) + tuple(base) if off else tuple(base)

    return jax.tree_util.tree_map_with_path(axes_for, params)


def moe_aux_loss(losses: Any, weight: float = 0.01):
    """Sum the sown MoE load-balancing losses from a ``losses`` collection
    (as returned by ``model.apply(..., mutable=["losses"])``)."""
    leaves = jax.tree_util.tree_leaves(losses)
    if not leaves:
        return jnp.float32(0.0)
    return weight * sum(jnp.sum(leaf) for leaf in leaves)
