"""The event-driven edge (ISSUE-16): connection-plane behavior that
thread-per-connection servers get wrong — slow readers, half-open
sockets, trickled uploads, connection breakers — pinned against a fake
gateway so the suite needs no jax and runs in milliseconds.

The fake implements exactly the surface both edges consume: submit()
with the on_event callback contract (("tokens", ids) / ("done", res,
metrics) / ("shed", status, reason)), health()/snapshot()/ready for
the GET routes, and register_edge() for the /stats edge block."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from tony_tpu.gateway.core import GatewayQueueFull, QuotaExceeded
from tony_tpu.gateway.edge import GatewayEdge
from tony_tpu.gateway.http import GatewayHTTP

pytestmark = pytest.mark.filterwarnings("ignore::ResourceWarning")


class _Result:
    def __init__(self, rid, prompt, tokens):
        self.id = rid
        self.prompt = list(prompt)
        self.tokens = list(tokens)
        self.finish_reason = "length"


class _Ticket:
    def __init__(self, request):
        import queue

        self.request = request
        self.events = queue.Queue()  # the threaded edge's consumer


class FakeGateway:
    """Event-contract double: submit() immediately streams scripted
    events from a worker thread, like replica threads do."""

    def __init__(self, script=None, shed=None, delay_s=0.0,
                 tokens_per_event=2, events=2):
        self.ready = True
        self.draining = False
        self.n_healthy = 1
        self.traces = None
        self._edge = None
        self.script = script
        self.shed = shed
        self.delay_s = delay_s
        self.tokens_per_event = tokens_per_event
        self.events = events
        self.submits = 0
        self.threads: list[threading.Thread] = []

    def register_edge(self, fn):
        self._edge = fn

    def health(self):
        return {"status": "ok", "healthy": 1, "replicas": []}

    def snapshot(self):
        out = {"completed": self.submits, "ready": self.ready}
        if self._edge is not None:
            out["edge"] = self._edge()
        return out

    def goodput_report(self):
        return {"goodput": 1.0}

    def submit(self, request, on_event=None):
        self.submits += 1
        if self.shed is not None:
            raise self.shed
        ticket = _Ticket(request)
        if on_event is None:  # the threaded edge reads ticket.events
            def on_event(t, event):
                t.events.put(event)

        def run():
            if self.script is not None:
                self.script(ticket, on_event)
                return
            toks = []
            for i in range(self.events):
                time.sleep(self.delay_s)
                batch = list(range(i * self.tokens_per_event,
                                   (i + 1) * self.tokens_per_event))
                toks.extend(batch)
                on_event(ticket, ("tokens", batch))
            res = _Result(request.id, request.prompt, toks)
            on_event(ticket, ("done", res, {"tokens_out": len(toks)}))

        t = threading.Thread(target=run, daemon=True)
        self.threads.append(t)
        t.start()
        return ticket


@pytest.fixture()
def edge_factory():
    """Yields a make(gateway, **kw) -> (edge, base_url) helper that
    tears every edge down at test end."""
    edges = []

    def make(gw, **kw):
        edge = GatewayEdge(gw, port=0, **kw).start()
        edges.append(edge)
        return edge, f"http://{edge.host}:{edge.port}"

    yield make
    for e in edges:
        e.stop()


def _post(url, doc, timeout=30):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _connect(url):
    host, port = url.split("//")[1].split(":")
    s = socket.create_connection((host, int(port)), timeout=30)
    return s


def _raw_request(sock, body: bytes, stream=True):
    doc = body if isinstance(body, bytes) else json.dumps(body).encode()
    sock.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Type: application/json\r\n"
                 b"Content-Length: " + str(len(doc)).encode()
                 + b"\r\n\r\n" + doc)


def _edge_stats(gw):
    return gw.snapshot()["edge"]


def _wait(pred, timeout=10.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------------- routes

def test_edge_routes_and_unary(edge_factory):
    gw = FakeGateway(events=2, tokens_per_event=2)
    _, url = edge_factory(gw)
    health = json.loads(urllib.request.urlopen(
        url + "/healthz", timeout=30).read())
    assert health["status"] == "ok"
    assert urllib.request.urlopen(url + "/readyz",
                                  timeout=30).status == 200
    doc = json.loads(_post(url, {"token_ids": [1, 2],
                                 "max_new_tokens": 4,
                                 "id": "u"}).read())
    assert doc["id"] == "u" and doc["request_id"] == "u"
    assert doc["token_ids"] == [1, 2, 0, 1, 2, 3]
    assert doc["finish_reason"] == "length"
    stats = json.loads(urllib.request.urlopen(
        url + "/stats", timeout=30).read())
    assert stats["edge"]["kind"] == "event"
    assert stats["edge"]["threads"] == 1 + stats["edge"]["workers"]
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", timeout=30)
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"max_new_tokens": 4})
    assert e.value.code == 400  # no token_ids/prompt


def test_edge_shed_maps_status_and_retry_after(edge_factory):
    gw = FakeGateway(shed=GatewayQueueFull("queue full"))
    _, url = edge_factory(gw)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"token_ids": [1]})
    assert e.value.code == 429
    gw.shed = QuotaExceeded("quota", retry_after_s=3.0)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"token_ids": [1]})
    assert e.value.code == 429
    assert e.value.headers.get("Retry-After") == "3"


def test_edge_streaming_token_exact(edge_factory):
    gw = FakeGateway(events=3, tokens_per_event=2)
    _, url = edge_factory(gw)
    resp = _post(url, {"token_ids": [7, 8], "max_new_tokens": 6,
                       "stream": True, "id": "s"})
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    lines = [json.loads(ln) for ln in resp.read().decode().splitlines()]
    toks = [t for ln in lines[:-1] for t in ln["token_ids"]]
    assert lines[-1]["finish_reason"] == "length"
    assert lines[-1]["token_ids"] == [7, 8] + toks
    assert toks == [0, 1, 2, 3, 4, 5]


# -------------------------------------------------- stream keepalives

@pytest.mark.parametrize("edge_kind", ["event", "threaded"])
def test_stream_keepalives_pinned_both_edges(edge_kind, edge_factory):
    """A quiet stream gets {"keepalive": true} frames at the keepalive
    cadence on BOTH edges; they carry no token_ids, so reassembling
    deltas while filtering keepalives stays token-exact. This is the
    documented client contract — a client that naively extends on
    every line would still be correct (keepalives have no token_ids),
    but one that errors on unknown lines would break: pinned here."""
    gw = FakeGateway(events=2, tokens_per_event=1, delay_s=0.6)
    if edge_kind == "event":
        _, url = edge_factory(gw, keepalive_s=0.1)
        http = None
    else:
        http = GatewayHTTP(gw, port=0, keepalive_s=0.1).start()
        url = f"http://{http.host}:{http.port}"
    try:
        resp = _post(url, {"token_ids": [1], "stream": True, "id": "k"})
        lines = [json.loads(ln)
                 for ln in resp.read().decode().splitlines()]
    finally:
        if http is not None:
            http.stop()
    keepalives = [ln for ln in lines if ln.get("keepalive")]
    assert keepalives, lines  # the 0.6 s gap must emit at least one
    assert all("token_ids" not in ln for ln in keepalives)
    toks = [t for ln in lines
            if "finish_reason" not in ln
            for t in ln.get("token_ids", [])]
    assert toks == [0, 1]
    assert lines[-1]["token_ids"] == [1] + toks


# ---------------------------------------------------- slow client

def test_slow_reader_aborted_counted_co_tenant_unharmed(edge_factory):
    """A client that stops reading mid-stream while the server keeps
    producing must be aborted by the slow-client policy (bounded write
    buffer + drain timeout), counted, with its slot freed — and a
    co-tenant request during AND after the abort must complete
    normally (never a 500, never a stall)."""
    stop = threading.Event()

    def firehose(ticket, on_event):
        # ~14 MB if nobody aborts: far past every kernel buffer
        # (tcp_wmem autotunes to 4 MB), so a reader that stalls MUST
        # trip the drain timeout
        n = 0
        while not stop.is_set() and n < 4000:
            on_event(ticket, ("tokens", list(range(512))))
            n += 1
            if n % 100 == 0:
                time.sleep(0.01)
        res = _Result(ticket.request.id, ticket.request.prompt, [0])
        on_event(ticket, ("done", res, {}))

    gw = FakeGateway(script=firehose)
    _, url = edge_factory(gw, write_buffer_kb=16, drain_timeout_s=0.3)
    host, port = url.split("//")[1].split(":")
    s = socket.socket()
    # BEFORE connect: caps the advertised receive window, so the
    # server side can't stash megabytes in flight
    s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    s.connect((host, int(port)))
    _raw_request(s, {"token_ids": [1], "stream": True, "id": "slow"})
    # read a little to commit headers, then go silent
    assert s.recv(256)
    _wait(lambda: _edge_stats(gw)["slow_client_aborts"] >= 1,
          timeout=20, msg="slow client abort")
    # co-tenant on a fresh connection: normal service
    gw.script = None
    doc = json.loads(_post(url, {"token_ids": [5], "id": "co"}).read())
    assert doc["id"] == "co"
    stop.set()
    _wait(lambda: _edge_stats(gw)["active_streams"] == 0,
          timeout=20, msg="stream slot freed")
    s.close()


def test_disconnect_without_fin_frees_slot(edge_factory):
    """A client that vanishes mid-stream (RST, no FIN) must be
    detected by the edge's write path, its connection and stream slot
    freed, and the disconnect counted — not a hung handler thread."""
    stop = threading.Event()

    def drip(ticket, on_event):
        while not stop.is_set():
            on_event(ticket, ("tokens", [1, 2, 3]))
            time.sleep(0.02)
        res = _Result(ticket.request.id, ticket.request.prompt, [0])
        on_event(ticket, ("done", res, {}))

    gw = FakeGateway(script=drip)
    _, url = edge_factory(gw)
    s = _connect(url)
    _raw_request(s, {"token_ids": [1], "stream": True, "id": "rst"})
    assert s.recv(256)
    # SO_LINGER 0 close() sends RST: the hard-vanish case
    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                 b"\x01\x00\x00\x00\x00\x00\x00\x00")
    s.close()
    _wait(lambda: _edge_stats(gw)["active_streams"] == 0,
          timeout=20, msg="stream slot freed after RST")
    stats = _edge_stats(gw)
    assert (stats["client_disconnects"] >= 1
            or stats["slow_client_aborts"] >= 1), stats
    stop.set()
    # the edge still serves: co-tenant sanity
    gw.script = None
    assert json.loads(_post(url, {"token_ids": [2],
                                  "id": "after"}).read())["id"] == "after"


def test_trickled_post_body_408_bounded(edge_factory):
    """A request body that dribbles in must be bounded by the io
    timeout (408 + close), not hold a parser slot forever. Idle
    keep-alive connections are exempt: only a STARTED request is on
    the clock."""
    gw = FakeGateway()
    _, url = edge_factory(gw, io_timeout_s=0.4)
    s = _connect(url)
    s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              b"Content-Length: 1000\r\n\r\n{\"tok")  # ...and stall
    buf = b""
    t0 = time.monotonic()
    while b"\r\n\r\n" not in buf and time.monotonic() - t0 < 15:
        chunk = s.recv(4096)
        if not chunk:
            break
        buf += chunk
    assert b" 408 " in buf.split(b"\r\n", 1)[0], buf[:200]
    s.close()


def test_idle_keepalive_connection_outlives_io_timeout(edge_factory):
    """An idle keep-alive connection sits PAST the io timeout for
    free, then still serves a request: the timeout clock only starts
    at a request's first byte (that's what makes 10k parked
    connections cost zero threads and zero timers)."""
    gw = FakeGateway(events=1, tokens_per_event=1)
    _, url = edge_factory(gw, io_timeout_s=0.3)
    s = _connect(url)
    time.sleep(1.0)  # 3x the io timeout: must NOT be reaped
    _raw_request(s, {"token_ids": [1], "id": "idle"})
    buf = b""
    t0 = time.monotonic()
    while b"\r\n\r\n" not in buf and time.monotonic() - t0 < 15:
        buf += s.recv(4096)
    assert b" 200 " in buf.split(b"\r\n", 1)[0], buf[:200]
    s.close()


# ------------------------------------------------- connection breaker

def test_connection_limit_breaker_503_retry_after(edge_factory):
    gw = FakeGateway(events=1, tokens_per_event=1)
    _, url = edge_factory(gw, max_connections=4)
    parked = [_connect(url) for _ in range(4)]
    _wait(lambda: _edge_stats(gw)["open_connections"] >= 4,
          timeout=10, msg="4 parked connections")
    s = _connect(url)
    _raw_request(s, {"token_ids": [1], "id": "over"})
    buf = b""
    t0 = time.monotonic()
    while time.monotonic() - t0 < 15:
        chunk = s.recv(4096)
        if not chunk:
            break
        buf += chunk
    head = buf.split(b"\r\n\r\n", 1)[0]
    assert b" 503 " in head.split(b"\r\n", 1)[0], buf[:200]
    assert b"retry-after" in head.lower(), head
    assert _edge_stats(gw)["conn_limit_sheds"] >= 1
    s.close()
    for p in parked:
        p.close()
    # breaker recovers once load drops
    _wait(lambda: _edge_stats(gw)["open_connections"] == 0,
          timeout=10, msg="connections drained")
    doc = json.loads(_post(url, {"token_ids": [1], "id": "ok"}).read())
    assert doc["id"] == "ok"


def test_edge_stats_detach_on_stop():
    gw = FakeGateway()
    edge = GatewayEdge(gw, port=0).start()
    assert "edge" in gw.snapshot()
    edge.stop()
    assert "edge" not in gw.snapshot()


def test_unary_shed_is_clean_error(edge_factory):
    """A mid-request shed (engine gave up) maps to its real status on
    the unary path too — not a 500, not a hang."""

    def shed_late(ticket, on_event):
        on_event(ticket, ("tokens", [1]))
        on_event(ticket, ("shed", 504, "deadline exceeded"))

    gw = FakeGateway(script=shed_late)
    _, url = edge_factory(gw)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"token_ids": [1], "id": "late"})
    assert e.value.code == 504


def test_mid_stream_shed_terminates_stream(edge_factory):
    """Once headers are committed a shed can't change the status —
    the stream ends with an in-band error doc + clean terminator."""

    def shed_mid(ticket, on_event):
        on_event(ticket, ("tokens", [1, 2]))
        time.sleep(0.05)
        on_event(ticket, ("shed", 504, "deadline exceeded"))

    gw = FakeGateway(script=shed_mid)
    _, url = edge_factory(gw)
    resp = _post(url, {"token_ids": [9], "stream": True, "id": "ms"})
    assert resp.status == 200  # already committed
    lines = [json.loads(ln) for ln in resp.read().decode().splitlines()]
    assert lines[0]["token_ids"] == [1, 2]
    assert lines[-1]["error"] and lines[-1]["status"] == 504
