"""Remote replicas: the gateway-side stub over a replica agent.

The other half of ``serve/agent.py`` — the piece that closes the TonY
loop for serving: the ApplicationMaster doesn't run the work, it
acquires hosts and SUPERVISES the TaskExecutors running there.
``RemoteServer`` presents the exact ``serve.Server`` surface the
in-process ``_Replica`` scheduler drives (``submit`` / ``step`` /
``live_progress`` / ``counters`` / ``reset`` / ``slots``), so
routing, WFQ admission, deadlines, autoscaling and the stats rollups
work UNCHANGED over a replica that lives on another machine. What
changes is only what a network adds:

- **Lease heartbeats**: a heartbeat thread GETs the agent's
  ``/healthz`` every ``heartbeat_interval_s``; each success pings a
  ``coordinator/liveness.LivenessMonitor`` lease (the same expiry
  machinery TonY's AM runs over its task heartbeats). No successful
  heartbeat for the lease horizon — dead process, network partition,
  black hole, it cannot matter which — expires the lease, and the
  bound supervisor callback funnels into the gateway's existing
  ``_fail_replica`` -> token-exact failover. A dead host is just a
  wedged replica.
- **The epoch fence, over the wire**: every call carries the stub's
  epoch and every agent response echoes one. ``reset()`` (the
  breaker's recovery step) bumps the epoch; readers discard any line
  carrying an older echo (``stale_epoch_drops``), and the agent
  itself refuses calls older than what it has adopted (409) — a
  wedged-then-revived host can neither deliver stale tokens nor
  accept stale work.
- **Resume, not failover, for connection blips**: every in-flight
  request streams at absolute token offsets, so a dropped connection
  to a HEALTHY agent reconnects at ``offset = tokens already held``
  and the stream continues exactly — no retry budget charged, no
  replica failed. Connect errors retry with capped exponential
  backoff + jitter *within* the lease (a transient blip is not a
  failover); only the lease decides death.
- **ONE multiplexed channel per replica** (ISSUE-16, the default):
  all of a replica's ticket streams ride a single long-lived
  ``POST /v1/channel`` connection as tagged NDJSON frames
  (``{"rid", "off", "token_ids"}`` / ``{"rid", "done", "result"}``),
  demuxed by ONE thread — connections and reader threads stop
  scaling with the replica's batch size. Reconnect re-establishes
  every in-flight stream at its offset in one round trip (the resume
  map rides the request body); the epoch fence and the PR-15 obs
  batches ride the same frames. ``agent_channel="per-ticket"``
  (``--agent-channel`` in the CLI) keeps the original
  one-connection-per-stream path as the A/B control.
- **Typed refusals**: the agent maps engine refusals to ``kind`` tags
  and the stub re-raises the real types (``QueueFull``,
  ``PoolExhausted``, ``ValueError``), so the gateway's admission
  paths cannot tell local from remote.
- **Live migration, over the wire** (ISSUE-18): ``submit`` ships a
  frozen session (``request.migrate``) to ``POST /v1/migrate_in``,
  and ``extract_session`` freezes a live slot OUT of the agent via
  ``POST /v1/migrate_out``. Owner-swap payloads (shared-pool page
  ids from a co-located source) are gathered to page CONTENT here —
  in place, consuming the transfer ref exactly once, so a retried or
  requeued ticket ships the gathered copy instead of dangling ids.
  The agent's bounded radix summary rides every heartbeat, and
  ``prefix_match_len`` scores it with the same grain-grid probe the
  local store uses — prefix affinity can now prefer a REMOTE replica
  that holds the prompt's prefix over a cold local one.
- **The observability plane, pulled over the wire** (ISSUE-15): an
  obs-puller rides the heartbeat cadence — after each successful
  ``/healthz`` it GETs ``/v1/obs?cursor=`` and lands the agent's
  incremental dispatch-timeline records, lifetime per-kind summary,
  and goodput ledger into a ``RemoteTimeline``/``goodput()`` that
  present the exact ``server.timeline``/``server.goodput()`` surface
  a local engine has, so ``/stats engine.dispatch``, the fleet
  goodput rollup, ``/debug/goodput``, the ``goodput_collapse`` alert,
  and per-request trace grafting work UNCHANGED over a remote
  replica. Record timestamps arrive in the AGENT's monotonic clock
  and are corrected by an RTT-midpoint offset estimate (EWMA over
  heartbeats: ``offset = agent_t_mono - heartbeat midpoint``,
  uncertainty = RTT/2) — honest-but-uncertain, so the offset AND its
  uncertainty ride every grafted span and export as
  ``tony_transport_clock_offset_ms``. A pull that fails degrades to
  staleness (``obs.lag_s`` grows, ``pull_errors`` counts), never to a
  replica failure: observability must not be able to take serving
  down.

Transport fault injection (``serve/faults.py`` transport ops, armed
via ``TONY_SERVE_FAULTS`` -> ``FaultPlan.transport_from_env``) hooks
the two choke points here — once per HTTP call, once per stream read
— so refuse / black-hole / delay / disconnect-mid-stream / half-open
are all deterministic, testable failure modes instead of hardware
folklore.
"""

from __future__ import annotations

import http.client
import json
import logging
import random
import subprocess
import sys
import threading
import time
from collections import deque
from types import SimpleNamespace

from tony_tpu.obs.timeline import record_from_doc
from tony_tpu.serve.agent import result_from_doc
from tony_tpu.serve.engine import PoolExhausted, QueueFull, Request
from tony_tpu.serve.prefix import summary_match_len

log = logging.getLogger(__name__)


def close_server(server, what: str) -> None:
    """Best-effort close of a replica server's remote machinery
    (lease/heartbeat threads, launched agent reaping) — a no-op for
    local engines, which have no ``close``. The ONE teardown helper
    every retire/destroy/drain path shares: teardown trouble is a
    logged event, never a dead caller."""
    close = getattr(server, "close", None)
    if close is None:
        return
    try:
        close()
    except Exception:
        log.exception("%s: remote server close failed", what)


class AgentHTTPError(RuntimeError):
    """A non-200 the agent answered deliberately (vs a transport
    error): carries the status and the parsed body."""

    def __init__(self, status: int, doc: dict):
        super().__init__(f"agent answered {status}: "
                         f"{doc.get('error', '(no error body)')}")
        self.status = status
        self.doc = doc


class AgentTransport:
    """One agent's HTTP client: JSON calls + NDJSON streams, an epoch
    header on everything, fault hooks at the choke points, and capped
    exponential backoff with jitter on CONNECT errors (refused/reset
    before a response) — the in-lease transient-blip absorber. Read
    timeouts are never retried here: the caller already paid the
    wait, and the lease is the authority on death.

    Control calls (``call()``: healthz / obs / submit / reset / drain)
    ride ONE persistent keep-alive connection (ISSUE-16): a heartbeat
    every second used to pay a TCP handshake every second, and under
    load the submits compounded that. The connection is rebuilt on any
    error; a REUSED connection that fails is the classic stale-keep-
    alive race (the agent closed it between our calls), so those
    failures stay in the retryable class — one backoff lap gets a
    fresh socket. Per-call timeout bounds still apply (the socket's
    deadline is set per request), so the obs pull's lease-slack bound
    carries over unchanged."""

    def __init__(self, address: str, *, connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 5.0, connect_retries: int = 3,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 0.5,
                 fault_plan=None):
        host, _, port = address.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"agent address must be host:port, "
                             f"got {address!r}")
        self.address = address
        self.host, self.port = host, int(port)
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self.connect_retries = max(0, connect_retries)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.fault_plan = fault_plan
        # transport observability (the /stats ``transport`` block)
        self.retries = 0         # connect-error retries that happened
        self.connect_errors = 0  # connect errors seen (pre-retry)
        self._lock = threading.Lock()
        self._rng = random.Random(0xA9E27 ^ hash(address))
        # the persistent control connection: all call()s serialize on
        # it (they are small and bounded; streams get their own
        # sockets). None = rebuild on next use.
        self._ctrl: http.client.HTTPConnection | None = None
        self._ctrl_lock = threading.Lock()

    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2 ** attempt))
        # full jitter (half to full of the computed backoff): retries
        # from many stubs against one recovering host must not arrive
        # in lockstep
        with self._lock:
            return base * (0.5 + 0.5 * self._rng.random())

    def close(self) -> None:
        """Drop the persistent control connection (stub shutdown)."""
        with self._ctrl_lock:
            self._drop_ctrl()

    def _drop_ctrl(self) -> None:
        # caller holds _ctrl_lock
        if self._ctrl is not None:
            try:
                self._ctrl.close()
            except Exception:  # noqa: BLE001 — closing a broken socket
                pass
            self._ctrl = None

    def _ctrl_roundtrip(self, method: str, path: str,
                        body: bytes | None, epoch: int,
                        timeout: float) -> tuple[int, bytes]:
        """One request/response on the persistent control connection.
        Caller holds ``_ctrl_lock``."""
        if self._ctrl is None:
            self._ctrl = http.client.HTTPConnection(
                self.host, self.port, timeout=self.connect_timeout_s)
            self._ctrl.connect()
        conn = self._ctrl
        if conn.sock is not None:
            # the per-call deadline (heartbeat bound, obs lease-slack
            # bound, drain budget) applies to THIS round trip, not the
            # connection's construction default
            conn.sock.settimeout(timeout)
        conn.request(method, path, body=body, headers={
            "X-Tony-Epoch": str(epoch),
            "Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.will_close:
            # the agent asked to close (its >=400 replies do): honor it
            # now rather than discovering a dead socket next call
            self._drop_ctrl()
        return resp.status, data

    def call(self, method: str, path: str, doc: dict | None = None,
             *, epoch: int = 0, request=None,
             timeout: float | None = None) -> dict:
        """One JSON request/response over the persistent control
        connection. Raises ``AgentHTTPError`` on a non-200,
        ``ConnectionError``/``TimeoutError`` on transport failure
        (after in-lease connect retries)."""
        attempt = 0
        tmo = timeout if timeout is not None else self.read_timeout_s
        body = None if doc is None else json.dumps(doc).encode()
        while True:
            reused = False
            try:
                # the fault hook INSIDE the retry scope: an injected
                # refusal must exercise the same backoff path a real
                # one would, or the chaos tests prove nothing
                if self.fault_plan is not None:
                    self.fault_plan.on_call(f"{method} {path}",
                                            request=request)
                with self._ctrl_lock:
                    reused = self._ctrl is not None
                    try:
                        status, data = self._ctrl_roundtrip(
                            method, path, body, epoch, tmo)
                    except BaseException:
                        self._drop_ctrl()  # never reuse a socket in an
                        raise              # unknown protocol state
                out = json.loads(data) if data else {}
                if status != 200:
                    raise AgentHTTPError(status, out)
                return out
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException) as e:
                # retryable: refused-class (dead port mid-restart), or
                # ANY non-timeout failure on a REUSED connection — the
                # agent may simply have closed the idle keep-alive
                # under us (HTTPException covers the garbled half-read
                # that race can leave). Timeouts are never retried:
                # the caller already paid the wait.
                retryable = isinstance(e, (ConnectionRefusedError,
                                           ConnectionResetError,
                                           BrokenPipeError)) \
                    or (reused and not isinstance(e, TimeoutError))
                with self._lock:
                    self.connect_errors += 1
                if not retryable or attempt >= self.connect_retries:
                    if isinstance(e, http.client.HTTPException) and \
                            not isinstance(e, ConnectionError):
                        # callers catch the ConnectionError family;
                        # a garbled response is transport trouble too
                        raise ConnectionError(
                            f"garbled agent response: {e!r}") from e
                    raise
                with self._lock:
                    self.retries += 1
                time.sleep(self._backoff(attempt))
                attempt += 1

    def stream_lines(self, path: str, *, epoch: int = 0, request=None,
                     method: str = "GET", doc: dict | None = None):
        """Generator over one NDJSON stream's parsed docs (its own
        dedicated socket — never the control connection). Transport
        trouble mid-stream raises; a clean server-side close just ends
        the generator (the reader's resume logic treats both as a
        disconnect). No internal retry — resume-by-offset IS the
        retry, and it needs the caller's current offset.

        A line that fails to parse is NOT fatal: it yields a
        ``{"_garbled": true}`` sentinel so the reader can count it and
        resync (reconnect at the offsets it holds) instead of dying —
        one corrupt frame on a multiplexed channel must not take down
        every stream riding it."""
        if self.fault_plan is not None:
            self.fault_plan.on_call(f"{method} {path}", request=request)
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.read_timeout_s)
        try:
            body = None if doc is None else json.dumps(doc).encode()
            conn.request(method, path, body=body,
                         headers={"X-Tony-Epoch": str(epoch),
                                  "Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                raise AgentHTTPError(resp.status,
                                     json.loads(resp.read() or b"{}"))
            while True:
                if self.fault_plan is not None:
                    self.fault_plan.on_stream(path, request=request)
                line = resp.readline()
                if not line:
                    return
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    yield {"_garbled": True}
        except (ConnectionError, TimeoutError, OSError):
            with self._lock:
                self.connect_errors += 1
            raise
        finally:
            conn.close()


class RemoteTimeline:
    """The pulled twin of ``obs.timeline.DispatchTimeline``: holds the
    agent's timeline as the obs-puller lands it, presenting the two
    methods the gateway reads — ``take_new`` (the replica thread's
    trace attacher drains pulled records exactly like a local ring)
    and ``summary`` (the agent's LIFETIME per-kind aggregates,
    relayed verbatim so ``/stats`` dispatch blocks and the
    ``DispatchTimeline.merge`` fleet rollup cannot tell local from
    remote). Sequence numbers are LOCAL (assigned at push): the
    agent's own seq space restarts when the agent does, and the
    consumer-side cursor must never rewind."""

    def __init__(self, pending_capacity: int = 4096):
        self._lock = threading.Lock()
        # BOUNDED like the local ring: the consumer (the replica
        # thread's trace attacher) never drains when gateway tracing
        # is off (--trace-capacity 0) or while the replica is parked
        # broken, and an unbounded pending queue would turn the obs
        # puller into a slow memory leak. Overflow drops the OLDEST
        # records — lost debug spans, never lost memory.
        self._pending: deque = deque(maxlen=max(1, pending_capacity))
        self._summary: dict = {}
        self._seq = 0

    def push(self, records: list, summary: dict) -> None:
        """Obs-puller entry: append offset-corrected records, adopt
        the newest lifetime summary."""
        with self._lock:
            for rec in records:
                self._seq += 1
                rec.seq = self._seq
                self._pending.append(rec)
            if summary:
                self._summary = summary

    def take_new(self, cursor: int) -> tuple[list, int]:
        with self._lock:
            new = [r for r in self._pending if r.seq > cursor]
            self._pending.clear()
            return new, self._seq

    def summary(self) -> dict:
        with self._lock:
            return dict(self._summary)


class _RemoteTicket:
    """One in-flight request's stub-side record: the absolute token
    sequence received so far plus the terminal result doc.

    ``confirmed`` = the agent's submit response has been read. In mux
    mode tickets register BEFORE the submit POST (the channel can race
    a fast engine and deliver frames before the POST returns — they
    must find the ticket), so an agent-side ``gone`` frame is only
    believed for confirmed tickets: before confirmation it just means
    the channel's resume raced our in-flight submit."""

    __slots__ = ("id", "epoch", "tokens", "result", "confirmed")

    def __init__(self, request_id, epoch: int, confirmed: bool = True):
        self.id = request_id
        self.epoch = epoch
        self.tokens: list[int] = []
        self.result: dict | None = None
        self.confirmed = confirmed


class _RemoteSlots:
    """The ``server.slots`` view the ``_Replica`` scheduler reads:
    slot occupancy mirrors the agent's batch, tracked stub-side as
    in-flight tickets (the stub never over-admits past it)."""

    def __init__(self, remote: "RemoteServer", batch_size: int):
        self._remote = remote
        self.batch_size = batch_size

    @property
    def n_active(self) -> int:
        return len(self._remote._tickets)

    def free_slots(self) -> list[int]:
        return list(range(max(0, self.batch_size - self.n_active)))


class RemoteServer:
    """The ``serve.Server``-shaped stub over one replica agent. See
    the module docstring; the ``_Replica`` scheduler drives this
    exactly like a local engine."""

    # surface parity with serve.Server attributes the gateway reads
    fault_plan = None  # engine faults live on the AGENT's engine

    # the obs channel's path — an attribute so tests (and an operator
    # against a pre-ISSUE-15 agent) can point it at nothing and watch
    # the degrade-to-staleness contract instead of a failure
    _OBS_PATH = "/v1/obs"

    def __init__(self, address: str, *, heartbeat_interval_s: float = 1.0,
                 lease_misses: int = 5, connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 5.0, boot_timeout_s: float = 60.0,
                 stall_timeout_s: float = 30.0, obs_pull: bool = True,
                 agent_channel: str = "mux", migrate_delta: bool = True,
                 transport_faults=None, agent_proc=None):
        if agent_channel not in ("mux", "per-ticket"):
            raise ValueError(f"agent_channel must be 'mux' or "
                             f"'per-ticket', got {agent_channel!r}")
        self.transport = AgentTransport(
            address, connect_timeout_s=connect_timeout_s,
            read_timeout_s=read_timeout_s, fault_plan=transport_faults)
        self.transport_faults = transport_faults
        self.host_addr = address
        # ISSUE-16: "mux" (default) carries every ticket's stream +
        # the obs batches over ONE long-lived /v1/channel connection
        # demuxed by a single thread; "per-ticket" is the original
        # one-connection-one-thread-per-stream path, kept for A/B
        self.agent_channel = agent_channel
        self._channel_thread: threading.Thread | None = None
        self.heartbeat_interval_s = max(0.05, heartbeat_interval_s)
        self.lease_misses = max(1, lease_misses)
        self.stall_timeout_s = stall_timeout_s
        self.agent_proc = agent_proc  # a subprocess we launched (owned)
        self.epoch = 0
        self._tickets: dict = {}
        self._cond = threading.Condition()
        self._progress = False
        self._dead: str | None = None
        self._closed = False
        self._on_dead = None
        self._monitor = None
        self._lease_paused = False  # recovery masks expiries (ISSUE-20)
        self._hb_thread: threading.Thread | None = None
        # transport observability
        self._stats_lock = threading.Lock()
        self.reconnects = 0
        self.stale_epoch_drops = 0
        self.lease_expiries = 0
        self.heartbeat_failures = 0
        self.garbled_frames = 0  # corrupt NDJSON frames survived
        # prefix-delta wire migration (ISSUE-19): trim migrate docs
        # against the agent's heartbeat radix summary; a StaleDelta
        # refusal re-ships the full payload once
        self.migrate_delta = bool(migrate_delta)
        self.migrate_delta_trims = 0      # docs shipped suffix-only
        self.migrate_delta_fallbacks = 0  # stale summary -> full re-ship
        self._rtt_ms = 0.0  # EMA over heartbeat round trips
        self._last_hb = time.monotonic()
        # fleet observability (ISSUE-15): the pulled timeline/ledger +
        # the clock-offset model. offset = agent monotonic - gateway
        # monotonic, EWMA'd over heartbeat RTT midpoints; uncertainty
        # is the EWMA'd half-RTT — the honest error bar every grafted
        # span carries.
        self.timeline = RemoteTimeline()
        # _obs_enabled is the configuration (what obs_stats reports);
        # _obs_pull is the live gate (tests freeze it to compare the
        # two scrape surfaces against one immutable pulled state)
        self._obs_enabled = bool(obs_pull)
        self._obs_pull = bool(obs_pull)
        self._obs_cursor = 0
        # agent seqs landed via stream terminal lines (pruned to
        # > cursor at every successful pull): the dedup between the
        # two record paths — cursor pulls and per-request fragments
        self._obs_stream_seen: set[int] = set()
        self.obs_pulls = 0
        self.obs_pull_errors = 0
        self._last_obs: float | None = None
        self._obs_goodput: dict | None = None
        self._clock_off_ms = 0.0
        self._clock_unc_ms = 0.0
        self._clock_samples = 0
        info = self._wait_ready(boot_timeout_s)
        self.agent_id = info.get("agent_id", "?")
        self.model = SimpleNamespace(cfg=SimpleNamespace(
            max_seq_len=int(info["max_seq_len"])))
        self.slots = _RemoteSlots(self, int(info["batch_size"]))
        self.paged = bool(info.get("paged", False))
        self.speculate_k = int(info.get("speculate_k", 0))
        # the engine-summary probe reads ``prefix is not None``
        self.prefix = True if info.get("prefix") else None
        self._counters = dict(info.get("counters", {}))
        # the agent's bounded radix summary ([[n_tokens, crc32], ...]),
        # refreshed on every heartbeat — what prefix_match_len scores
        self._prefix_summary = list(info.get("prefix_summary") or [])

    # ------------------------------------------------------------ boot

    def _wait_ready(self, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                doc = self.transport.call("GET", "/healthz",
                                          epoch=self.epoch)
                if doc.get("ok"):
                    return doc
                last = RuntimeError(f"agent not ok: "
                                    f"{doc.get('failed') or 'draining'}")
            except (ConnectionError, TimeoutError, OSError,
                    AgentHTTPError) as e:
                last = e
            time.sleep(0.1)
        raise RuntimeError(
            f"replica agent at {self.host_addr} not ready after "
            f"{timeout_s:.0f}s: {type(last).__name__}: {last}")

    # ----------------------------------------------------- supervision

    def bind_supervisor(self, on_dead) -> None:
        """Called by ``_Replica``: arms the lease. ``on_dead(reason)``
        is the funnel into ``Gateway._fail_replica`` — fired (at most
        once per outage) when the agent misses a whole lease of
        heartbeats. Re-binding replaces the callback (the heartbeat
        machinery starts once)."""
        from tony_tpu.coordinator.liveness import LivenessMonitor

        self._on_dead = on_dead
        if self._monitor is None:
            self._monitor = LivenessMonitor(
                interval_ms=max(1, int(self.heartbeat_interval_s * 1000)),
                max_missed=self.lease_misses,
                on_expired=self._lease_expired).start()
            self._monitor.register("agent")
            self._hb_thread = threading.Thread(
                target=self._hb_loop,
                name=f"agent-hb-{self.host_addr}", daemon=True)
            self._hb_thread.start()

    @property
    def lease_s(self) -> float:
        """The lease horizon (the LivenessMonitor expiry formula)."""
        return self.heartbeat_interval_s * max(3, self.lease_misses)

    def _hb_loop(self) -> None:
        while not self._closed:
            t0 = time.monotonic()
            reachable = False
            try:
                doc = self.transport.call(
                    "GET", "/healthz", epoch=self.epoch,
                    timeout=max(self.heartbeat_interval_s, 2.0))
                t1 = time.monotonic()
                reachable = True
                # clock-offset model: the agent read its monotonic
                # clock somewhere inside [t0, t1]; the midpoint is the
                # unbiased estimate and half the RTT bounds the error.
                # EWMA'd like the rtt so one jittery round trip cannot
                # whipsaw every span correction.
                agent_t = doc.get("t_mono")
                if isinstance(agent_t, (int, float)):
                    off_ms = (float(agent_t) - (t0 + t1) / 2.0) * 1e3
                    unc_ms = (t1 - t0) / 2.0 * 1e3
                    with self._stats_lock:
                        if self._clock_samples == 0:
                            self._clock_off_ms = off_ms
                            self._clock_unc_ms = unc_ms
                        else:
                            self._clock_off_ms = 0.8 * self._clock_off_ms \
                                + 0.2 * off_ms
                            self._clock_unc_ms = 0.8 * self._clock_unc_ms \
                                + 0.2 * unc_ms
                        self._clock_samples += 1
                busy = doc.get("n_active", 0) or doc.get("n_pending", 0)
                wedged = bool(busy) and \
                    doc.get("stepper_age_s", 0.0) > self.stall_timeout_s
                if doc.get("ok") and not wedged:
                    rtt_ms = (t1 - t0) * 1e3
                    with self._stats_lock:
                        self._rtt_ms = rtt_ms if self._rtt_ms == 0.0 \
                            else 0.8 * self._rtt_ms + 0.2 * rtt_ms
                        self._last_hb = time.monotonic()
                    counters = doc.get("counters")
                    if isinstance(counters, dict):
                        self._counters = counters
                    summary = doc.get("prefix_summary")
                    if isinstance(summary, list):
                        # atomic swap; readers never see a partial list
                        self._prefix_summary = summary
                    # register (not ping): also RESURRECTS the lease
                    # entry after an expiry once the agent is back
                    if self._monitor is not None:
                        self._monitor.register("agent")
                else:
                    # the agent process answered but its engine is
                    # failed/draining — or busy with a stepper that
                    # stopped beating (a WEDGED dispatch behind a
                    # healthy HTTP face): alive on the network, dead
                    # for serving — no lease ping, same as silence
                    with self._stats_lock:
                        self.heartbeat_failures += 1
            except (ConnectionError, TimeoutError, OSError,
                    AgentHTTPError, ValueError,
                    http.client.HTTPException):
                with self._stats_lock:
                    self.heartbeat_failures += 1
            if reachable and self._obs_pull:
                # the obs-puller rides the heartbeat cadence, but only
                # when the host just answered: an unreachable host
                # must cost ONE timeout per beat, not two. Pulled even
                # when the engine is failed/draining — a failing agent
                # is the one whose timeline an operator wants most.
                # Belt-and-braces except: ANY escape here would kill
                # the heartbeat thread and fail a healthy replica via
                # lease expiry — the exact inversion of the channel's
                # degrade-to-staleness contract.
                try:
                    self._pull_obs()
                except Exception:  # noqa: BLE001 — see above
                    log.exception("obs pull failed unexpectedly")
                    with self._stats_lock:
                        self.obs_pull_errors += 1
            left = self.heartbeat_interval_s - (time.monotonic() - t0)
            if left > 0:
                time.sleep(left)

    def _pull_obs(self) -> None:
        """One incremental observability pull (see the module
        docstring). ANY failure degrades to staleness — counted in
        ``pull_errors``, visible as a growing ``obs.lag_s`` — and
        never touches the lease or the dead marker: the obs channel
        must not be able to fail a serving replica."""
        try:
            # timeout bounded by the LEASE SLACK, not the read
            # timeout: the pull shares the heartbeat thread, and an
            # agent that answers /healthz promptly but stalls on
            # /v1/obs must not delay the next lease ping past the
            # horizon — a slow obs channel degrades to a failed pull,
            # never to a false lease expiry on a healthy replica
            doc = self.transport.call(
                "GET", f"{self._OBS_PATH}?cursor={self._obs_cursor}",
                epoch=self.epoch,
                timeout=max(0.1, min(max(self.heartbeat_interval_s,
                                         2.0), self.lease_s / 3.0)))
        except (ConnectionError, TimeoutError, OSError,
                AgentHTTPError, ValueError,
                http.client.HTTPException):
            # HTTPException too: a garbled response (BadStatusLine,
            # IncompleteRead mid-restart) is neither an OSError nor a
            # ValueError, and it must degrade like any other bad pull
            with self._stats_lock:
                self.obs_pull_errors += 1
            return
        self._ingest_obs_batch(doc)

    def _ingest_obs_batch(self, doc: dict) -> None:
        """Land one /v1/obs document — from the heartbeat-cadence GET
        or from an ``obs`` frame riding the multiplexed channel. The
        two producers dedup against each other by cursor/seq inside
        ``_ingest_obs_records``."""
        if not isinstance(doc, dict):
            return
        try:
            cursor = int(doc.get("cursor", self._obs_cursor))
        except (TypeError, ValueError):
            cursor = self._obs_cursor
        summary = doc.get("summary")
        self._ingest_obs_records(doc.get("records") or (),
                                 new_cursor=cursor,
                                 summary=summary
                                 if isinstance(summary, dict) else {})
        goodput = doc.get("goodput")
        with self._stats_lock:
            if isinstance(goodput, dict):
                self._obs_goodput = goodput
            self.obs_pulls += 1
            self._last_obs = time.monotonic()

    def _ingest_obs_records(self, docs, *, new_cursor: int | None = None,
                            summary: dict | None = None) -> None:
        """Convert wire record docs to gateway-clock ``DispatchRecord``s
        and land them in the ``RemoteTimeline``. Two producers feed
        this — the cursor pull (``new_cursor`` set) and a stream's
        terminal-line fragments (``new_cursor`` None) — deduplicated
        by AGENT sequence number: fragments remember their seqs in
        ``_obs_stream_seen`` until a pull's cursor passes them; pulls
        skip seqs a fragment already landed. An agent restart (cursor
        regression) resets the seq space."""
        with self._stats_lock:
            regressed = new_cursor is not None \
                and new_cursor < self._obs_cursor
            if regressed:
                # agent restarted: its seq space began again — and so,
                # possibly, did its CLOCK (a host reboot restarts
                # CLOCK_MONOTONIC): the offset EWMA re-seeds from the
                # next heartbeat (samples==0 assigns directly) instead
                # of blending a wildly stale correction 20% at a time.
                # This batch lands offset-0 (uncorrected); the trace
                # clamp keeps it well-formed.
                self._obs_stream_seen.clear()
                self._clock_off_ms = 0.0
                self._clock_unc_ms = 0.0
                self._clock_samples = 0
            off_s = self._clock_off_ms / 1e3
            off_ms = round(self._clock_off_ms, 3)
            unc_ms = round(self._clock_unc_ms, 3)
            records = []
            for rd in docs:
                try:
                    rec = record_from_doc(rd)
                except (TypeError, ValueError):
                    continue  # one malformed record must not drop all
                if rec.seq in self._obs_stream_seen:
                    continue  # pulled twin of a landed fragment
                if new_cursor is None:
                    if rec.seq <= self._obs_cursor:
                        continue  # the puller already landed it
                    self._obs_stream_seen.add(rec.seq)
                elif not regressed and rec.seq <= self._obs_cursor:
                    # TWO pull producers exist now (the heartbeat GET
                    # and the channel's obs frames): whichever lands a
                    # window second must not re-land its records
                    continue
                # agent monotonic -> gateway monotonic, with the
                # honest error bar stamped on the record (and thus on
                # any trace span grafted from it)
                rec.t0 -= off_s
                rec.tags.setdefault("host", self.host_addr)
                rec.tags["clock_offset_ms"] = off_ms
                rec.tags["clock_offset_unc_ms"] = unc_ms
                records.append(rec)
            if new_cursor is not None:
                self._obs_cursor = new_cursor
                self._obs_stream_seen = {
                    s for s in self._obs_stream_seen if s > new_cursor}
            elif len(self._obs_stream_seen) > 65536:
                # pulls failing for a long time (degraded channel)
                # must not grow the dedup set without bound: keep the
                # most recent window — worst case a long-dead seq
                # re-lands as a duplicate span in a debug trace
                self._obs_stream_seen = set(sorted(
                    self._obs_stream_seen)[-4096:])
        self.timeline.push(records, summary or {})

    def pause_lease(self) -> None:
        """Mask lease expiries — crash recovery's adopt calls can hold
        the ONE control connection for whole seconds (a freeze-for-
        adopt waits out the engine's current dispatch), starving the
        heartbeat GETs behind them; expiring the lease for that would
        fail over the very replica recovery is adopting from. Paused
        expiries re-arm the entry instead of firing the supervisor."""
        self._lease_paused = True

    def resume_lease(self) -> None:
        self._lease_paused = False
        if self._monitor is not None:
            self._monitor.register("agent")

    def _lease_expired(self, task_id: str) -> None:
        if getattr(self, "_lease_paused", False):
            log.info("agent %s lease lapsed during recovery — masked "
                     "(control connection busy with adopts)",
                     self.host_addr)
            if self._monitor is not None:
                self._monitor.register("agent")
            return
        reason = (f"agent {self.host_addr} lease expired: no heartbeat "
                  f"for {self.lease_s:.1f}s")
        with self._stats_lock:
            self.lease_expiries += 1
        self._note_dead(reason)

    def _note_dead(self, reason: str) -> None:
        """Mark the transport dead (``step``/``submit`` raise until the
        next ``reset``) and fire the supervisor funnel."""
        if self._closed:
            return
        with self._cond:
            if self._dead is None:
                self._dead = reason
            self._cond.notify_all()
        cb = self._on_dead
        if cb is not None:
            try:
                cb(reason)
            except Exception:
                log.exception("remote supervisor callback failed")

    # ------------------------------------------------- engine surface

    @property
    def n_pending(self) -> int:
        return 0  # admission maps 1:1 onto agent slots (no stub queue)

    @property
    def n_active(self) -> int:
        return len(self._tickets)

    @property
    def done(self) -> bool:
        return not self._tickets

    def submit(self, request: Request):
        if self._dead:
            raise ConnectionError(self._dead)
        doc = {
            "id": request.id, "prompt": list(request.prompt),
            "max_new_tokens": request.max_new_tokens,
            "temperature": request.temperature, "top_k": request.top_k,
            "seed": request.seed, "epoch": self.epoch,
        }
        # the GATEWAY request id (ISSUE-20), distinct from the
        # per-replica engine id above: the agent parks orphaned
        # sessions under it, so a RESTARTED gateway — which only
        # remembers its own journal's ids — can adopt them back
        rid = getattr(request, "rid", None)
        if rid is not None:
            doc["rid"] = rid
        path = "/v1/submit"
        if request.prefill_only:
            doc["prefill_only"] = True
        if request.handoff is not None:
            # the decode pool's remote intake: ship the page payload
            # base64-leaf-encoded (a pure-router gateway holds it in
            # wire form already; a local prefill replica's device
            # pytree is encoded here) — the agent's engine scatters it
            # into its own pool and the round trip is bitwise
            from tony_tpu.serve.tier import encode_array, encode_payload

            ho = request.handoff
            if "page_ids" in ho:
                # an owner-swap payload (shared-pool page ids) routed
                # off-host after all: gather the content — consuming
                # the transfer ref — and rewrite the dict IN PLACE
                # (ticket and request alias it, so a requeue ships the
                # gathered copy, never dangling ids)
                from tony_tpu.serve.migrate import gather_local

                ho["pages"] = encode_payload(
                    gather_local(ho.pop("pool"), ho.pop("page_ids")))
                if not isinstance(ho["logits"], dict):
                    ho["logits"] = encode_array(ho["logits"])
            pages = ho["pages"]
            logits = ho["logits"]
            doc["handoff"] = {
                "n_tokens": int(ho["n_tokens"]),
                "pages": encode_payload(pages),
                "logits": logits if isinstance(logits, dict)
                else encode_array(logits),
            }
            path = "/v1/handoff"
        mig_full = None
        if request.migrate is not None:
            # live migration intake (ISSUE-18): a frozen session rides
            # /v1/submit's contract to /v1/migrate_in. A LOCAL snapshot
            # (owner-swap page ids) is gathered to wire form first —
            # mutated in place for the same requeue-safety reason as
            # the handoff above: the transfer ref is consumed exactly
            # once, and retries re-ship the encoded content.
            from tony_tpu.serve.migrate import SessionSnapshot, \
                delta_trim_doc, gather_local, snapshot_to_doc
            from tony_tpu.serve.tier import encode_payload

            mig = request.migrate
            if isinstance(mig, SessionSnapshot):
                if mig.local:
                    pool, ids = mig.pool, mig.pages
                    mig.pages = encode_payload(gather_local(pool, ids))
                    mig.local = False
                    mig.pool = None
                mig_full = snapshot_to_doc(mig)
            else:
                mig_full = mig  # already wire form (remote hop)
            # prefix-delta trim (ISSUE-19): when the agent's heartbeat
            # radix summary says it already holds a prefix of this
            # session's context, ship only the uncovered suffix pages.
            # Advisory — a stale summary comes back kind=StaleDelta
            # and the full doc re-ships below.
            trimmed = delta_trim_doc(mig_full, self._prefix_summary) \
                if self.migrate_delta else None
            if trimmed is not None:
                with self._stats_lock:
                    self.migrate_delta_trims += 1
            doc["migrate"] = trimmed if trimmed is not None \
                else mig_full
            path = "/v1/migrate_in"
        # Mux mode pre-registers the ticket: a warm engine can finish
        # the request and the channel deliver every frame BEFORE this
        # submit POST returns — the demux must find the ticket or the
        # result is dropped on the floor. The ticket stays unconfirmed
        # until the response lands so a racing ``gone`` frame (the
        # channel resumed before the agent saw the submit) is ignored.
        pre = self.agent_channel == "mux" and request.id is not None
        if pre:
            with self._cond:
                ticket = _RemoteTicket(request.id, self.epoch,
                                       confirmed=False)
                self._tickets[request.id] = ticket
                self._cond.notify_all()  # wake a parked channel loop
            self._ensure_channel()
        try:
            try:
                resp = self.transport.call("POST", path, doc,
                                           epoch=self.epoch,
                                           request=request.id)
            except AgentHTTPError as e:
                # stale-summary fallback (ISSUE-19): the adopter no
                # longer holds the prefix the trim assumed — re-ship
                # the FULL payload once. Correctness never rests on
                # summary freshness; only the wire-byte win does.
                if e.doc.get("kind", "") != "StaleDelta" \
                        or mig_full is None \
                        or doc.get("migrate") is mig_full:
                    raise
                with self._stats_lock:
                    self.migrate_delta_fallbacks += 1
                doc["migrate"] = mig_full
                resp = self.transport.call("POST", path, doc,
                                           epoch=self.epoch,
                                           request=request.id)
        except AgentHTTPError as e:
            if pre:
                self._unregister(request.id)
            kind = e.doc.get("kind", "")
            if kind == "StaleDelta":
                # a full payload refused as stale is an agent bug —
                # surface it as the invalid-request it claims to be
                raise ValueError(e.doc.get("error", str(e))) from None
            if kind == "QueueFull":
                raise QueueFull(e.doc.get("error", str(e))) from None
            if kind == "PoolExhausted":
                raise PoolExhausted(e.doc.get("error", str(e))) from None
            if e.status == 400 or kind == "ValueError":
                raise ValueError(e.doc.get("error", str(e))) from None
            if e.status == 409:
                with self._stats_lock:
                    self.stale_epoch_drops += 1
            # 409 stale epoch / 503 draining-or-failed: this replica
            # cannot take work right now — surface as a transport
            # failure so the scheduler's failover path owns it
            raise ConnectionError(str(e)) from e
        except Exception:
            if pre:
                self._unregister(request.id)
            raise
        rid = resp.get("id", request.id)
        with self._cond:
            ticket = self._tickets.get(rid) if pre and rid == request.id \
                else None
            if ticket is None or ticket.epoch != self.epoch:
                ticket = _RemoteTicket(rid, self.epoch)
                self._tickets[rid] = ticket
            ticket.confirmed = True
            self._cond.notify_all()  # wake a parked channel loop
        if self.agent_channel == "mux":
            # the multiplexed channel: one demux loop carries every
            # ticket — the agent discovers new tickets automatically,
            # so a submit is just bookkeeping plus (once) the thread
            self._ensure_channel()
        else:
            threading.Thread(target=self._read_stream, args=(ticket,),
                             name=f"agent-stream-{self.host_addr}",
                             daemon=True).start()
        return rid

    def _unregister(self, rid) -> None:
        """Drop a pre-registered ticket whose submit never landed (the
        POST failed) — unless frames already carried a result to it."""
        with self._cond:
            t = self._tickets.get(rid)
            if t is not None and t.result is None and not t.confirmed:
                del self._tickets[rid]

    def extract_session(self, request_id, *, wire: bool = True):
        """Freeze one live session OUT of the agent (ISSUE-18): POST
        /v1/migrate_out returns the wire snapshot of the request's
        decode slot, or ``None`` when the agent holds no live slot for
        the id (finished, still pending, mid-prefill — nothing worth
        moving). Remote snapshots are always wire form; ``wire`` is
        accepted for surface parity with ``serve.Server`` and ignored.

        While the call is in flight the stub ticket is marked
        unconfirmed, so a ``gone`` frame racing on the mux channel
        (the agent drops its ticket the moment the freeze lands) is
        not read as an agent restart. On success the ticket leaves
        with the session — its stream continues from the adopting
        replica at the absolute offset the gateway already holds; on
        anything else it is restored and the stream resumes here."""
        from tony_tpu.serve.migrate import snapshot_from_doc

        if self._dead:
            raise ConnectionError(self._dead)
        with self._cond:
            ticket = self._tickets.get(request_id)
            was_confirmed = True if ticket is None else ticket.confirmed
            if ticket is not None:
                ticket.confirmed = False
        try:
            resp = self.transport.call(
                "POST", "/v1/migrate_out",
                {"id": request_id, "epoch": self.epoch},
                epoch=self.epoch, request=request_id,
                timeout=max(self.transport.read_timeout_s, 30.0))
        except AgentHTTPError as e:
            self._unfreeze(request_id, was_confirmed)
            if e.status == 409:
                with self._stats_lock:
                    self.stale_epoch_drops += 1
            raise ConnectionError(str(e)) from e
        except Exception:
            self._unfreeze(request_id, was_confirmed)
            raise
        if not resp.get("found"):
            self._unfreeze(request_id, was_confirmed)
            return None
        with self._cond:
            self._tickets.pop(request_id, None)
            self._cond.notify_all()
        return snapshot_from_doc(resp["snapshot"])

    def _unfreeze(self, rid, confirmed: bool) -> None:
        """Undo ``extract_session``'s gone-frame suppression when the
        session did NOT leave: the ticket stays live here."""
        with self._cond:
            t = self._tickets.get(rid)
            if t is not None:
                t.confirmed = confirmed
                self._cond.notify_all()

    # ------------------------------------- restart recovery (ISSUE-20)

    def list_parked(self) -> list:
        """GET /v1/parked: the sessions this agent would hand a
        (re)connecting gateway — parked orphan snapshots plus
        finished-but-undelivered results. Read-only, no epoch fence."""
        resp = self.transport.call("GET", "/v1/parked", None,
                                   epoch=self.epoch)
        return list(resp.get("parked") or [])

    def adopt_parked(self, rid):
        """POST /v1/adopt: take one parked session back by GATEWAY
        request id. Returns the raw response doc — ``snapshot`` (wire
        form, feed it to a requeue as ``request.migrate``) or
        ``finished`` + ``result`` — or None on 404 (unknown/reaped:
        the caller re-runs from the prompt). 409 (a second adopter on
        a stale epoch) raises ConnectionError like every other fenced
        call."""
        try:
            resp = self.transport.call(
                "POST", "/v1/adopt", {"id": rid, "epoch": self.epoch},
                epoch=self.epoch, request=rid,
                timeout=max(self.transport.read_timeout_s, 30.0))
        except AgentHTTPError as e:
            if e.status == 404:
                return None
            if e.status == 409:
                with self._stats_lock:
                    self.stale_epoch_drops += 1
            raise ConnectionError(str(e)) from e
        return resp if resp.get("found") else None

    def sync_recovery_epoch(self) -> int:
        """Fence out the PREVIOUS gateway incarnation: read the
        agent's current epoch off /healthz and adopt one past it, so
        our first fenced call bumps the agent forward and any stale
        stream line (or a second recovering gateway racing us on the
        old epoch) is refused by the ordinary PR-5/11 machinery. A
        recovering gateway must NOT ``reset()`` — that would wipe the
        very tickets and parked sessions it came back for."""
        hz = self.transport.call("GET", "/healthz", None)
        self.epoch = max(self.epoch, int(hz.get("epoch", 0)) + 1)
        return self.epoch

    def _ensure_channel(self) -> None:
        with self._stats_lock:
            if self._channel_thread is not None:
                return
            self._channel_thread = threading.Thread(
                target=self._channel_loop,
                name=f"agent-channel-{self.host_addr}", daemon=True)
        self._channel_thread.start()

    def step(self) -> list:
        """One scheduler beat: wait briefly for stream progress, then
        hand back any finished results. Raises when the transport has
        been declared dead — the scheduler's exception route."""
        with self._cond:
            if self._dead:
                raise ConnectionError(self._dead)
            ready = [t for t in self._tickets.values()
                     if t.result is not None]
            if not ready and not self._progress:
                self._cond.wait(timeout=0.05)
                if self._dead:
                    raise ConnectionError(self._dead)
                ready = [t for t in self._tickets.values()
                         if t.result is not None]
            self._progress = False
            for t in ready:
                del self._tickets[t.id]
        return [result_from_doc(t.result) for t in ready]

    def live_progress(self, since: dict | None = None) -> dict:
        with self._cond:
            out = {}
            for t in self._tickets.values():
                start = since.get(t.id, 0) if since else 0
                out[t.id] = t.tokens[start:]
            return out

    def counters(self) -> dict:
        return dict(self._counters)

    def prefix_match_len(self, tokens) -> int:
        """The router's affinity probe, remote flavor (ISSUE-18):
        scored against the radix summary the agent ships on every
        heartbeat — the same grain-grid ``[[n_tokens, crc32], ...]``
        convention the device store and host tier publish, so a
        REMOTE replica holding the prompt's prefix can win routing
        over a cold local one. Staleness is bounded by the heartbeat
        interval; a stale hit costs one suboptimal preference, never
        correctness (the engine re-probes its own store on admit)."""
        return summary_match_len(self._prefix_summary, tokens)

    def goodput(self):
        """The agent engine's goodput ledger, as of the last obs pull
        (None until one lands — an UNOBSERVED replica, distinct from
        an idle one). A copy: ``goodput_report`` annotates rows in
        place and must not mutate the pulled snapshot."""
        with self._stats_lock:
            g = self._obs_goodput
        return dict(g) if g is not None else None

    def reset(self) -> None:
        """The breaker's recovery step, remote flavor: bump the epoch
        (fencing off every outstanding stream and any late agent
        output), drop local tickets, clear the dead marker so probes
        can try again, and hard-reset the AGENT's engine under the new
        epoch (ghost requests on a wedged-then-revived host die
        here). Raises when the agent is unreachable — the recovery
        loop logs and laps."""
        with self._cond:
            self.epoch += 1
            epoch = self.epoch
            self._tickets.clear()
            self._dead = None
            self._progress = False
            self._cond.notify_all()
        try:
            self.transport.call("POST", "/v1/reset", {"epoch": epoch},
                                epoch=epoch, timeout=10.0)
        except (ConnectionError, TimeoutError, OSError) as e:
            raise ConnectionError(
                f"agent {self.host_addr} reset failed: {e}") from e
        except AgentHTTPError as e:
            raise ConnectionError(str(e)) from e

    # -------------------------------------------------- stream reader

    def _channel_loop(self) -> None:
        """The multiplexed channel's ONE demux thread (ISSUE-16): a
        long-lived POST /v1/channel connection carries every ticket's
        stream as tagged frames plus the incremental obs batches; this
        loop places token windows by absolute offset, lands results,
        and on ANY disconnect reconnects with the full resume map —
        every in-flight stream re-established at its offset in one
        round trip. A garbled frame degrades (counted, resynced via
        reconnect — absolute offsets make the resume exact), never
        kills the loop. Parks while the replica is marked dead; the
        breaker's reset() revives it under the bumped epoch."""
        attempt = 0
        while not self._closed:
            with self._cond:
                if self._dead is not None:
                    self._cond.wait(timeout=0.25)
                    continue
                epoch = self.epoch
                resume = [[t.id, len(t.tokens)]
                          for t in self._tickets.values()
                          if t.result is None and t.epoch == epoch]
            body = {"epoch": epoch, "streams": resume}
            if self._obs_enabled:
                with self._stats_lock:
                    body["obs_cursor"] = self._obs_cursor
            # ``resync``: the channel ended deliberately (stale epoch,
            # garbled frame, gap) — reconnect immediately, without the
            # disconnect counter or backoff a NETWORK failure gets
            resync = False
            try:
                for doc in self.transport.stream_lines(
                        "/v1/channel", epoch=epoch, method="POST",
                        doc=body):
                    if self._closed:
                        return
                    if doc.get("_garbled"):
                        with self._stats_lock:
                            self.garbled_frames += 1
                        resync = True
                        break
                    if doc.get("stale") or doc.get("epoch") != epoch:
                        # the fence: the agent (or we) moved on — drop
                        # the channel, reconnect under the current epoch
                        with self._stats_lock:
                            self.stale_epoch_drops += 1
                        resync = True
                        break
                    if doc.get("keepalive") or doc.get("channel"):
                        attempt = 0
                        continue
                    try:
                        if "obs" in doc and "rid" not in doc:
                            # the PR-15 pull, riding the channel
                            if self._obs_pull:
                                self._ingest_obs_batch(doc["obs"])
                            attempt = 0
                            continue
                        if "error" in doc and "rid" not in doc:
                            # the agent's ENGINE failed: same funnel
                            # as a dead dispatch
                            self._note_dead(
                                f"agent {self.host_addr} reported: "
                                f"{doc['error']}")
                            break
                        rid = doc.get("rid")
                        with self._cond:
                            ticket = self._tickets.get(rid)
                        if ticket is None or ticket.epoch != epoch:
                            continue  # collected, or a late frame
                        if doc.get("gone"):
                            if not ticket.confirmed:
                                # channel resume raced an in-flight
                                # submit: the agent hasn't seen the
                                # ticket *yet* — its discovery loop
                                # picks it up once the POST lands
                                continue
                            # the agent no longer knows an in-flight
                            # ticket: it restarted (state gone) —
                            # everything it held must fail over
                            self._note_dead(
                                f"agent {self.host_addr} lost request "
                                f"{rid!r} (agent restart?)")
                            break
                        if "token_ids" in doc:
                            self._place(ticket, int(doc["off"]),
                                        [int(x) for x in
                                         doc["token_ids"]])
                            attempt = 0
                        if doc.get("done"):
                            obs = doc.get("obs")
                            if obs and self._obs_enabled:
                                self._ingest_obs_records(obs)
                            with self._cond:
                                if ticket.epoch == self.epoch:
                                    ticket.result = doc["result"]
                                    self._progress = True
                                    self._cond.notify_all()
                    except Exception as e:
                        # ANY malformed frame — a gap RuntimeError
                        # from _place (a garbled frame HID a window),
                        # a done frame missing its result, an obs
                        # batch that fails to parse — degrades: count
                        # it and resync-reconnect (absolute offsets
                        # make the resume exact). The demux thread
                        # must never die to one bad frame.
                        log.warning("agent %s channel frame rejected "
                                    "(%r) — resyncing",
                                    self.host_addr, e)
                        with self._stats_lock:
                            self.garbled_frames += 1
                        resync = True
                        break
                # EOF without a terminal frame: mid-stream disconnect
            except AgentHTTPError as e:
                if e.status == 409:
                    with self._stats_lock:
                        self.stale_epoch_drops += 1
                    resync = True  # re-open under the adopted epoch
                else:
                    log.warning("agent %s channel error: %s",
                                self.host_addr, e)
            except (ConnectionError, TimeoutError, OSError) as e:
                log.debug("agent %s channel disconnect: %r",
                          self.host_addr, e)
            if self._closed:
                return
            if resync:
                time.sleep(0.01)  # bounds a pathological 409 spin
            else:
                with self._stats_lock:
                    self.reconnects += 1
                time.sleep(self.transport._backoff(attempt))
                attempt = min(attempt + 1, 8)

    def _read_stream(self, ticket: _RemoteTicket) -> None:
        """One in-flight request's reader: follow the agent's NDJSON
        stream, placing token windows by ABSOLUTE offset; on any
        disconnect, resume at the offset already held (reconnect, not
        failover) with capped backoff — until the ticket finishes, the
        epoch moves on, or the replica is declared dead."""
        attempt = 0
        while True:
            with self._cond:
                if (self._closed or self._dead is not None
                        or ticket.result is not None
                        or ticket.epoch != self.epoch
                        or self._tickets.get(ticket.id) is not ticket):
                    return
                offset = len(ticket.tokens)
            path = (f"/v1/stream/{ticket.id}?offset={offset}"
                    f"&epoch={ticket.epoch}")
            try:
                for doc in self.transport.stream_lines(
                        path, epoch=ticket.epoch, request=ticket.id):
                    if doc.get("_garbled"):
                        # corrupt frame: count it and resync by
                        # reconnecting at the offset already held
                        with self._stats_lock:
                            self.garbled_frames += 1
                        break
                    if doc.get("epoch") != ticket.epoch:
                        # a revived host talking from another epoch:
                        # the fence — count and drop the whole stream
                        with self._stats_lock:
                            self.stale_epoch_drops += 1
                        return
                    if doc.get("keepalive"):
                        continue
                    if doc.get("stale"):
                        with self._stats_lock:
                            self.stale_epoch_drops += 1
                        return
                    if "error" in doc:
                        # the agent's ENGINE failed under our request:
                        # same funnel as a dead dispatch
                        self._note_dead(
                            f"agent {self.host_addr} reported: "
                            f"{doc['error']}")
                        return
                    if "token_ids" in doc:
                        self._place(ticket, int(doc["offset"]),
                                    [int(x) for x in doc["token_ids"]])
                        attempt = 0  # progress resets the backoff
                    if doc.get("done"):
                        # the terminal line's per-request dispatch
                        # fragments land BEFORE the result becomes
                        # visible: the scheduler iteration that
                        # delivers this request grafts them first, so
                        # even a shorter-than-one-heartbeat request
                        # finishes with its complete span set
                        obs = doc.get("obs")
                        if obs and self._obs_enabled:
                            self._ingest_obs_records(obs)
                        with self._cond:
                            if ticket.epoch == self.epoch:
                                ticket.result = doc["result"]
                                self._progress = True
                                self._cond.notify_all()
                        return
                # EOF without a terminal line: mid-stream disconnect
            except AgentHTTPError as e:
                if e.status == 409:
                    with self._stats_lock:
                        self.stale_epoch_drops += 1
                    return
                if e.status == 404:
                    # the agent no longer knows this ticket: it
                    # restarted (state gone) — everything it held must
                    # fail over
                    self._note_dead(
                        f"agent {self.host_addr} lost request "
                        f"{ticket.id!r} (agent restart?)")
                    return
                log.warning("agent %s stream error: %s",
                            self.host_addr, e)
            except (ConnectionError, TimeoutError, OSError) as e:
                log.debug("agent %s stream disconnect for %r: %r",
                          self.host_addr, ticket.id, e)
            with self._stats_lock:
                self.reconnects += 1
            time.sleep(self.transport._backoff(attempt))
            attempt = min(attempt + 1, 8)

    def _place(self, ticket: _RemoteTicket, offset: int,
               tokens: list) -> None:
        """Append the absolute window [offset, offset+len) — overlap
        with what we already hold is dropped (resumes may re-send),
        and a gap (can't happen with an honest agent) fails loudly
        rather than corrupting the stream."""
        with self._cond:
            have = len(ticket.tokens)
            if offset > have:
                raise RuntimeError(
                    f"stream gap for {ticket.id!r}: offset {offset} "
                    f"past {have} tokens held")
            new = tokens[have - offset:]
            if new:
                ticket.tokens.extend(new)
                self._progress = True
                self._cond.notify_all()

    # --------------------------------------------------- observability

    def transport_stats(self) -> dict:
        """The per-replica ``transport`` block (/stats, /metrics):
        where the time goes between this gateway and that host."""
        with self._stats_lock:
            return {
                "address": self.host_addr,
                "agent_id": self.agent_id,
                "rtt_ms": round(self._rtt_ms, 3),
                "heartbeat_age_s": round(
                    time.monotonic() - self._last_hb, 3),
                "lease_s": round(self.lease_s, 3),
                # which stream carrier this stub runs ("mux" = one
                # multiplexed /v1/channel connection; "per-ticket" =
                # the A/B control) and the demux loop's resilience
                # counter — a non-zero garbled_frames with healthy
                # streams IS the degrade-don't-die contract working
                "channel": self.agent_channel,
                "garbled_frames": self.garbled_frames,
                "reconnects": self.reconnects,
                "retries": self.transport.retries,
                "connect_errors": self.transport.connect_errors,
                "heartbeat_failures": self.heartbeat_failures,
                "stale_epoch_drops": self.stale_epoch_drops,
                "lease_expiries": self.lease_expiries,
                # prefix-delta wire migration (ISSUE-19)
                "migrate_delta_trims": self.migrate_delta_trims,
                "migrate_delta_fallbacks": self.migrate_delta_fallbacks,
                # the clock-offset model (ISSUE-15): what remote span
                # timestamps were corrected by, and how far off that
                # correction could honestly be
                "clock_offset_ms": round(self._clock_off_ms, 3),
                "clock_offset_unc_ms": round(self._clock_unc_ms, 3),
            }

    def obs_stats(self) -> dict:
        """The per-replica ``obs`` block: the pull channel's health —
        an explicit surface, so a dashboard can tell an IDLE remote
        replica (fresh lag, zero counts) from an UNOBSERVED one
        (growing lag / pull errors / ``lag_s: null`` never pulled)."""
        with self._stats_lock:
            return {
                "enabled": self._obs_enabled,
                "cursor": self._obs_cursor,
                "pulls": self.obs_pulls,
                "pull_errors": self.obs_pull_errors,
                "lag_s": round(time.monotonic() - self._last_obs, 3)
                if self._last_obs is not None else None,
            }

    # ------------------------------------------------------- shutdown

    def close(self, drain_agent: bool | None = None,
              timeout_s: float = 10.0) -> None:
        """Stop the lease/heartbeat machinery and the readers. With
        ``drain_agent`` (default: only for agents this stub LAUNCHED)
        also politely drain the agent and stop its process — the
        scale-down/deprovision path; attached agents are left running
        (they belong to whoever started them)."""
        if self._closed:
            return
        self._closed = True
        if self._monitor is not None:
            self._monitor.stop()
        with self._cond:
            self._cond.notify_all()
        own = self.agent_proc is not None
        if drain_agent is None:
            drain_agent = own
        if drain_agent:
            try:
                self.transport.call("POST", "/v1/drain",
                                    {"timeout_s": timeout_s},
                                    epoch=self.epoch,
                                    timeout=timeout_s + 5.0)
            except (ConnectionError, TimeoutError, OSError,
                    AgentHTTPError) as e:
                log.debug("agent %s drain on close failed: %r",
                          self.host_addr, e)
        if own:
            proc = self.agent_proc
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.transport.close()  # drop the persistent control conn


def launch_local_agent(agent_args: list[str], *, port_file: str,
                       env: dict | None = None,
                       boot_timeout_s: float = 120.0):
    """Launch ``python -m tony_tpu.cli.replica`` as a local subprocess
    and wait for its bound address. The localhost member of the
    launcher family (coordinator/launcher.py): the provisioned-host
    story runs the same CLI via the slice's own channel; a
    StaticProvisioner's localhost "hosts" and the smoke/chaos rounds
    run it here. Returns ``(proc, "host:port")``; the caller owns the
    process (hand it to ``RemoteServer(agent_proc=...)`` so close()
    reaps it)."""
    import os

    cmd = [sys.executable, "-m", "tony_tpu.cli.replica",
           *agent_args, "--port-file", port_file]
    proc = subprocess.Popen(cmd, env=env)
    deadline = time.monotonic() + boot_timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica agent exited {proc.returncode} before "
                f"binding (cmd: {' '.join(cmd)})")
        if os.path.exists(port_file):
            with open(port_file) as f:
                parts = f.read().split()
            if len(parts) == 2:
                return proc, f"{parts[0]}:{parts[1]}"
        time.sleep(0.1)
    proc.terminate()
    raise RuntimeError(f"replica agent did not bind within "
                       f"{boot_timeout_s:.0f}s")
