"""Multi-replica serving front door: admission, deadlines, routing, drain.

The layer above ``tony_tpu.serve``: PR 1's ``Server`` multiplexes many
requests onto ONE resident KV cache; this module multiplexes many
CLIENTS onto N such servers (data-parallel replicas, one scheduler
thread each — the serving analog of TonY's coordinator packing a fleet
of role tasks onto a container pool). The pieces, front to back:

- ``Gateway.submit()`` is the ADMISSION gate: a bounded queue (past
  ``max_queue`` waiting requests it sheds with ``GatewayQueueFull`` ->
  HTTP 429) with a per-request deadline (``ttl_s``); requests whose
  deadline passes while they wait are shed with ``DeadlineExceeded``
  (-> 504) BEFORE they ever occupy a cache slot — a dead client's
  request must not spend decode steps nobody will read.
- Routing picks the replica with the LEAST OUTSTANDING TOKENS
  (queued + in-flight prompt+budget estimate — queue-length routing
  would park a burst of 512-token requests behind one another while a
  replica full of 8-token requests sits idle). A ``session`` key opts
  into affinity (hash -> replica), keeping a conversation's requests
  on one replica.
- Each ``_Replica`` owns a ``serve.Server`` and drives it on its own
  thread: admit from its queue (deadline-checked at the moment a slot
  is actually free), ``step()``, stream per-token deltas to tickets,
  deliver results. The engine's lock-protected ``submit()`` plus this
  single-owner step loop is the whole concurrency story — no lock is
  ever held across a device dispatch.
- ``drain()`` is the SIGTERM story: close the front door (new submits
  shed with ``GatewayClosed`` -> 503), let every replica finish its
  queue and in-flight slots, then join the threads — zero accepted
  requests lost.
- Every finished request records queue-wait / TTFT / TPOT / tokens
  in+out: into the rolling ``/stats`` window (p50/p99), into lifetime
  fixed-bucket histograms (the ``/metrics`` exposition), into a
  ``metrics.MetricsStore`` under ``gateway:replica-<i>`` (the
  coordinator-side sink TaskMetricsMonitor pushes to), and optionally
  into a portal-browsable history job (``GatewayHistory``).
- OBSERVABILITY (the TonY every-job-leaves-a-record story, request
  granularity — ``tony_tpu.obs``, docs/OBSERVABILITY.md): every ticket
  accumulates a span trace (attempt per replica placement, queue-wait,
  the engine dispatches it rode; a failover's both attempts in ONE
  trace) exported as Chrome trace-event JSON via ``/debug/trace/<id>``
  and history ``metrics/traces.jsonl``; the engines' per-dispatch
  timelines surface as ``/stats`` dispatch blocks; ``/metrics`` renders
  everything as Prometheus text; ``POST /debug/profile`` arms an
  on-demand jax.profiler capture polled by the replica threads.
- ADMISSION TIERS (``gateway/admission.py``, docs/SERVING.md): each
  replica's queue is a weighted fair queue over priority tiers
  (``interactive``/``standard``/``batch``) — a saturating batch flood
  cannot starve interactive requests, an idle fleet still gives batch
  its full throughput — with per-tenant token-rate quotas priced as
  immediate 429 + ``Retry-After`` (``QuotaExceeded``), and
  deadline-first ordering within a tier. Stolen (failover) tickets
  keep their tier and are never re-charged quota.
- ELASTICITY (``gateway/autoscale.py`` — the TonY
  acquire-and-release-to-match-the-job loop, serving flavor):
  ``add_replica()`` grows the fleet at runtime, with the newcomer
  entering through the circuit breaker's PROBE path — it joins
  routing only after a real probe generation (which also pays its
  compile warmup off the traffic path); ``remove_replica()`` shrinks
  it over the existing zero-loss drain (the retiring replica leaves
  routing immediately, finishes its queue and in-flight slots, then
  parks RETIRED with its engine released). The ``AutoScaler`` drives
  both from the fleet's own signals (queue depth + oldest wait, shed
  rate, TTFT SLO burn, KV-page pressure) behind hysteresis, cooldowns
  and min/max bounds.
- SUPERVISION (the TonY ApplicationMaster story, ported to serving):
  every replica thread heartbeats per scheduler iteration; a
  ``LivenessMonitor`` watchdog declares a replica failed when its
  beats stop for ``stall_timeout_s`` (a wedged dispatch, not just a
  raised one). Either failure route — exception or stall — bumps the
  replica's EPOCH, steals every ticket it holds, and FAILS THEM OVER:
  queued tickets (which never touched the failed engine) move to a
  healthy replica untouched; engine-admitted tickets are charged one
  attempt, exclude the failed replica, and RE-RUN from their prompt —
  greedy and seeded-sampling decodes are deterministic, so the retry
  reproduces the exact token sequence and the stream emits only the
  tokens past what the client already received (the analog of TonY's
  task retries, token-exact). A ticket out of budget
  (``max_attempts``) or with no healthy replica left sheds **503**
  (retriable) — never 500. The failed replica resets its engine and
  enters the CIRCUIT BREAKER: exponential backoff
  (``breaker_base_s`` doubling to ``breaker_max_s``), then a probe
  generation; success rejoins it to the routing set, repeated failures
  (``quarantine_after`` consecutive) quarantine it. ``/healthz``
  exposes per-replica heartbeat age + breaker state, ``/readyz`` flips
  503 when zero replicas are healthy, and every failure / retry /
  probe / rejoin counts into ``/stats`` ``supervision``.
- REMOTE REPLICAS (``gateway/remote.py``, docs/SERVING.md): a replica
  whose ``server`` is a ``RemoteServer`` stub runs its engine on
  another host behind a replica agent (``serve/agent.py``). The same
  ``_Replica`` scheduler drives it — routing, WFQ, deadlines,
  failover, the breaker and every stats rollup are identical — while
  the stub adds the network layer: a heartbeat LEASE (reusing
  ``coordinator/liveness.LivenessMonitor``) whose expiry funnels into
  ``_fail_replica`` exactly like a watchdog stall, the PR-5 epoch
  fence carried on every call and echoed in every response (stale
  either way is discarded), resumable per-request token streams (a
  dropped connection to a healthy agent is a reconnect at the held
  offset, not a failover), and in-lease connect retries with capped
  jittered backoff. A dead host is just a wedged replica.
- LIVE MIGRATION (``serve/migrate.py``, ISSUE-18): every PLANNED
  topology change — ``remove_replica()`` retirement, the autoscaler's
  scale-down, a ``migrate_session()`` rebalance — moves in-flight
  decode sessions to the survivors instead of finishing or re-running
  them: the source engine freezes each live slot at a dispatch
  boundary into a ``SessionSnapshot`` (pages + sampler/PRNG state +
  absolute emitted offset) and the ticket re-routes carrying it, so
  the stream resumes mid-flight on its new replica, token-exact.
  Between co-located replicas lent one SHARED ``PagePool`` the
  transfer is a zero-copy refcount owner swap (page ids, no KV bytes
  moved); to a remote replica the snapshot rides the agent wire
  (``POST /v1/migrate_in``) over the multiplexed channel. Failures
  mid-migration fall back to the crash path above — re-run from the
  prompt, still token-exact.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import queue
import threading
import time
import uuid
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from tony_tpu.gateway.admission import (DEFAULT_TIER, WFQueue, TenantQuotas,
                                        parse_tier_weights)
from tony_tpu.gateway.admission import DEFAULT_TIER_WEIGHTS as _DEFAULT_WEIGHTS
from tony_tpu.obs import Histogram, RequestTrace, TraceBuffer
from tony_tpu.obs.alerts import AlertBus, default_rules
from tony_tpu.obs.goodput import merge_ledgers
from tony_tpu.obs.timeline import DispatchTimeline
from tony_tpu.serve import PoolExhausted, QueueFull, Request, Server

log = logging.getLogger(__name__)


class Shed(Exception):
    """A request the gateway refused or gave up on; ``http_status`` is
    the status the front door maps it to."""

    http_status = 500

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class BadRequest(Shed):
    http_status = 400


class GatewayQueueFull(Shed):
    http_status = 429


class QuotaExceeded(Shed):
    """The tenant's token bucket can't cover this request right now:
    429 with an honest ``Retry-After`` (seconds until the bucket
    refills enough). Priced at submit, never queued — a tenant's
    overrun cannot occupy queue slots other tenants need."""

    http_status = 429

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(reason)
        self.retry_after_s = max(0.0, retry_after_s)


class GatewayClosed(Shed):
    http_status = 503


class DeadlineExceeded(Shed):
    http_status = 504


class NoHealthyReplicas(Shed):
    """Every replica's breaker is open (or quarantined): the gateway
    sheds clean 503s — retriable service-unavailable, the load
    balancer's signal to back off — until a probe rejoins a replica."""

    http_status = 503


class RetryBudgetExhausted(Shed):
    """The request burned ``max_attempts`` failed engine runs across
    replica failures: shed 503 — retriable (the request was fine, the
    fleet was not), and distinct from ``GatewayClosed`` so a client can
    tell transient fleet trouble from a shutdown in progress."""

    http_status = 503


class _ReplicaUnhealthy(Exception):
    """Internal routing signal: the chosen replica flipped unhealthy
    between route and enqueue — re-route, never queue onto a broken
    replica."""


@dataclass
class GenRequest:
    """One client request. ``ttl_s`` bounds its whole life (queue wait
    included): ``None`` = no deadline. ``session`` opts into replica
    affinity. Sampling knobs mirror ``serve.Request``."""

    prompt: list
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    id: Any = None
    ttl_s: float | None = None
    session: str | None = None
    # multi-tenant admission (gateway/admission.py): ``priority`` names
    # a WFQ tier (None -> "standard"; unknown names are a 400),
    # ``tenant`` keys the token-rate quota bucket (None -> the shared
    # anonymous bucket when quotas are on)
    tenant: str | None = None
    priority: str | None = None
    # set by the HTTP layer: when the front door read the request off
    # the wire (time.monotonic()); the trace's http_receive span —
    # None for in-process submits, whose trace starts at submit
    t_receive: float | None = None


# ticket lifecycle states
QUEUED, RUNNING, DONE, SHED = "QUEUED", "RUNNING", "DONE", "SHED"

# replica health states (the circuit-breaker cycle): HEALTHY routable,
# BROKEN waiting out its breaker backoff, PROBING running the probe
# generation, QUARANTINED out of the rotation for good, RETIRED
# scale-down finished its zero-loss drain and released the engine
HEALTHY, BROKEN, PROBING, QUARANTINED, RETIRED = (
    "healthy", "broken", "probing", "quarantined", "retired")

# window for the per-replica recent-enqueue-rate sensor (queue block)
_ENQ_RATE_WINDOW_S = 10.0


class Ticket:
    """The caller's handle on a submitted request: an event stream plus
    a blocking ``result()``.

    Events (also forwarded to ``on_event`` from the replica thread):
      ("tokens", [ids])          newly generated tokens (streaming)
      ("done", Result, metrics)  finished; metrics = the per-request
                                 observability record (queue_wait_ms,
                                 ttft_ms, tpot_ms, tokens_in/out, ...)
      ("shed", status, reason)   refused after admission (deadline hit
                                 in queue, retry budget / fleet health
                                 exhausted after replica failures)

    On replica failure the ticket is REQUEUED, not shed (see
    ``Gateway._failover``): ``attempts`` counts engine runs that
    failed, ``excluded`` the replicas that failed it. The retry re-runs
    from the prompt; because greedy and seeded-sampling decodes are
    deterministic, the regenerated stream is byte-identical, and
    ``_n_emitted`` makes the replica emit only tokens the client has
    not already received — a mid-stream failover is invisible apart
    from latency.
    """

    def __init__(self, request: GenRequest, ttl_s: float | None,
                 on_event: Callable | None = None):
        self.request = request
        self.ttl_s = ttl_s
        self.t_submit = time.monotonic()
        self.t_queued = self.t_submit  # refreshed per enqueue (failover)
        self.t_admit: float | None = None
        self.t_first: float | None = None
        self.trace: RequestTrace | None = None  # set by Gateway.submit
        self.replica: int | None = None
        self.state = QUEUED
        # admission-tier bookkeeping (set by Gateway.submit): the WFQ
        # tier travels WITH the ticket, so a failover re-enqueue keeps
        # its priority; quota was charged once at submit and never
        # again. queue_pos is the position it joined its (last) queue
        # at — the after-the-fact tier-behavior audit trail.
        self.tier = DEFAULT_TIER
        self.tenant: str | None = None
        self.queue_pos = -1
        # disaggregation state (roles mode only): ``phase`` routes the
        # ticket to its pool ("prefill" until the handoff, "decode"
        # after; None = roleless fleet); ``handoff`` carries the page
        # payload between pools; ``_prefill_meta`` the prefill half's
        # stats, merged into the final request metrics
        self.phase: str | None = None
        self.handoff: Any = None
        self._prefill_meta: dict | None = None
        # live migration (ISSUE-18): the frozen ``SessionSnapshot`` a
        # planned move carries between replicas — set by
        # _relay_migration, consumed (and CLEARED: the payload is
        # one-shot, its transfer ref moves into the adopting slot) by
        # the admission that resumes it. A ticket whose snapshot is
        # gone falls back to the crash path: re-run from the prompt,
        # token-exact.
        self.migrate: Any = None
        self._wfq_key: tuple | None = None  # set by WFQueue.push
        self.metrics: dict | None = None  # the done-event record
        self.events: queue.Queue = queue.Queue()
        self.attempts = 0  # engine runs that FAILED (retry budget)
        self.excluded: set[int] = set()  # replicas that failed it
        self._on_event = on_event
        self._n_emitted = 0  # tokens already streamed out
        self._emit_lock = threading.Lock()  # serializes token emission
        self._shed_exc_cls: type | None = None  # result()'s exception
        #                                         class, when the status
        #                                         alone is ambiguous
        # crash-safe control plane (ISSUE-20): the absolute token
        # sequence emitted so far — what GET /v1/stream/<id>?offset=
        # serves a client that reconnects (possibly across a gateway
        # restart). Invariant: len(_tokens) == _n_emitted, both
        # advanced together under _emit_lock; recovery seeds both from
        # an adopted snapshot's ``generated`` prefix. ``_journal`` is
        # the gateway's write-ahead log when one is armed; ``t_terminal``
        # stamps done/shed so the resume registry can reap the ticket
        # after the park TTL.
        self._tokens: list[int] = []
        self._journal = None
        self.t_terminal: float | None = None
        # the terminal shed, replayable: a client that reconnects
        # after its request was shed gets the same status/reason the
        # live stream carried, not a 404
        self._shed_status: int | None = None
        self._shed_reason = ""

    # estimate used by least-outstanding-tokens routing: the work a
    # replica signs up for when it accepts this ticket
    @property
    def cost(self) -> int:
        return len(self.request.prompt) + self.request.max_new_tokens

    @property
    def deadline(self) -> float | None:
        """Absolute deadline, DERIVED from the original submit time so
        it is structurally impossible for a failover re-enqueue (which
        refreshes ``t_queued``) to extend it: a request gets ``ttl_s``
        of wall clock from submit, across however many replicas it
        visits."""
        return None if self.ttl_s is None else self.t_submit + self.ttl_s

    def _emit(self, event: tuple) -> None:
        self.events.put(event)
        if self._on_event is not None:
            try:
                self._on_event(self, event)
            except Exception:
                log.exception("ticket on_event callback failed")

    def _emit_tokens(self, start: int, tokens: list, now: float) -> None:
        """Emit the absolute window ``[start, start + len(tokens))`` of
        this request's generated sequence, skipping whatever the client
        already has. Advance-and-emit are atomic under a PER-TICKET
        lock, so a failed replica's late delta and its failover
        successor's resumed stream serialize into one exactly-ordered,
        gap-free, duplicate-free client stream (decoding is
        deterministic, so overlapping windows carry identical values —
        whoever wins the lock emits them). A ticket-scoped lock on
        purpose: no replica lock is held across the ``on_event``
        callback, so a slow consumer stalls only its own request."""
        with self._emit_lock:
            if self.state == SHED:
                return  # terminal shed already delivered: no tokens
                #         after the final event
            cur = self._n_emitted
            if cur >= start + len(tokens):
                return
            new = tokens[cur - start:]
            self._n_emitted = cur + len(new)
            self._tokens.extend(new)  # the resume buffer (ISSUE-20)
            if self.t_first is None:
                self.t_first = now
            self._emit(("tokens", new))
        j = self._journal
        if j is not None:
            # outside the emit lock (the journal has its own): the
            # cumulative offset row is idempotent — replay takes the max
            j.emit(self.request.id, self._n_emitted)

    def result(self, timeout: float | None = None):
        """Block until the request finishes; returns the
        ``serve.Result``. Raises the mapped ``Shed`` subclass if the
        gateway gave up on it. Token events are drained silently (use
        ``on_event`` or read ``events`` yourself to stream)."""
        t_end = None if timeout is None else time.monotonic() + timeout
        while True:
            left = None if t_end is None else max(0.0, t_end - time.monotonic())
            try:
                kind, *rest = self.events.get(timeout=left)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.request.id!r} not finished after "
                    f"{timeout}s (state {self.state})") from None
            if kind == "done":
                return rest[0]
            if kind == "shed":
                status, reason = rest
                cls = self._shed_exc_cls or {
                    429: GatewayQueueFull, 503: GatewayClosed,
                    504: DeadlineExceeded}.get(status, Shed)
                exc = cls(reason)
                exc.http_status = status
                raise exc


def _release_snapshot(snap) -> None:
    """Give back the shared-pool transfer ref a LOCAL (owner-swap)
    ``SessionSnapshot`` still holds. Wire snapshots carry content, not
    references — nothing to release."""
    if snap is None or isinstance(snap, dict):
        return
    pool = getattr(snap, "pool", None)
    if not getattr(snap, "local", False) or pool is None:
        return
    try:
        with pool.lock:
            pool.unref([int(p) for p in snap.pages])
    except Exception:
        log.exception("migrate snapshot page release failed")
    snap.local = False
    snap.pool = None


class _SnapLease:
    """The extract-vs-steal handshake (this PR): registered by
    ``_migrate_ticket`` BEFORE it freezes a session, claimed by
    ``_failover`` when the source replica dies with the extract still
    in flight. Without it, a SIGKILL between freeze and ship abandons
    the frozen snapshot — failover re-runs the victim from its prompt
    even when a complete, token-exact snapshot materializes a moment
    later (a remote agent can answer ``/v1/migrate_out`` and die
    before the relay). With it, failover waits a SHORT lease for the
    in-flight extract: complete -> adopt the snapshot (no recompute),
    timeout -> mark it abandoned so the extractor releases it, crash
    path proceeds. All fields are mutated under the gateway's
    ``_lease_lock``; ``done`` doubles as the claimer's wakeup."""

    __slots__ = ("done", "snap", "abandoned", "t0")

    def __init__(self):
        self.done = threading.Event()
        self.snap = None
        self.abandoned = False
        self.t0 = time.monotonic()


def _lease_key(ticket) -> object:
    """Lease key: the gateway request id (what migrate_session is
    addressed by), falling back to the ticket's identity for requests
    submitted without one — extractor and claimer must compute the
    SAME key from the same ticket."""
    rid = ticket.request.id
    return rid if rid is not None else id(ticket)


def _release_ticket_payload(ticket) -> None:
    """Drop (and, for owner-swap forms, unref) the one-shot payloads a
    ticket still carries — run on every terminal path and on the
    refused-payload fallback, so a shed or re-run mid-migration can
    never leak shared-pool pages. Wire payloads hold no references and
    device-tree handoffs stay reusable, so only the id-carrying forms
    are touched."""
    snap, ticket.migrate = ticket.migrate, None
    _release_snapshot(snap)
    ho = ticket.handoff
    if isinstance(ho, dict) and "page_ids" in ho:
        ticket.handoff = None
        pool = ho.get("pool")
        try:
            if pool is not None:
                with pool.lock:
                    pool.unref([int(p) for p in ho["page_ids"]])
        except Exception:
            log.exception("handoff page release failed")


class _Replica:
    """One ``serve.Server`` + the thread that drives it, under
    supervision: the thread heartbeats (``last_beat``) every scheduler
    iteration; ``epoch`` is the fencing token — every failure
    (exception OR watchdog-declared stall) bumps it, and any state the
    thread computed under the old epoch is discarded, so a wedged step
    that eventually returns cannot deliver results for tickets that
    were already failed over to another replica."""

    def __init__(self, index: int, server: Server, gateway: "Gateway"):
        self.index = index
        self.server = server
        self.gateway = gateway
        # disaggregation role (gateway ``roles=``): "any" = generalist
        # (the default), "prefill" = admission/chunked-prefill only
        # (requests leave as page-list handoffs), "decode" = receives
        # handoffs and decodes them
        self.role = "any"
        # REMOTE replicas (gateway/remote.RemoteServer): the server is
        # a stub over an agent on another host — bind its lease
        # machinery into the gateway's failure funnel, and carry the
        # host address so per-request records can name the machine
        # that served them ("local" for in-process thread replicas)
        self.host = getattr(server, "host_addr", "local")
        bind = getattr(server, "bind_supervisor", None)
        if bind is not None:
            bind(lambda reason, _r=self: gateway._fail_remote(_r, reason))
        self.queue = WFQueue(gateway.tier_weights)
        self.cv = threading.Condition()
        self.outstanding = 0  # token-cost estimate: queued + in-flight
        self.completed = 0
        self.shed = 0
        # queue sensors (the /stats "queue" block — the autoscaler's
        # primary pressure signal): lifetime enqueue counter plus a
        # short timestamp ring for the recent enqueue rate
        self.enqueued = 0
        self._enq_times: deque[float] = deque(maxlen=256)
        # scale-down (Gateway.remove_replica): ``retiring`` leaves the
        # routing set immediately while the thread finishes its queue
        # and in-flight slots; ``retired`` marks the drain complete and
        # the engine released
        self.retiring = False
        self.retired = False
        self.spawned = False  # added by add_replica (vs boot-time)
        # supervision / breaker state (all mutated under self.cv except
        # the plain counters, which only this thread or the gateway's
        # failure path touch)
        self.state = HEALTHY
        self.epoch = 0
        self.last_beat = time.monotonic()
        self.failures = 0              # breaker trips, lifetime
        self.consecutive_failures = 0  # since the last delivered result
        self.probes = 0
        self.rejoins = 0
        self._stop = False
        self._exited = False  # the thread left _loop: nothing enqueued
        #                       after this is ever processed
        self._tickets: dict[int, Ticket] = {}  # engine id -> ticket
        self._next_id = 0
        self._tl_cursor = 0  # dispatch-timeline read position (tracing)
        self._probe_first = False  # scale-up: earn admission via probe
        # orders the failure-claim against the breaker (ISSUE-20):
        # _fail_replica holds this across the ticket steal + failover
        # (including the park-adoption probe of the agent), and
        # _recover takes it before its hard engine reset — without the
        # handshake, a lease expiry detected on the monitor thread
        # races the replica thread's breaker entry, and the reset
        # wipes the very agent session _claim_parked came to adopt
        self.fail_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"gateway-replica-{index}",
                                        daemon=True)

    # ---------------------------------------------------------- intake

    def enqueue(self, ticket: Ticket, force: bool = False) -> None:
        """``force=True`` is the FAILOVER entry: a stolen ticket must be
        allowed in even mid-drain (the drain promise covers it), as long
        as this thread is still alive to process it."""
        with self.cv:
            if (self._stop and not force) or self._exited:
                # closes the submit-vs-drain race: a ticket landing
                # after the stop signal could otherwise strand forever
                # on a thread that already exited
                raise GatewayClosed("gateway is draining")
            if self.state != HEALTHY or self.retiring:
                # closes the route-vs-fail race: the router saw this
                # replica healthy, the breaker opened (or a scale-down
                # started retiring it) before the enqueue landed — the
                # caller re-routes
                raise _ReplicaUnhealthy(
                    f"replica {self.index} is "
                    f"{'retiring' if self.retiring else self.state}")
            ticket.replica = self.index
            ticket.t_queued = time.monotonic()
            if ticket.trace is not None:
                # one attempt span per placement on a replica; its
                # epoch is the fencing tag the failover story pivots
                # on, its host names the machine (agent address |
                # "local") — the Chrome export's process row
                ticket.trace.begin_attempt(self.index, self.epoch,
                                           t0=ticket.t_queued,
                                           host=self.host)
            ticket.queue_pos = self.queue.push(ticket)
            self.enqueued += 1
            self._enq_times.append(ticket.t_queued)
            self.outstanding += ticket.cost
            self.cv.notify()
        j = ticket._journal
        if j is not None:
            # WAL route row (ISSUE-20): which replica — and for remote
            # ones, which HOST — this placement landed on, so a
            # recovering gateway knows where to look for the parked
            # session. Outside the cv (the journal has its own lock).
            j.route(ticket.request.id, self.index,
                    None if self.host == "local" else self.host)

    @property
    def n_queued(self) -> int:
        return len(self.queue)

    @property
    def busy(self) -> bool:
        return bool(self._server_busy() or self.queue)

    def queue_signals(self, now: float | None = None) -> dict:
        """The per-replica queue block: depth, oldest-wait age, recent
        enqueue rate, per-tier depths — the autoscaler's primary
        sensor, exported per replica on /stats and /metrics."""
        if now is None:
            now = time.monotonic()
        with self.cv:
            depth = len(self.queue)
            oldest = self.queue.oldest_t_queued()
            recent = sum(1 for t in self._enq_times
                         if now - t <= _ENQ_RATE_WINDOW_S)
            span = _ENQ_RATE_WINDOW_S
            if recent == self._enq_times.maxlen:
                # the ring saturated inside the window: rate over the
                # span actually retained, else heavy bursts (the exact
                # loads this sensor exists for) read as a flat ceiling
                span = max(1e-3, now - self._enq_times[0])
            by_tier = self.queue.depth_by_tier()
        return {
            "depth": depth,
            "oldest_wait_s": round(max(0.0, now - oldest), 3)
            if oldest is not None else 0.0,
            "enqueue_rate_per_s": round(recent / span, 3),
            "by_tier": by_tier,
        }

    # ------------------------------------------------------------ loop

    def start(self, probe_first: bool = False) -> None:
        """``probe_first=True`` is the SCALE-UP entry (add_replica):
        the replica starts BROKEN and runs the circuit breaker's probe
        cycle before it ever joins routing — a new replica earns
        admission exactly the way a recovered one does, and its first
        compiles happen on the probe, off the traffic path."""
        self._probe_first = probe_first
        self._thread.start()

    def signal_stop(self) -> None:
        with self.cv:
            self._stop = True
            self.cv.notify()

    def join(self, timeout: float | None = None) -> None:
        if self._thread.ident is not None:  # join pre-start is an error
            self._thread.join(timeout)

    def _loop(self) -> None:
        if self._probe_first:
            # scale-up path: prove the engine works (and pay its first
            # compiles) through a real probe generation before joining
            # routing — _recover() ends with the rejoin that registers
            # us with the watchdog and flips us HEALTHY
            self._probe_first = False
            if not self._recover():
                return
        while True:
            with self.cv:
                epoch = self.epoch
                while not self.queue and not self._server_busy() \
                        and not self._stop and self.epoch == epoch:
                    self.cv.wait(timeout=self.gateway._beat_interval_s)
                    # beat WHILE idle too — an idle replica that only
                    # beat on work would look stalled to the watchdog
                    self.gateway._beat(self)
                if self._stop and not self.queue \
                        and not self._server_busy():
                    self._exited = True
                    # stop being watched: the watchdog now outlives the
                    # join (it must — a step that wedges DURING drain
                    # still needs its tickets failed over), so a
                    # cleanly-exited thread going silent must not read
                    # as a stall
                    self.gateway._unwatch(self)
                    return
                stale = self.epoch != epoch
            self.gateway._beat(self)
            if stale:
                # the watchdog (or a probe race) declared us failed
                # while we were idle — clean up and re-earn admission
                if not self._recover():
                    return
                continue
            if self.retiring:
                # planned exit (ISSUE-18): hand the work to survivors
                # instead of finishing it here — every loop iteration,
                # so a request that was still mid-prefill last round
                # migrates the moment it reaches a live decode slot
                self._migrate_out(epoch)
            try:
                self._admit_from_queue(epoch)
                with self.cv:
                    stale = self.epoch != epoch
                # declared failed during admission: the engine holds
                # only ghosts now — stepping it would burn a full
                # (multi-dispatch) round whose output is guaranteed to
                # be discarded. _stream_deltas/_deliver fence
                # internally, so the stale flag only skips the step.
                if not stale:
                    busy = self._server_busy()
                    finished = self.server.step() if busy else []
                    if busy:
                        # one WORKING iteration: the on-demand serving
                        # profiler counts it (near-free attribute read
                        # while no capture is armed)
                        self.gateway.profiler.poll()
                    now = time.monotonic()
                    # INSIDE the try: an exception in the delivery half
                    # (a metrics/history consumer, say) must take the
                    # same failover path as a dead dispatch — outside,
                    # it would kill this thread with state still
                    # HEALTHY, a permanently-lost replica no probe can
                    # ever resurrect
                    self._attach_dispatch_spans(epoch)
                    self._stream_deltas(now, epoch)
                    self._deliver(finished, now, epoch)
            except Exception as e:
                # a failed replica must not strand its tickets with no
                # terminal event — but unlike the old shed-everything
                # response, failure here means FAILOVER: the gateway
                # steals every ticket we hold and requeues it on a
                # healthy replica (token-exact re-run); we reset and
                # enter the breaker
                log.exception("replica %d step failed", self.index)
                self.gateway._fail_replica(
                    self, epoch, f"replica {self.index} step failed: "
                    f"{type(e).__name__}: {e}")
                if not self._recover():
                    return
                continue
            with self.cv:
                stale = self.epoch != epoch
            if stale:
                # the step wedged long enough for the watchdog to fire:
                # our tickets are already re-running elsewhere — any
                # output was a previous epoch's and was discarded by
                # the internal fences; re-earn admission
                if not self._recover():
                    return

    def _migrate_out(self, epoch: int) -> None:
        """Retirement accelerator (ISSUE-18), on this replica's own
        thread: a retiring replica moves its work to the survivors
        instead of decoding it to completion. Queued tickets simply
        re-route (they never started); live decode slots freeze into
        ``SessionSnapshot``s and resume mid-stream elsewhere,
        token-exact. Whatever cannot move — no healthy taker, an
        unpaged engine, a request still mid-prefill — keeps running
        here, so the zero-loss drain promise is unchanged; migration
        only makes the drain fast."""
        gw = self.gateway
        # queued first: a ticket that re-routes before admission costs
        # nothing to move
        while True:
            with self.cv:
                if self.epoch != epoch:
                    return
                ticket = self.queue.pop()
            if ticket is None:
                break
            try:
                target = gw._route(ticket,
                                   ticket.excluded | {self.index})
            except NoHealthyReplicas:
                # nobody can take work: keep it and run it here
                with self.cv:
                    if self.epoch == epoch:
                        self.queue.unpop(ticket)
                break
            with self.cv:
                if self.epoch == epoch:
                    self.outstanding = max(
                        0, self.outstanding - ticket.cost)
            if ticket.trace is not None:
                ticket.trace.end_attempt(time.monotonic(),
                                         outcome="moved")
            ticket.state = QUEUED
            ticket.replica = None
            try:
                target.enqueue(ticket, force=True)
            except (GatewayClosed, _ReplicaUnhealthy):
                gw._requeue(self, ticket,
                            f"replica {self.index} retiring")
        # then the live slots: freeze + relay, one at a time
        with self.cv:
            if self.epoch != epoch:
                return
            live = list(self._tickets.items())
        for engine_id, ticket in live:
            gw._migrate_ticket(self, engine_id, ticket, epoch)

    def _server_busy(self) -> bool:
        server = self.server  # single read vs concurrent retirement
        if server is None:  # retired: engine released
            return False
        # n_active, not slots.n_active: a slot parked mid-chunked-
        # prefill holds a request the loop must keep driving
        return bool(server.n_active or server.n_pending)

    def _admit_from_queue(self, epoch: int) -> None:
        """Move tickets into the engine, AT MOST as many as there are
        free slots — the deadline check runs at the moment a slot is
        genuinely available, so an expired request is shed having never
        occupied one (and never cost a prefill dispatch)."""
        free = len(self.server.slots.free_slots()) \
            - self.server.n_pending \
            - getattr(self.server, "n_prefilling", 0)
        while free > 0:
            with self.cv:
                ticket = self.queue.pop()  # the WFQ decision: least
                # virtual work among non-empty tiers, deadline-first
                # within the tier
                if ticket is None:
                    return
            now = time.monotonic()
            if ticket.deadline is not None and now >= ticket.deadline:
                self._shed(ticket, 504,
                           f"deadline exceeded after "
                           f"{now - ticket.t_submit:.3f}s in queue",
                           epoch=epoch)
                continue
            req = ticket.request
            engine_id = self._next_id
            self._next_id += 1
            engine_req = Request(
                list(req.prompt), req.max_new_tokens,
                temperature=req.temperature, top_k=req.top_k,
                seed=req.seed, id=engine_id,
                # role-split plumbing: a prefill-pool replica runs
                # admission/prefill only (the result is a page
                # handoff); a ticket carrying a handoff payload
                # admits it instead of prefilling
                prefill_only=self.role == "prefill",
                handoff=ticket.handoff,
                # a migrated-in session resumes mid-stream: the
                # engine arms a slot from the snapshot instead of
                # prefilling (serve/migrate.py)
                migrate=ticket.migrate)
            # the GATEWAY request id rides along (ISSUE-20): remote
            # stubs ship it so the agent can park an orphaned session
            # under the one id a restarted gateway still knows
            engine_req.rid = req.id
            try:
                self.server.submit(engine_req)
            except QueueFull:
                # engine bound hit (shouldn't happen: we feed at most
                # free-slot many) — put it back and stop admitting.
                # Epoch-fenced like every other path here: appending to
                # a replica whose steal already ran would park the
                # ticket on a BROKEN queue forever
                with self.cv:
                    if self.epoch == epoch:
                        self.queue.unpop(ticket)  # back at its old
                        # position, tier charge refunded
                        return
                self.gateway._failover(
                    self, [], [ticket],
                    f"replica {self.index} failed during admission")
                return
            except PoolExhausted as e:
                # capacity, not malformation: the request can never fit
                # this replica's KV page pool — 503 so a caller against
                # a bigger deployment may legitimately retry
                self._shed(ticket, 503, str(e), epoch=epoch)
                continue
            except ValueError as e:
                if ticket.migrate is not None or (
                        isinstance(ticket.handoff, dict)
                        and "page_ids" in ticket.handoff):
                    # this engine refused the CARRIED state (owner-swap
                    # payload from a pool it does not hold, codec
                    # drift after a topology change) — that is a
                    # placement mistake, not the client's: drop the
                    # payload (refs released) and fall back to the
                    # crash path, a token-exact re-run from the prompt
                    log.warning(
                        "replica %d refused a migrated payload (%s); "
                        "falling back to re-run", self.index, e)
                    _release_ticket_payload(ticket)
                    with self.cv:
                        if self.epoch == epoch:
                            self.queue.unpop(ticket)
                            continue
                    self.gateway._failover(
                        self, [], [ticket],
                        f"replica {self.index} failed during admission")
                    return
                self._shed(ticket, 400, str(e), epoch=epoch)
                continue
            except (ConnectionError, TimeoutError, OSError):
                # REMOTE submit failed in transit (the stub's in-lease
                # retries already ran): put the popped ticket back
                # where the failover steal can find it, then let the
                # raise take the scheduler's exception route into
                # _fail_replica. Epoch-fenced like the QueueFull path:
                # if the steal already ran, this ticket was missed by
                # it and must be failed over directly.
                with self.cv:
                    if self.epoch == epoch:
                        self.queue.unpop(ticket)
                        raise
                self.gateway._failover(
                    self, [], [ticket],
                    f"replica {self.index} transport failed during "
                    f"admission")
                return
            # one-shot payloads are CONSUMED by the submit that
            # succeeded (their transfer ref moved into the engine), so
            # they must not survive on the ticket: a later failover
            # re-submitting a spent owner-swap doc would install
            # dangling page ids. Clearing them degrades that failover
            # to the crash path — re-run from the prompt, token-exact.
            if ticket.migrate is not None:
                ticket.migrate = None
            if isinstance(ticket.handoff, dict) \
                    and "page_ids" in ticket.handoff:
                ticket.handoff = None
            with self.cv:
                if self.epoch != epoch:
                    # declared failed mid-admission: the ticket we just
                    # popped was missed by the steal — requeue it
                    # untouched (the engine ghost dies in the reset)
                    stray = ticket
                else:
                    ticket.t_admit = now
                    ticket.state = RUNNING
                    self._tickets[engine_id] = ticket
                    stray = None
            if stray is not None:
                self.gateway._failover(
                    self, [], [stray],
                    f"replica {self.index} failed during admission")
                return
            if ticket.trace is not None:
                ticket.trace.add("queue_wait", ticket.t_queued, now,
                                 attempt_key=(self.index, epoch),
                                 engine_id=engine_id)
            free -= 1

    def _attach_dispatch_spans(self, epoch: int) -> None:
        """Fold the engine's new ``DispatchRecord``s into the traces of
        the requests that rode them: admit records (prefill/hit_admit/
        cow_admit) carry the engine id they admitted; decode/verify
        records carry
        the engine ids live at dispatch time. Runs on the replica
        thread after each step. Records for tickets already stolen are
        DROPPED by the trace's ``attempt_key`` fence — checked against
        the open attempt's (replica, epoch) tags atomically under the
        trace lock, so even a steal + re-placement racing this snapshot
        cannot mis-attribute a dead replica's dispatch to the
        survivor's attempt.

        REMOTE replicas take this exact path (ISSUE-15): the stub's
        obs-puller lands the agent's dispatch records — offset-
        corrected to this gateway's clock, tagged with the host and
        the offset±uncertainty — in a ``RemoteTimeline`` whose
        ``take_new`` this method drains like any local ring, so one
        trace spans both hosts of a remote failover with zero special
        casing here. Spans attach CLAMPED: the offset correction is an
        estimate, and a few ms of clock error must bend into the
        attempt window rather than corrupt the trace invariants."""
        tl = self.server.timeline
        if tl is None or self.gateway.traces is None:
            return
        new, self._tl_cursor = tl.take_new(self._tl_cursor)
        if not new:
            return
        with self.cv:
            tickets = dict(self._tickets)
        key = (self.index, epoch)
        for rec in new:
            if rec.kind in ("prefill", "prefill_chunk", "hit_admit",
                            "cow_admit", "handoff_admit",
                            "handoff_out", "migrate_out",
                            "migrate_in"):
                targets = [tickets.get(rec.request_id)]
            else:
                targets = [tickets.get(eid)
                           for eid in rec.tags.get("requests", ())]
            t1 = rec.t0 + rec.dur_ms / 1e3
            tags = {k: v for k, v in rec.tags.items() if k != "requests"}
            tags.update(occupancy=rec.occupancy, bucket=rec.bucket,
                        tokens=rec.tokens)
            if rec.compile:
                tags["compile"] = True
            for ticket in targets:
                if ticket is not None and ticket.trace is not None:
                    ticket.trace.add(rec.kind, rec.t0, t1,
                                     attempt_key=key, clamp=True,
                                     **tags)

    def _stream_deltas(self, now: float, epoch: int) -> None:
        with self.cv:
            if self.epoch != epoch:
                return
            tickets = dict(self._tickets)
            emitted = {eid: t._n_emitted for eid, t in tickets.items()}
        progress = self.server.live_progress(emitted)
        # no second epoch fence: emission is offset-based and
        # per-ticket-serialized (Ticket._emit_tokens), so even a delta
        # computed just before a steal lands exactly — the failover
        # replica's resumed stream skips whatever this emit covered,
        # and vice versa. No replica lock is held across the emits.
        for engine_id, new in progress.items():
            ticket = tickets.get(engine_id)
            if ticket is not None and new:
                ticket._emit_tokens(emitted[engine_id], new, now)

    def _deliver(self, finished, now: float, epoch: int) -> None:
        for res in finished:
            with self.cv:
                if self.epoch != epoch:
                    # failed mid-delivery: remaining tickets were
                    # stolen and will re-run token-exactly elsewhere
                    return
                ticket = self._tickets.pop(res.id, None)
                if ticket is not None:
                    self.outstanding = max(0,
                                           self.outstanding - ticket.cost)
                    self.consecutive_failures = 0  # real work
                    # delivered: the breaker's failure streak is over.
                    # Reset INSIDE the fence: unfenced, it could race a
                    # concurrent _fail_replica increment and wipe the
                    # streak a flapping replica needs to reach
                    # quarantine_after
            if ticket is None:
                continue
            if res.finish_reason == "handoff" \
                    and getattr(res, "handoff", None) is not None:
                # the prefill pool's half is done: not a completion —
                # the ticket moves to a decode replica carrying the
                # page payload, and the client sees nothing yet
                self.gateway._relay_handoff(self, ticket, res, now)
                continue
            # the whole sequence as one absolute window: _emit_tokens
            # dedups past the client's cursor, so this emits exactly
            # the un-streamed tail (all of it, for unary requests)
            ticket._emit_tokens(0, res.tokens, now)
            ticket.state = DONE
            self.completed += 1
            metrics = self._request_metrics(ticket, res, now)
            ticket.metrics = metrics  # unary responders read it after
            # result(); same record the stream's final line carries
            res = type(res)(ticket.request.id, res.prompt, res.tokens,
                            res.finish_reason, res.prefix_hit_tokens,
                            res.prefill_tokens_saved,
                            res.drafted, res.accepted,
                            getattr(res, "prefill_chunks", 0))
            if ticket.trace is not None:
                ticket.trace.end_attempt(now, outcome="done")
                ticket.trace.finish(
                    now, outcome="done",
                    finish_reason=res.finish_reason,
                    tokens_in=metrics["tokens_in"],
                    tokens_out=metrics["tokens_out"],
                    ttft_ms=metrics["ttft_ms"],
                    tpot_ms=metrics["tpot_ms"],
                    attempts=ticket.attempts)
                self.gateway._export_trace(ticket)
            self.gateway._record_done(self, metrics)
            ticket.t_terminal = now
            ticket._emit(("done", res, metrics))
            if ticket._journal is not None:
                ticket._journal.done(ticket.request.id)

    def _request_metrics(self, ticket: Ticket, res, now: float) -> dict:
        n_out = len(res.tokens)
        ttft = (ticket.t_first - ticket.t_submit) if ticket.t_first else 0.0
        tpot = ((now - ticket.t_first) / (n_out - 1)
                if n_out > 1 and ticket.t_first else 0.0)
        # role-split requests: the prefill half's savings/chunk counts
        # rode over in the handoff relay; the decode-side Result knows
        # nothing about them
        meta = ticket._prefill_meta or {}
        return {
            **({"prefill_replica": meta["prefill_replica"]}
               if meta else {}),
            "id": ticket.request.id,
            "replica": self.index,
            # WHICH MACHINE served it (agent address for remote
            # replicas, "local" for in-process threads): the field
            # that lets an operator attribute a bad TTFT to a host
            # from the /stats window or history requests.jsonl
            "host": self.host,
            "queue_wait_ms": round(
                (ticket.t_admit - ticket.t_submit) * 1e3, 3),
            "ttft_ms": round(ttft * 1e3, 3),
            "tpot_ms": round(tpot * 1e3, 3),
            "e2e_ms": round((now - ticket.t_submit) * 1e3, 3),
            "tokens_in": len(res.prompt),
            "tokens_out": n_out,
            "prefix_hit_tokens": meta.get("prefix_hit_tokens",
                                          res.prefix_hit_tokens),
            "prefill_tokens_saved": meta.get("prefill_tokens_saved",
                                             res.prefill_tokens_saved),
            "prefill_chunks": meta.get(
                "prefill_chunks", getattr(res, "prefill_chunks", 0)),
            "drafted": res.drafted,
            "accepted": res.accepted,
            "draft_hit_rate": round(res.draft_hit_rate, 4),
            "attempts": ticket.attempts,  # failed engine runs this
            # request survived (0 = no failover; latency fields span
            # the whole life, retries included)
            # tier audit trail (ISSUE-9): which tenant/tier this ran
            # as and the queue position it joined its (last) queue at
            # — so WFQ behavior is checkable after the fact from the
            # /stats window and history requests.jsonl
            "tenant": ticket.tenant,
            "priority": ticket.tier,
            "queue_pos": ticket.queue_pos,
            "finish_reason": res.finish_reason,
        }

    def _shed(self, ticket: Ticket, status: int, reason: str,
              epoch: int | None = None) -> None:
        self.shed += 1
        _release_ticket_payload(ticket)  # a dead ticket must not pin
        #                                  shared-pool pages
        with self.cv:
            if epoch is None or self.epoch == epoch:
                # fenced + clamped: a steal that raced the caller's
                # queue pop already zeroed outstanding wholesale —
                # subtracting again would drive it negative and skew
                # least-outstanding routing forever after rejoin
                self.outstanding = max(0, self.outstanding - ticket.cost)
        self.gateway._record_shed(self, status, tier=ticket.tier)
        if ticket.trace is not None:
            ticket.trace.finish(outcome="shed", status=status,
                                reason=reason)
            self.gateway._export_trace(ticket)
        with ticket._emit_lock:
            # state flip + terminal emit together: a previous owner's
            # late token delta can't land after the final shed event
            ticket.state = SHED
            ticket.t_terminal = time.monotonic()
            ticket._shed_status = status
            ticket._shed_reason = reason
            ticket._emit(("shed", status, reason))
        if ticket._journal is not None:
            ticket._journal.shed(ticket.request.id, status)

    # ------------------------------------------------- breaker recovery

    def _recover(self) -> bool:
        """The circuit-breaker cycle, on this replica's own thread,
        entered after a declared failure (exception or watchdog stall;
        tickets already stolen and failed over by the gateway): reset
        the engine, wait out the exponential backoff, run a PROBE
        generation, and either rejoin the routing set (re-earning the
        watchdog's watch) or go around again. ``quarantine_after``
        consecutive failures (probe failures included) quarantine the
        replica — parked out of the rotation until shutdown. Returns
        False when the gateway is stopping: the thread exits."""
        gw = self.gateway
        first = True
        while True:
            try:
                # first lap: wait out any in-flight _fail_replica (the
                # lease-expiry route runs on the monitor thread) — its
                # _claim_parked must adopt the agent-side session
                # BEFORE this hard reset wipes it (ISSUE-20)
                with self.fail_lock if first \
                        else contextlib.nullcontext():
                    self.server.reset()  # pending + _live + slots
                # together: slots alone would leave engine ghosts
                # decoding phantom results for tickets now re-running
                # elsewhere
            except Exception:
                log.exception("replica %d engine reset failed", self.index)
            first = False
            if self.consecutive_failures >= gw.quarantine_after:
                with self.cv:
                    if self.state != QUARANTINED:
                        self.state = QUARANTINED
                        gw._note_quarantine(self)
                    while not self._stop:  # out of the rotation for
                        # good; park so drain() can still join us
                        self.cv.wait(timeout=gw._beat_interval_s)
                        # refresh like the backoff loop: the thread is
                        # alive and parked BY DESIGN — /healthz must
                        # not show an unboundedly climbing age that
                        # reads as a dead thread
                        self.last_beat = time.monotonic()
                    self._exited = True
                return False
            backoff = min(gw.breaker_max_s, gw.breaker_base_s
                          * (2 ** max(0, self.consecutive_failures - 1)))
            deadline = time.monotonic() + backoff
            with self.cv:
                while not self._stop and time.monotonic() < deadline:
                    self.cv.wait(timeout=min(gw._beat_interval_s,
                                             backoff))
                    self.last_beat = time.monotonic()
                if self._stop:
                    self._exited = True
                    return False
                self.state = PROBING
            self.probes += 1
            gw._note_probe(self)
            t0 = time.monotonic()
            try:
                # a real (tiny) generation through the same engine paths
                # traffic takes — prefill, decode, evict. The fault
                # plan's hooks fire here too, so a ``times=-1`` fault
                # keeps a replica down through every probe.
                self.server.submit(Request([1], max_new_tokens=2,
                                           id="__probe__"))
                for _ in range(64):
                    self.server.step()
                    if self.server.done:
                        break
                else:
                    raise RuntimeError("probe did not finish in 64 steps")
                took = time.monotonic() - t0
                if took > gw.stall_timeout_s:
                    # a wedged-but-eventually-returning probe is a
                    # failed probe: real traffic would have stalled
                    raise RuntimeError(f"probe wedged for {took:.1f}s")
                self.server.reset()
            except Exception as e:  # noqa: BLE001 — ANY probe failure
                # means another breaker lap, never a crashed supervisor
                log.warning("replica %d probe failed: %s: %s",
                            self.index, type(e).__name__, e)
                self.consecutive_failures += 1
                with self.cv:
                    self.state = BROKEN
                continue
            with self.cv:
                self.state = HEALTHY
                self.last_beat = time.monotonic()
            self.rejoins += 1
            gw._note_rejoin(self)
            log.warning("replica %d probe succeeded: rejoining the "
                        "routing set", self.index)
            return True

    def stats(self, include_dispatch: bool = False) -> dict:
        # NOTE: no queue_signals() here — stats() runs on the
        # per-request MetricsStore push (every completion/shed), and
        # the oldest-wait scan is O(queue depth) under the cv. The
        # snapshot path merges the queue block in itself, once per
        # scrape (Gateway.snapshot).
        server = self.server  # single read: remove_replica nulls the
        # attribute concurrently, and a check-then-access would race
        out = {
            "replica": self.index,
            "role": self.role,
            "queued": self.n_queued,
            "enqueued": self.enqueued,
            "active_slots": server.slots.n_active
            if server is not None else 0,
            "batch_size": server.slots.batch_size
            if server is not None else 0,
            "outstanding_tokens": self.outstanding,
            "completed": self.completed,
            "shed": self.shed,
            # supervision: state is a string (MetricsStore's numeric
            # filter drops it; /stats and /healthz carry it)
            "state": self.state,
            "epoch": self.epoch,
            "heartbeat_age_s": round(time.monotonic() - self.last_beat, 3),
            "failures": self.failures,
            "consecutive_failures": self.consecutive_failures,
            "probes": self.probes,
            "rejoins": self.rejoins,
        }
        # engine counters (prefills, decode_steps, dispatches, the
        # prefix_* family) flat, so the MetricsStore numeric filter and
        # /stats both carry them per replica
        if server is not None:
            out.update(server.counters())
        # remote replicas: the transport block (rtt, heartbeat age,
        # reconnects, retries, stale-epoch drops) — nested, so the
        # MetricsStore numeric filter skips it while /stats and
        # /metrics carry it
        ts = getattr(server, "transport_stats", None)
        if ts is not None:
            out["transport"] = ts()
            # the obs-pull channel's health (remote stubs only) — an
            # EXPLICIT block, so "idle replica" and "unobserved
            # replica" are distinguishable from a dashboard
            obs = getattr(server, "obs_stats", None)
            if callable(obs):
                out["obs"] = obs()
        # sharded replicas (ISSUE-14): mesh topology + per-chip
        # residency — nested, so the MetricsStore numeric filter skips
        # it while /stats carries it (the flat mesh_* counters above
        # feed MetricsStore). Remote stubs have no mesh_info; their
        # agents' counters carry the flat twins over the wire.
        mi = getattr(server, "mesh_info", None)
        if callable(mi):
            m = mi()
            if m is not None:
                out["mesh"] = m
        # the per-replica radix summary (nested — the MetricsStore
        # numeric filter skips it): entry/byte/shape counts the
        # affinity router's decisions can be audited against. Behind
        # include_dispatch like the timeline block: the nodes/depth
        # walk is O(tree) and must not run on every completion's
        # metrics push. Remote stubs carry ``prefix = True`` (a
        # bool), hence the stats() duck check.
        if include_dispatch and server is not None:
            prefix = getattr(server, "prefix", None)
            if prefix is not None and hasattr(prefix, "stats"):
                out["prefix"] = prefix.stats()
            tier = getattr(server, "host_tier", None)
            if tier is not None:
                out["kv_host"] = tier.stats()
        # per-dispatch timeline aggregates (kind -> count/ms/compile
        # split/tokens) — opt-in: snapshot() wants it, but the
        # per-request MetricsStore push (whose numeric filter would
        # drop the nested dict anyway) must not pay a summary build on
        # every completion
        if include_dispatch and server is not None \
                and server.timeline is not None:
            out["dispatch"] = server.timeline.summary()
        return out


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


class _Stats:
    """Rolling per-request window + monotonic counters behind /stats."""

    def __init__(self, window: int = 1024):
        self.lock = threading.Lock()
        self.window: deque[dict] = deque(maxlen=window)
        # LIFETIME latency distributions in fixed buckets (seconds) —
        # the /metrics form a scraper can rate() and aggregate, where
        # the rolling window's exact percentiles cannot; both are fed
        # from the same per-request record so they can never disagree
        self.hist = {key: Histogram()
                     for key in ("queue_wait", "ttft", "tpot", "e2e")}
        self.accepted = 0
        self.completed = 0
        self.shed_by_status: dict[int, int] = {}
        # per-tier admission accounting (WFQ observability): lifetime
        # completed/shed counts plus a queue-wait histogram per tier —
        # the surface that proves batch cannot starve interactive
        self.completed_by_tier: dict[str, int] = {}
        self.shed_by_tier: dict[str, int] = {}
        self.tier_wait: dict[str, Histogram] = {}
        self.quota_rejections = 0
        self.tokens_in = 0
        self.tokens_out = 0
        self.prefix_hit_tokens = 0
        self.prefill_tokens_saved = 0
        self.drafted = 0
        self.draft_accepted = 0
        # supervision (the TonY retry-counter analog)
        self.replica_failures = 0  # HEALTHY -> BROKEN transitions
        self.failovers = 0         # tickets requeued onto another replica
        self.retries = 0           # failed engine runs charged to tickets
        self.probes = 0
        self.rejoins = 0
        self.quarantines = 0
        # elasticity (the TonY acquire/release loop): runtime
        # membership changes, however triggered (autoscaler or a
        # direct add_replica/remove_replica call)
        self.replicas_added = 0
        self.replicas_removed = 0
        # disaggregation (ISSUE-12): routing decisions won by the
        # prefix-affinity probe, and prefill->decode handoffs relayed
        self.prefix_routed = 0
        self.handoffs = 0
        # live migration (ISSUE-18): sessions relayed mid-stream to a
        # new replica (retirement drain, scale-down defrag, or a
        # migrate_session rebalance). ``migrate_carry`` holds the
        # migration counters of replicas that RETIRED — the out-side
        # of a retirement drain lives on the engine being released, so
        # without the carry every scale-down would erase its own
        # ledger from /stats
        self.migrations = 0
        self.migrate_carry: dict[str, float] = {}
        # frozen snapshots a FAILOVER adopted instead of re-running
        # from the prompt (the extract-vs-steal lease, this PR): each
        # one is a mid-stream crash whose victim resumed token-exact
        # with no recompute
        self.migrate_lease_adoptions = 0
        # crash recovery (ISSUE-20): ``--recover`` boots that replayed
        # a journal, and what happened to each live entry — adopted
        # mid-stream off a parked agent session (zero re-prefill),
        # re-run from the prompt (local engine died with the process),
        # or materialized from a finished-but-undelivered result.
        # ``park_adoptions`` counts the FAILOVER flavor: a live-crash
        # failover that found the victim's session parked on its agent
        # and resumed it instead of re-running.
        self.recoveries = 0
        self.sessions_adopted = 0
        self.sessions_rerun = 0
        self.recovered_finished = 0
        self.recovery_wall_ms = 0.0
        self.park_adoptions = 0
        # the flight recorder (ISSUE-15): alert-triggered debug
        # bundles dumped into the history job dir
        self.bundles_written = 0
        self.last_bundle = ""

    def snapshot(self) -> dict:
        with self.lock:
            recent = list(self.window)
            out = {
                "accepted": self.accepted,
                "completed": self.completed,
                "shed": dict(self.shed_by_status),
                "tokens_in": self.tokens_in,
                "tokens_out": self.tokens_out,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prefill_tokens_saved": self.prefill_tokens_saved,
                "drafted": self.drafted,
                "draft_accepted": self.draft_accepted,
            }
        for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
            vals = sorted(r[key] for r in recent)
            out[key] = {"p50": _percentile(vals, 0.50),
                        "p95": _percentile(vals, 0.95),
                        "p99": _percentile(vals, 0.99)}
        out["window"] = len(recent)
        return out


class GatewayHistory:
    """Portal hookup: the gateway as a browsable history job.

    Writes the coordinator's on-disk layout (``events/history.py``)
    under ``<history>/intermediate/<app_id>/``: an in-progress
    ``.jhist.jsonl`` event log (inited/finished) plus per-request
    metric rows in ``metrics/requests.jsonl`` — the portal's existing
    /job/<id>/metrics page renders them with zero portal changes, and
    the history mover/purger manage the directory like any other job's.
    """

    def __init__(self, history_root: str, app_id: str = "",
                 n_replicas: int = 1):
        from tony_tpu.events import history
        from tony_tpu.events.event import application_inited

        self._lock = threading.Lock()
        started = int(time.time() * 1000)
        self.app_id = app_id or f"application_gateway_{started}"
        self.started = started
        self.job_dir = history.intermediate_dir(history_root, self.app_id)
        os.makedirs(os.path.join(self.job_dir, "metrics"), exist_ok=True)
        self.jhist = os.path.join(
            self.job_dir, history.inprogress_name(self.app_id, started))
        self._append_event(application_inited(
            self.app_id, n_replicas, os.uname().nodename))
        self._metrics_path = os.path.join(self.job_dir, "metrics",
                                          "requests.jsonl")
        self._traces_path = os.path.join(self.job_dir, "metrics",
                                         "traces.jsonl")
        self._scaling_path = os.path.join(self.job_dir, "metrics",
                                          "scaling.jsonl")
        self._alerts_path = os.path.join(self.job_dir, "metrics",
                                         "alerts.jsonl")
        self._autotune_path = os.path.join(self.job_dir, "metrics",
                                           "autotune.jsonl")
        self._bundles_path = os.path.join(self.job_dir, "metrics",
                                          "bundles.jsonl")
        self._rebalance_path = os.path.join(self.job_dir, "metrics",
                                            "rebalance.jsonl")

    def _append_event(self, event) -> None:
        with self._lock, open(self.jhist, "a") as f:
            f.write(json.dumps(event.to_dict()) + "\n")

    def record(self, row: dict) -> None:
        with self._lock, open(self._metrics_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def record_trace(self, doc: dict) -> None:
        """One finished request's Chrome trace-event doc, one JSON doc
        per line — keyed by the same request id requests.jsonl rows
        carry, so the portal (or an operator's jq) links them."""
        with self._lock, open(self._traces_path, "a") as f:
            f.write(json.dumps(doc) + "\n")

    def record_scaling(self, row: dict) -> None:
        """One autoscaler decision (action, reason, the signals it
        read) in ``metrics/scaling.jsonl`` — rendered by the portal's
        metrics page next to requests.jsonl, so an operator can answer
        "why did the fleet grow at 14:02" from the job history."""
        with self._lock, open(self._scaling_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def record_alert(self, row: dict) -> None:
        """One alert fire/resolve transition in
        ``metrics/alerts.jsonl`` — the portal's metrics page renders
        it next to requests/scaling, so "what was alerting at 14:02"
        is answerable from the job history."""
        with self._lock, open(self._alerts_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def record_autotune(self, row: dict) -> None:
        """One shape-controller actuation (knob, from -> to, the
        ledger signals that justified it, whether it paid a new
        compile) in ``metrics/autotune.jsonl`` — "why did chunk depth
        change at 14:02" is answerable from the job history."""
        with self._lock, open(self._autotune_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def record_rebalance(self, row: dict) -> None:
        """One rebalancer decision (move/no_victim/move_failed, the
        occupancy it saw) in ``metrics/rebalance.jsonl`` — "why did
        request 17 jump replicas at 14:02" is answerable from the job
        history."""
        with self._lock, open(self._rebalance_path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def write_bundle(self, doc: dict) -> str:
        """One debug bundle (the ISSUE-15 flight recorder: active
        alerts, recent traces incl. remote spans, per-replica
        dispatch/goodput/transport/obs blocks, scale signals) as a
        SINGLE self-contained JSON file under ``<job dir>/bundles/``
        — the TonY job-history story at incident granularity: a 3 a.m.
        alert leaves a record the portal (or plain jq) can browse
        after the fleet is long gone. Named by wall-clock ms + the
        triggering alerts, written atomically (tmp + rename) so a
        reader never sees a torn bundle."""
        bundles = os.path.join(self.job_dir, "bundles")
        os.makedirs(bundles, exist_ok=True)
        slug = "-".join(str(t) for t in doc.get("trigger") or ()) \
            or doc.get("reason", "manual")
        slug = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in slug)[:64]
        path = os.path.join(
            bundles, f"bundle-{int(time.time() * 1000)}-{slug}.json")
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        # one POINTER row in metrics/bundles.jsonl per dump: the
        # portal's metrics page renders metrics/*.jsonl with zero
        # portal changes (the alerts.jsonl pattern), so the 3 a.m.
        # incident shows up in the job's browsable history with its
        # trigger, headline numbers, and the bundle file to open
        alerts = doc.get("alerts") or {}
        with self._lock, open(self._bundles_path, "a") as f:
            f.write(json.dumps({
                "t": doc.get("t_wall"),
                "reason": doc.get("reason"),
                "trigger": ",".join(str(t) for t in
                                    doc.get("trigger") or ()),
                "active_alerts": len(alerts.get("active") or ()),
                "replicas": len(doc.get("replicas") or ()),
                "traces": (doc.get("traces") or {}).get("count", 0),
                "path": path,
            }) + "\n")
        return path

    def close(self, status: str = "SUCCEEDED",
              metrics: dict | None = None) -> None:
        from tony_tpu.events import history
        from tony_tpu.events.event import application_finished

        self._append_event(application_finished(
            self.app_id, status, 0, metrics or {}))
        completed = int(time.time() * 1000)
        final = os.path.join(self.job_dir, history.finished_name(
            self.app_id, self.started, completed,
            os.environ.get("USER", "unknown"), status))
        with self._lock:
            os.replace(self.jhist, final)


class _AlertLoop(threading.Thread):
    """The alert bus's evaluation cadence: one consistent
    ``Gateway.alert_signals()`` read per tick through
    ``AlertBus.evaluate()``, transitions logged and appended to
    history ``metrics/alerts.jsonl``. Daemon + stop-event so drain()
    shuts it down before the fleet join (an alert evaluated against a
    half-drained fleet would be noise)."""

    def __init__(self, gateway: "Gateway", interval_s: float):
        super().__init__(name="gateway-alerts", daemon=True)
        self.gateway = gateway
        self.interval_s = max(0.05, interval_s)
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        gw = self.gateway
        while not self._stop.wait(self.interval_s):
            try:
                events = gw.alerts.evaluate(gw.alert_signals())
            except Exception:
                log.exception("alert evaluation failed")
                continue
            for ev in events:
                (log.warning if ev.state == "firing" else log.info)(
                    "alert %s %s: %s %s", ev.alert, ev.state.upper(),
                    ev.message, ev.detail)
                if gw.history is not None:
                    try:
                        gw.history.record_alert(ev.to_row())
                    except Exception:
                        log.exception("history alert write failed")
            # the flight recorder (ISSUE-15): a FIRING transition dumps
            # one self-contained debug bundle into the history job dir
            # — the bus's fire-once dedup is the debounce (no re-dump
            # while the alert stays active), and dump failures are
            # logged, never allowed to take the alert loop down
            firing = [ev.alert for ev in events if ev.state == "firing"]
            if firing and gw.bundle_on_alert:
                gw.dump_bundle(reason="alert", trigger=firing)


class _AutotuneLoop(threading.Thread):
    """The adaptive shape controller's cadence (serve/autotune.py):
    one ``AutotuneController.tick()`` per interval over the LIVE local
    replicas, actuations logged and appended to history
    ``metrics/autotune.jsonl``. Daemon + stop-event, stopped by
    drain() before the fleet join — an actuation mid-shutdown would
    only churn compile state the process is about to drop."""

    def __init__(self, gateway: "Gateway", interval_s: float):
        super().__init__(name="gateway-autotune", daemon=True)
        self.gateway = gateway
        self.interval_s = max(0.05, interval_s)
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        gw = self.gateway
        while not self._stop.wait(self.interval_s):
            try:
                replicas = [(r.index, r.server)
                            for r in gw.live_replicas]
                decisions = gw.autotune.tick(replicas)
            except Exception:
                log.exception("autotune tick failed")
                continue
            for row in decisions:
                if gw.history is not None:
                    try:
                        gw.history.record_autotune(row)
                    except Exception:
                        log.exception("history autotune write failed")


class Gateway:
    """The front door over N replica servers. See the module docstring
    for the full story; the API surface:

    - ``submit(req, on_event=None) -> Ticket`` (raises ``Shed``)
    - ``drain()`` then ``stop()`` — or just ``stop()`` (drains)
    - ``snapshot()`` — the /stats payload
    - ``ready`` / ``draining`` — the /readyz signal
    """

    def __init__(self, servers: list[Server], *, max_queue: int = 128,
                 default_ttl_s: float | None = None,
                 metrics_store=None, history: GatewayHistory | None = None,
                 max_attempts: int = 3, stall_timeout_s: float = 30.0,
                 breaker_base_s: float = 0.25, breaker_max_s: float = 8.0,
                 quarantine_after: int = 5, tracing: bool = True,
                 trace_capacity: int = 256,
                 profile_dir: str | None = None,
                 tier_weights: dict[str, float] | str | None = None,
                 tenant_quota_rate: float = 0.0,
                 tenant_quota_burst: float = 0.0,
                 alerts: bool = True, alert_interval_s: float = 1.0,
                 alert_thresholds: dict | None = None,
                 bundle_on_alert: bool = True,
                 roles: list | None = None,
                 prefix_affinity: bool = True,
                 autotune: bool = False,
                 autotune_interval_s: float = 1.0,
                 autotune_config: dict | None = None,
                 journal=None, park_ttl_s: float = 60.0):
        if not servers:
            raise ValueError("gateway needs at least one replica server")
        # disaggregated prefill/decode (ISSUE-12): ``roles`` names each
        # replica's pool ("prefill" runs admission/chunked-prefill only
        # and hands finished page lists to "decode" replicas). The
        # handoff unit is a page list, so every role-split replica must
        # serve the paged cache.
        self.roles = list(roles) if roles else None
        if self.roles:
            if len(self.roles) != len(servers):
                raise ValueError(
                    f"roles names {len(self.roles)} replicas, gateway "
                    f"has {len(servers)}")
            bad = set(self.roles) - {"prefill", "decode"}
            if bad:
                raise ValueError(f"unknown roles {sorted(bad)} "
                                 "(valid: prefill, decode)")
            if "prefill" not in self.roles or "decode" not in self.roles:
                raise ValueError("role split needs at least one "
                                 "prefill AND one decode replica")
            unpaged = [i for i, s in enumerate(servers)
                       if not getattr(s, "paged", False)]
            if unpaged:
                raise ValueError(
                    f"role split needs the paged KV cache on every "
                    f"replica (unpaged: {unpaged})")
        # prefix-affinity routing: send a request to the replica whose
        # radix tree holds its longest cached prefix (generalizes crc32
        # session affinity; degrades to least-outstanding). Off is the
        # A/B control for bench extras.disagg.
        self.prefix_affinity = bool(prefix_affinity)
        # admission tiers + quotas (gateway/admission.py): weights may
        # arrive as the CLI's "name=w,..." spec; quotas default OFF
        if isinstance(tier_weights, str):
            tier_weights = parse_tier_weights(tier_weights)
        self.tier_weights = dict(tier_weights) if tier_weights \
            else None  # None -> WFQueue's defaults
        if self.tier_weights is not None \
                and DEFAULT_TIER not in self.tier_weights:
            raise ValueError(
                f"tier weights must include the default tier "
                f"{DEFAULT_TIER!r} (got {sorted(self.tier_weights)})")
        self.quotas = TenantQuotas(tenant_quota_rate, tenant_quota_burst)
        self.replicas = [_Replica(i, s, self) for i, s in enumerate(servers)]
        if self.roles:
            for replica, role in zip(self.replicas, self.roles):
                replica.role = role
        # model bound captured once: replicas share the model config,
        # and a retired replica's released engine must not be the
        # thing submit() validates against
        self._max_seq_len = servers[0].model.cfg.max_seq_len
        self.max_queue = max(1, max_queue)
        self.default_ttl_s = default_ttl_s
        self.metrics_store = metrics_store
        self.history = history
        # supervision knobs (the TonY AM's heartbeat/retry settings,
        # serving flavor). stall_timeout_s must comfortably exceed one
        # step's WORST dispatch time (first-compile included when the
        # compile cache is cold) or healthy replicas get declared dead.
        self.max_attempts = max(1, max_attempts)
        self.stall_timeout_s = stall_timeout_s
        self.breaker_base_s = breaker_base_s
        self.breaker_max_s = breaker_max_s
        self.quarantine_after = max(1, quarantine_after)
        self._beat_interval_s = max(0.05, stall_timeout_s / 10)
        self._watchdog = None
        self.stats = _Stats()
        # request tracing (obs/trace.py): a bounded ring of finished
        # traces behind GET /debug/trace/<id>, optionally mirrored into
        # the history dir's metrics/traces.jsonl. tracing=False is the
        # overhead A/B knob (bench extras.obs) — the layer is cheap
        # enough to stay on in production.
        self.traces = TraceBuffer(trace_capacity) if tracing else None
        # on-demand serving profiles (profiler.ServeProfiler): armed by
        # POST /debug/profile, burned down by replica threads' working
        # iterations. Always constructed — an un-armed poll() is one
        # attribute read.
        from tony_tpu.profiler import ServeProfiler

        if profile_dir is None and history is not None:
            profile_dir = os.path.join(history.job_dir, "profiles")
        self.profiler = ServeProfiler(profile_dir)
        self._lock = threading.Lock()
        self._drain_lock = threading.Lock()
        # in-flight frozen-snapshot leases (_SnapLease): keyed by
        # gateway request id, registered before every migrate extract,
        # claimed by _failover when the source dies mid-move
        self._snap_leases: dict = {}
        self._lease_lock = threading.Lock()
        self.migrate_lease_s = 5.0  # how long a failover waits for an
        #                             in-flight extract before falling
        #                             back to re-run-from-prompt
        self._drain_done: bool | None = None
        # crash-safe control plane (ISSUE-20): ``journal`` is the
        # write-ahead TicketJournal every admit/route/emit/terminal
        # rides (None = off); ``_resume`` is the request-id -> Ticket
        # registry behind GET /v1/stream/<id>?offset= — every admitted
        # ticket registers, terminals stay fetchable for ``park_ttl_s``
        # (the client-side twin of the agent's park TTL), then reap.
        self.journal = journal
        self.park_ttl_s = max(1.0, float(park_ttl_s))
        self._resume: dict = {}
        self._resume_lock = threading.Lock()
        self._t_recovered: float | None = None  # alert signal stamp
        self._host_cache: tuple[float, dict] | None = None
        self._tpu_discoverer = None
        self._started = False
        self._closed = False
        # an attached AutoScaler (autoscale.AutoScaler registers
        # itself): snapshot() surfaces its status block, drain() stops
        # its loop before closing the fleet
        self.scaler = None
        # an attached Rebalancer (gateway/rebalance.py registers
        # itself): the pressure-driven session-packing loop — same
        # snapshot/drain contract as the scaler
        self.rebalancer = None
        # the network face's connection-plane stats provider (ISSUE-16:
        # gateway/edge.py registers its snapshot fn) — the gateway core
        # knows nothing about sockets, but /stats and /metrics are the
        # one pane of glass, so the edge block rides the same snapshot
        self._edge_stats: Callable | None = None
        # the alert/event bus (obs/alerts.py): a rule engine evaluated
        # on the same consistent snapshot the autoscaler reads, firing
        # deduplicated fire/resolve events into /stats ``alerts``,
        # /metrics ``tony_alerts_*``, and history metrics/alerts.jsonl.
        # alerts=False is the A/B knob (bench extras.goodput).
        self.alerts = AlertBus(default_rules(alert_thresholds)) \
            if alerts else None
        self._alert_loop = _AlertLoop(self, alert_interval_s) \
            if alerts else None
        # the flight recorder (ISSUE-15): a firing alert dumps one
        # debug bundle into the history job dir (needs history for a
        # place to land; GET /debug/bundle works regardless)
        self.bundle_on_alert = bool(bundle_on_alert)
        # the adaptive shape controller (serve/autotune.py, ISSUE-13):
        # samples each local replica's goodput/timeline deltas and
        # steers chunk_steps / speculate_k / prefill_chunk within
        # bounds. Off by default — it is the --autotune opt-in; every
        # decision lands in /stats engine.autotune, tony_autotune_*
        # metrics, and history metrics/autotune.jsonl.
        from tony_tpu.serve.autotune import AutotuneController

        self.autotune = AutotuneController(**(autotune_config or {})) \
            if autotune else None
        self._autotune_loop = _AutotuneLoop(self, autotune_interval_s) \
            if autotune else None

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Gateway":
        from tony_tpu.coordinator.liveness import LivenessMonitor

        # the watchdog IS the coordinator's LivenessMonitor (the TonY
        # AM heartbeat expiry machinery): expiry = stall_timeout_s,
        # checked at a 1/5 cadence. It catches the failure exceptions
        # cannot: a dispatch that WEDGES instead of raising.
        self._watchdog = LivenessMonitor(
            interval_ms=max(1, int(self.stall_timeout_s * 1000 / 5)),
            max_missed=5, on_expired=self._on_stall).start()
        for r in self.replicas:
            self._watchdog.register(str(r.index))
            r.start()
        if self._alert_loop is not None:
            self._alert_loop.start()
        if self._autotune_loop is not None:
            self._autotune_loop.start()
        self._started = True
        return self

    @property
    def ready(self) -> bool:
        return self._started and not self._closed

    @property
    def draining(self) -> bool:
        return self._closed

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admitting (submit -> 503), let every
        replica finish its queue and in-flight slots, join the threads.
        Returns True when everything drained inside ``timeout``.
        Idempotent — a second call (stop() after drain()) returns the
        first outcome instead of re-finalizing the history job."""
        scaler = self.scaler
        if scaler is not None:
            # stop the control loop FIRST: a scale-up racing the drain
            # would find _closed and fail, but there is no reason to
            # let it try — and a scale-down's remove_replica must not
            # interleave with the fleet-wide join below
            scaler.stop()
        rebalancer = self.rebalancer
        if rebalancer is not None:
            # same reasoning: migrating sessions around a fleet that
            # is about to join is churn at best, a stranded frozen
            # snapshot at worst
            rebalancer.stop()
        if self._alert_loop is not None:
            # same reasoning: an alert evaluated over a half-joined
            # fleet is noise, and the history file is about to close
            self._alert_loop.stop()
        if self._autotune_loop is not None:
            # actuating shapes on a fleet about to join is pure churn
            self._autotune_loop.stop()
        with self._drain_lock:
            if self._drain_done is not None:
                return self._drain_done
            self._closed = True
            for r in self.replicas:
                r.signal_stop()
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            ok = True
            for r in self.replicas:
                left = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                r.join(left)
                ok = ok and not r._thread.is_alive()
            # stop the watchdog only AFTER the join: a dispatch that
            # wedges while its replica drains still gets declared
            # stalled and its tickets failed over (or terminal-shed
            # 503 once every other replica has exited) — the
            # no-stranded-ticket promise holds through shutdown. A
            # replica that finishes its queue and exits unregisters
            # itself, so a busy-but-progressing final join is never
            # misread as a stall.
            wd = self._watchdog
            self._watchdog = None
            if wd is not None:
                wd.stop()
            # remote replicas: stop lease/heartbeat machinery after
            # the fleet join (attached agents keep running — they
            # belong to whoever started them; launched agents are
            # drained and reaped)
            from tony_tpu.gateway.remote import close_server

            for r in self.replicas:
                close_server(r.server, f"replica {r.index} drain")
            # a profile capture left mid-flight (operator armed it,
            # traffic stopped) is finalized so its xplane files land
            self.profiler.close()
            if self.journal is not None:
                # clean drain COMPACTS the WAL (every request reached
                # a terminal -> empty file; the next --recover finds
                # nothing to do); a drain that timed out leaves the
                # journal whole — those stragglers are exactly what
                # recovery should see
                try:
                    self.journal.close(compact=ok)
                except Exception:
                    log.exception("journal close failed")
            if self.history is not None:
                self.history.close("SUCCEEDED" if ok else "KILLED",
                                   self.stats.snapshot())
            self._drain_done = ok
            return ok

    def stop(self, timeout: float | None = None) -> bool:
        return self.drain(timeout)

    # -------------------------------------------------------- elasticity

    @property
    def live_replicas(self) -> list[_Replica]:
        """Replicas that are part of the fleet: not retired, not mid
        scale-down drain. (Routability is stricter — see ``_route``.)"""
        return [r for r in self.replicas
                if not r.retired and not r.retiring]

    def add_replica(self, server: Server, *, probe: bool = True) -> int:
        """Grow the fleet at runtime (the autoscaler's scale-up
        primitive; also a valid operator call). With ``probe=True``
        (the default, and the only setting the autoscaler uses) the
        new replica enters through the circuit breaker's PROBE path:
        it starts BROKEN, runs a real tiny generation through the
        traffic code paths — paying its first compiles off the traffic
        path — and joins routing only when that probe succeeds,
        exactly the way a recovered replica re-earns admission.
        Returns the new replica's index."""
        if not self._started:
            raise RuntimeError("add_replica() needs a started gateway")
        with self._lock:
            if self._closed:
                raise GatewayClosed("gateway is draining")
            replica = _Replica(len(self.replicas), server, self)
            replica.spawned = True
            if probe:
                replica.state = BROKEN  # joins routing via _recover()
            self.replicas.append(replica)
        if not probe:
            wd = self._watchdog  # snapshot (see _beat)
            if wd is not None:
                wd.register(str(replica.index))
        replica.start(probe_first=probe)
        with self.stats.lock:
            self.stats.replicas_added += 1
        log.warning("replica %d added (%s)", replica.index,
                    "probe admission" if probe else "immediate")
        return replica.index

    def remove_replica(self, index: int,
                       timeout: float | None = None) -> bool:
        """Shrink the fleet at runtime over the existing ZERO-LOSS
        drain: the replica leaves routing immediately (``retiring`` —
        new submits re-route, the enqueue race re-routes), MIGRATES
        its work to the survivors — queued tickets re-route untouched,
        live decode slots freeze into ``SessionSnapshot``s and resume
        mid-stream elsewhere, token-exact (ISSUE-18) — then parks
        RETIRED with its engine released (the KV cache's memory goes
        back to the provisioner's account). What cannot migrate (an
        unpaged engine, a request mid-prefill, no healthy taker) is
        finished here, so the drain time is bounded by the slowest
        FREEZE rather than the longest remaining generation whenever
        migration applies. A dispatch that wedges during the drain
        still fails over: the watchdog keeps watching until the
        thread is joined. Refuses to remove the last live replica.
        Returns True when the drain completed inside ``timeout``."""
        replica = self.replicas[index]  # IndexError = caller bug
        with self._lock:
            if replica.retired:
                return True
            live = self.live_replicas
            if replica in live and len(live) <= 1:
                raise ValueError(
                    "cannot remove the last live replica (drain() the "
                    "gateway instead)")
            with replica.cv:
                replica.retiring = True
                replica.cv.notify_all()
        replica.signal_stop()
        replica.join(timeout)
        if replica._thread.is_alive():
            # still draining past the deadline: leave it retiring (out
            # of routing, still finishing work) — the caller may retry
            return False
        self._unwatch(replica)
        with replica.cv:
            replica.retired = True
            replica.state = RETIRED
            # release the engine: the whole point of scale-down is
            # giving the KV cache + weights references back; stats()
            # and busy() guard against the None
            server = replica.server
            replica.server = None
        # fold the departing engine's migration ledger into the carry
        # before the reference is dropped — the out-side of the drain
        # it just performed is counted on IT
        try:
            counts = server.counters() if server is not None else {}
        except Exception:
            counts = {}
        with self.stats.lock:
            for key in ("migrations_out", "migrations_in",
                        "migrations_local", "migrations_remote",
                        "migrate_pages_moved", "migrate_bytes_avoided",
                        "migrate_bytes_wire", "migrate_delta_in",
                        "migrate_freeze_resume_ms"):
                if counts.get(key):
                    self.stats.migrate_carry[key] = \
                        self.stats.migrate_carry.get(key, 0) \
                        + counts[key]
        # remote replicas: stop the stub's lease/heartbeat machinery
        # (and, for agents the stub launched, drain + reap the agent
        # process) — a retired replica must not keep pinging a host
        from tony_tpu.gateway.remote import close_server

        close_server(server, f"replica {index} retire")
        with self.stats.lock:
            self.stats.replicas_removed += 1
        log.warning("replica %d retired (zero-loss drain complete)",
                    index)
        return True

    def scale_signals(self) -> dict:
        """One consistent read of everything the autoscaler watches:
        queue pressure (depth / oldest wait / enqueue rate), capacity
        sheds, the TTFT histogram (SLO burn is computed from deltas of
        it), occupancy, and KV page pressure. Also the source of the
        /stats ``queue`` block, so the autoscaler and a human reading
        /stats see the same numbers."""
        now = time.monotonic()
        live = self.live_replicas
        queue = self._queue_block(live, now)
        servers = [s for s in (r.server for r in live) if s is not None]
        counts = [s.counters() for s in servers]
        with self.stats.lock:
            # capacity sheds only: quota 429s are policy, not pressure
            # — an autoscaler feeding on them would grow the fleet to
            # chase a tenant's rate limit
            shed_capacity = sum(
                n for status, n in self.stats.shed_by_status.items()
                if status in (429, 503, 504)) - self.stats.quota_rejections
        return {
            "now": now,
            "replicas_live": len(live),
            "replicas_routable": sum(1 for r in live
                                     if r.state == HEALTHY),
            **queue,
            "active_slots": sum(s.slots.n_active for s in servers),
            "slots": sum(s.slots.batch_size for s in servers),
            "shed_capacity_total": max(0, shed_capacity),
            "ttft_hist": self.stats.hist["ttft"].snapshot(),
            "kv_pages_total": sum(c.get("kv_pages_total", 0)
                                  for c in counts),
            "kv_pages_free": sum(c.get("kv_pages_free", 0)
                                 for c in counts),
            "kv_pages_reserved": sum(c.get("kv_pages_reserved", 0)
                                     for c in counts),
            # host-tier restore traffic (cumulative bytes): the
            # kv_host_thrash alert diffs this per tick against the
            # pressure condition above
            "kv_host_page_in_bytes": sum(
                c.get("kv_host_page_in_bytes", 0) for c in counts),
        }

    def rebalance_signals(self) -> dict:
        """One consistent read of everything the rebalancer watches:
        per-replica slot occupancy, queue depth, and the in-flight
        ticket set (request id, prompt for the prefix-heat probe,
        remaining work for the tie-break). Only HEALTHY replicas with
        a live engine appear — a broken or retiring replica is the
        failover/retirement machinery's problem, not a packing
        target."""
        now = time.monotonic()
        rows = []
        for r in self.live_replicas:
            server = r.server  # single read vs concurrent retirement
            if server is None or r.state != HEALTHY:
                continue
            with r.cv:
                tickets = [
                    {"rid": t.request.id,
                     "prompt": list(t.request.prompt),
                     "remaining": max(
                         0, t.request.max_new_tokens - t._n_emitted)}
                    for t in r._tickets.values()
                    if t.request.id is not None]
            rows.append({
                "index": r.index,
                "active": server.slots.n_active,
                "slots": server.slots.batch_size,
                "depth": r.queue_signals(now)["depth"],
                "outstanding": r.outstanding,
                "tickets": tickets,
            })
        return {"now": now, "replicas": rows}

    def alert_signals(self) -> dict:
        """``scale_signals()`` plus what the alert rules additionally
        watch (breaker failure counts, replica states, fleet goodput,
        token flow) — ONE consistent read, so an alert and a scale
        decision can never disagree about the fleet they saw."""
        sig = self.scale_signals()
        live = self.live_replicas
        with self.stats.lock:
            sig["replica_failures"] = self.stats.replica_failures
            sig["completed"] = self.stats.completed
            sig["tokens_out"] = self.stats.tokens_out
        sig["states"] = [r.state for r in live]
        # the connection-plane's sheds (ISSUE-20 satellite, closing a
        # ROADMAP-3 gap): 429s the EDGE refused at its connection cap
        # never reached admission, so without this row a pure
        # connection storm was invisible to the shed-storm alert
        edge = self._edge_stats
        conn_sheds = 0
        if edge is not None:
            try:
                conn_sheds = int(
                    (edge() or {}).get("conn_limit_sheds", 0))
            except Exception:
                conn_sheds = 0
        sig["edge_conn_limit_sheds"] = conn_sheds
        # a recent --recover boot (fires the one-shot recovery alert:
        # operators should KNOW the gateway came back from a crash)
        t_rec = self._t_recovered
        sig["recovered_ago_s"] = None if t_rec is None \
            else round(time.monotonic() - t_rec, 3)
        fleet = self.fleet_goodput(live)
        if fleet:
            sig["goodput_useful"] = fleet.get("useful_fraction")
            # raw milliseconds, not fractions: the collapse rule
            # needs per-tick DELTAS of useful vs dispatch time (a
            # cumulative fraction decays during idle lulls with
            # nothing wrong; a wall denominator reads trickle traffic
            # as collapse)
            sig["goodput_dispatch_ms"] = fleet.get("dispatch_ms")
            sig["goodput_useful_ms"] = sum(
                v for k, v in fleet.get("ms", {}).items()
                if k.startswith("useful."))
        else:
            sig["goodput_useful"] = None
            sig["goodput_dispatch_ms"] = None
            sig["goodput_useful_ms"] = None
        return sig

    def fleet_goodput(self, live: list | None = None) -> dict:
        """Fleet goodput ledger: per-replica ledgers merged weighted
        by wall clock (obs/goodput.merge_ledgers). Empty dict when no
        replica runs a timeline."""
        replicas = live if live is not None else self.live_replicas
        ledgers = []
        for r in replicas:
            server = r.server  # single read vs concurrent retirement
            if server is not None:
                ledgers.append(server.goodput())
        return merge_ledgers(ledgers)

    def goodput_report(self) -> dict:
        """The ``GET /debug/goodput`` payload: the fleet ledger with
        its single largest waste bucket named, plus each replica's own
        ledger (per-kind bytes/FLOPs and HBM-BW%/MFU where a roofline
        reference exists — null on CPU)."""
        live = self.live_replicas
        per_replica = []
        for r in live:
            server = r.server
            if server is None:
                continue
            g = server.goodput()
            if g is not None:
                g["replica"] = r.index
                per_replica.append(g)
        fleet = merge_ledgers(per_replica)
        return {
            "enabled": bool(per_replica),
            "fleet": fleet,
            "largest_waste": fleet.get("largest_waste"),
            "replicas": per_replica,
        }

    # --------------------------------------- fleet observability (15)

    @property
    def has_local_replicas(self) -> bool:
        """True when any live replica's engine runs IN THIS process —
        the gate for arming the gateway's own ``ServeProfiler``: a
        pure-router fleet (every replica remote) has no local jax work
        worth capturing, and a stuck local arm must not be able to
        409-block the remote fan-out forever."""
        return any(getattr(r.server, "transport", None) is None
                   for r in self.live_replicas if r.server is not None)

    @property
    def has_remote_replicas(self) -> bool:
        return any(getattr(r.server, "transport", None) is not None
                   for r in self.live_replicas)

    def _remote_profile_fanout(self, call) -> dict:
        """Run ``call(server) -> dict`` against every remote replica
        CONCURRENTLY (each call handles its own errors): the per-host
        results are independent, and N sequential timeouts against a
        half-dead fleet — exactly when an operator profiles — would
        tie a gateway handler thread up for N x timeout."""
        import concurrent.futures

        targets = [r.server for r in self.live_replicas
                   if getattr(r.server, "transport", None) is not None]
        if not targets:
            return {}
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(targets))) as pool:
            futures = [(s.host_addr, pool.submit(call, s))
                       for s in targets]
            return {addr: fut.result() for addr, fut in futures}

    def arm_remote_profiles(self, steps: int) -> dict:
        """The remote half of ``POST /debug/profile`` (ISSUE-15): fan
        the capture request out to every remote replica's agent
        (``POST /v1/profile``), so one operator curl profiles the
        WHOLE fleet — local replicas through this process's
        ``ServeProfiler``, each agent host through its own (xplane
        files land on that host, under the agent's profile dir).
        Best-effort per host: an unreachable or already-capturing
        agent reports its error in the returned map and never blocks
        the rest. Empty map = no remote replicas."""
        from tony_tpu.gateway.remote import AgentHTTPError

        def arm(server) -> dict:
            try:
                doc = server.transport.call(
                    "POST", "/v1/profile", {"steps": int(steps)},
                    epoch=server.epoch, timeout=3.0)
                return {"armed": True, "logdir": doc.get("logdir")}
            except AgentHTTPError as e:
                return {"armed": False, "status": e.status,
                        "error": e.doc.get("error", str(e))}
            except Exception as e:  # noqa: BLE001 — best-effort PER
                # HOST is the contract: json.loads ValueErrors,
                # http.client garbled-response exceptions, anything —
                # one bad agent reports its error, never 500s the
                # whole fan-out
                return {"armed": False,
                        "error": f"{type(e).__name__}: {e}"}

        return self._remote_profile_fanout(arm)

    def remote_profile_status(self) -> dict:
        """Per-agent ``GET /v1/profile`` statuses for the fleet view
        behind ``GET /debug/profile`` — best-effort (a debug read
        must not 5xx because one host is down)."""
        from tony_tpu.gateway.remote import AgentHTTPError

        def status(server) -> dict:
            try:
                return server.transport.call(
                    "GET", "/v1/profile", epoch=server.epoch,
                    timeout=3.0)
            except Exception as e:  # noqa: BLE001 — see arm(): a
                # debug read is best-effort per host, never a 5xx
                return {"error": f"{type(e).__name__}: {e}"}

        return self._remote_profile_fanout(status)

    def debug_bundle(self, reason: str = "manual",
                     trigger: list | None = None,
                     trace_limit: int = 8) -> dict:
        """The flight recorder's payload (``GET /debug/bundle``, and
        what a firing alert dumps to disk): ONE self-contained JSON
        document an operator can read after the incident — active +
        recent alerts, the signal snapshot the rules judged, the
        fleet/per-replica goodput report, every replica's stats row
        (dispatch timeline, transport + obs blocks for remote hosts),
        supervision counters, the autoscaler's status, and the most
        recent request traces (full Chrome docs for the last
        ``trace_limit``, summaries for the rest) — remote spans, with
        their clock-offset tags, included."""
        live = [r for r in self.replicas if not r.retired]
        replicas = []
        for r in live:
            row = r.stats(include_dispatch=True)
            server = r.server
            if server is not None:
                row["goodput"] = server.goodput()
            replicas.append(row)
        traces: dict = {"count": 0, "summaries": [], "recent": []}
        if self.traces is not None:
            traces["summaries"] = self.traces.summaries()
            traces["count"] = len(traces["summaries"])
            recent_ids = self.traces.ids()[-trace_limit:] \
                if trace_limit > 0 else []  # [-0:] would mean ALL
            for rid in recent_ids:
                tr = self.traces.get(rid)
                if tr is not None:
                    traces["recent"].append(tr.to_chrome())
        try:
            signals = self.alert_signals()
        except Exception:  # noqa: BLE001 — a half-drained fleet must
            # still bundle what it can, not crash the recorder
            log.exception("bundle signal read failed")
            signals = {}
        with self.stats.lock:
            supervision = {
                "replica_failures": self.stats.replica_failures,
                "failovers": self.stats.failovers,
                "retries": self.stats.retries,
                "probes": self.stats.probes,
                "rejoins": self.stats.rejoins,
                "quarantines": self.stats.quarantines,
                "replicas_added": self.stats.replicas_added,
                "replicas_removed": self.stats.replicas_removed,
            }
            bundles = {"written": self.stats.bundles_written,
                       "last_path": self.stats.last_bundle}
        scaler = self.scaler
        return {
            "t_wall": round(time.time(), 3),
            "reason": reason,
            "trigger": list(trigger) if trigger else [],
            "app_id": self.history.app_id
            if self.history is not None else None,
            "alerts": {"enabled": True, **self.alerts.snapshot()}
            if self.alerts is not None else {"enabled": False},
            "signals": signals,
            "goodput": self.goodput_report(),
            "supervision": supervision,
            "replicas": replicas,
            "scaler": scaler.status() if scaler is not None else None,
            "traces": traces,
            "bundles": bundles,
        }

    def dump_bundle(self, reason: str = "manual",
                    trigger: list | None = None) -> str | None:
        """Write ``debug_bundle()`` into the history job dir. Returns
        the path, or None when there is no history (nowhere to land)
        or the write failed — the recorder degrades, it never raises
        into its caller (the alert loop)."""
        history = self.history
        if history is None:
            return None
        try:
            path = history.write_bundle(
                self.debug_bundle(reason=reason, trigger=trigger))
        except Exception:
            log.exception("debug bundle dump failed")
            return None
        with self.stats.lock:
            self.stats.bundles_written += 1
            self.stats.last_bundle = path
        log.warning("debug bundle (%s: %s) -> %s", reason,
                    ",".join(trigger) if trigger else "-", path)
        return path

    def _queue_block(self, replicas: list[_Replica], now: float) -> dict:
        """The queue-pressure block, ONE implementation for both
        consumers — the autoscaler's ``scale_signals()`` and the
        /stats ``queue`` block — so they cannot drift apart."""
        per_replica = []
        by_tier: dict[str, int] = {}
        for r in replicas:
            sig = r.queue_signals(now)
            sig["replica"] = r.index
            per_replica.append(sig)
            for tier, n in sig["by_tier"].items():
                by_tier[tier] = by_tier.get(tier, 0) + n
        return {
            "depth": sum(s["depth"] for s in per_replica),
            "oldest_wait_s": max((s["oldest_wait_s"]
                                  for s in per_replica), default=0.0),
            "enqueue_rate_per_s": round(
                sum(s["enqueue_rate_per_s"] for s in per_replica), 3),
            "by_tier": by_tier,
            "per_replica": per_replica,
        }

    # --------------------------------------------------------- admission

    def submit(self, request: GenRequest,
               on_event: Callable | None = None) -> Ticket:
        """Admission gate + router. Raises ``GatewayClosed`` (503) when
        draining, ``BadRequest`` (400) on invalid shapes,
        ``GatewayQueueFull`` (429) past ``max_queue`` waiting requests,
        ``DeadlineExceeded`` (504) for an already-dead ttl,
        ``NoHealthyReplicas`` (503) when every replica's breaker is
        open."""
        if self._closed:
            self.stats_shed(503)
            raise GatewayClosed("gateway is draining")
        prompt = list(request.prompt)
        max_len = self._max_seq_len
        if not prompt:
            self.stats_shed(400)
            raise BadRequest("empty prompt")
        if len(prompt) >= max_len:
            self.stats_shed(400)
            raise BadRequest(f"prompt ({len(prompt)}) leaves no room for "
                             f"generation in max_seq_len ({max_len})")
        if request.max_new_tokens < 1:
            self.stats_shed(400)
            raise BadRequest("max_new_tokens must be >= 1")
        tier = request.priority if request.priority is not None \
            else DEFAULT_TIER
        weights = self.tier_weights if self.tier_weights is not None \
            else _DEFAULT_WEIGHTS
        if tier not in weights:
            self.stats_shed(400)
            raise BadRequest(f"unknown priority {tier!r} "
                             f"(tiers: {', '.join(weights)})")
        ttl = request.ttl_s if request.ttl_s is not None \
            else self.default_ttl_s
        if ttl is not None and ttl <= 0:
            self.stats_shed(504)
            raise DeadlineExceeded("ttl_s already expired at submit")
        cost = len(prompt) + request.max_new_tokens
        if request.id is None:
            # server-minted UUID (clients may supply their own): echoed
            # in responses, /stats window rows, history requests.jsonl,
            # and keying the request's trace — the correlation handle
            # TonY's per-task history gives every job
            request.id = uuid.uuid4().hex
        with self._lock:
            if sum(r.n_queued for r in self.replicas
                   if not r.retired) >= self.max_queue:
                self.stats_shed(429)
                raise GatewayQueueFull(
                    f"admission queue at max_queue={self.max_queue}")
            # tenant quota AFTER validation + the queue bound (a
            # request the gateway can't even queue must not drain the
            # tenant's bucket), BEFORE the ticket exists. Charged
            # exactly once — failover re-enqueues never re-pass this
            # gate — and refunded on the no-service exits below.
            retry_after = self.quotas.admit(request.tenant, cost)
            if retry_after is not None:
                with self.stats.lock:
                    self.stats.quota_rejections += 1
                    self.stats.shed_by_tier[tier] = \
                        self.stats.shed_by_tier.get(tier, 0) + 1
                self.stats_shed(429)
                raise QuotaExceeded(
                    f"tenant {request.tenant or '(anonymous)'!r} over "
                    f"its token rate ({self.quotas.rate:g}/s, burst "
                    f"{self.quotas.burst:g}); retry in {retry_after:.2f}s",
                    retry_after_s=retry_after)
            ticket = Ticket(request, ttl, on_event)
            ticket.tier = tier
            ticket.tenant = request.tenant
            # role-split fleets: every new request enters through the
            # prefill pool; the handoff relay moves it to decode
            ticket.phase = "prefill" if self.roles else None
            if self.traces is not None:
                t0 = request.t_receive if request.t_receive is not None \
                    else ticket.t_submit
                trace = RequestTrace(request.id, t0=t0)
                trace.root.tags.update(
                    prompt_len=len(prompt),
                    max_new_tokens=request.max_new_tokens,
                    priority=tier,
                    **({"tenant": request.tenant}
                       if request.tenant else {}))
                if request.t_receive is not None:
                    trace.add("http_receive", request.t_receive,
                              ticket.t_submit, attempt=False)
                ticket.trace = trace
            # WAL + resume registry (ISSUE-20): the admit row lands
            # BEFORE the enqueue so the journal never misses a routed
            # request, and the ticket registers for client resume —
            # GET /v1/stream/<id>?offset= works for every admitted
            # request, crash or no crash
            if self.journal is not None:
                ticket._journal = self.journal
                self.journal.admit(request.id, {
                    "prompt": prompt,
                    "max_new_tokens": request.max_new_tokens,
                    "temperature": request.temperature,
                    "top_k": request.top_k, "seed": request.seed,
                    **({"session": request.session}
                       if request.session else {}),
                    **({"tenant": request.tenant}
                       if request.tenant else {}),
                    **({"priority": request.priority}
                       if request.priority else {}),
                }, time.time())
            self._register_resume(ticket)
            tried: set[int] = set()
            while True:
                try:
                    replica = self._route(ticket, tried)
                except NoHealthyReplicas:
                    self.quotas.refund(request.tenant, cost)  # zero
                    # service delivered: the bucket must not pay
                    self.stats_shed(503)
                    self._abandon_resume(ticket, 503)
                    raise
                try:
                    # enqueue INSIDE the gateway lock: the bound check
                    # and the depth increment must be atomic or two
                    # concurrent submits both pass at max_queue - 1 and
                    # overshoot. Lock order gateway._lock -> replica.cv
                    # is safe: no replica-thread path takes the gateway
                    # lock.
                    replica.enqueue(ticket)
                    break
                except _ReplicaUnhealthy:
                    tried.add(replica.index)  # flipped between route
                    # and enqueue: re-route among the others
                except GatewayClosed:  # the drain race
                    self.quotas.refund(request.tenant, cost)
                    self.stats_shed(503)
                    self._abandon_resume(ticket, 503)
                    raise
        with self.stats.lock:
            self.stats.accepted += 1
        return ticket

    # a prefix-affinity match shorter than this (and shorter than the
    # whole prompt) is not worth overriding load balance for: seeding
    # a few tokens saves less than an imbalanced queue costs
    _AFFINITY_MIN_TOKENS = 8

    def _route(self, ticket: Ticket,
               excluded: set | frozenset = frozenset()) -> _Replica:
        """Routing, in preference order: (1) the ticket's ROLE pool
        (role-split fleets: "prefill" tickets only ever land on
        prefill replicas, handoffs on decode replicas); (2) PREFIX
        AFFINITY — the replica whose radix tree (device store or host
        tier) holds the longest cached prefix of this prompt, the
        generalization of session affinity that makes a fleet-wide hot
        system prompt prefill ONCE instead of once per replica; (3)
        crc32 session affinity when the request asks; (4) least
        outstanding tokens (ties -> lowest index, deterministic).
        Every preference degrades to the next — affinity is a cache
        preference, never a correctness requirement. Only HEALTHY
        replicas outside ``excluded`` are candidates; none left raises
        ``NoHealthyReplicas`` (503, retriable)."""
        request, phase = ticket.request, ticket.phase
        healthy = [r for r in self.replicas
                   if r.state == HEALTHY and not r.retiring
                   and r.index not in excluded
                   and (phase is None or r.role == phase)]
        if not healthy:
            pool = f"{phase} " if phase else ""
            raise NoHealthyReplicas(
                f"no healthy {pool}replica (states: "
                + ", ".join(r.state + ("/retiring" if r.retiring else "")
                            for r in self.replicas if not r.retired) + ")")
        if self.prefix_affinity and phase != "decode":
            pinned = self._prefix_match(request.prompt, healthy)
            if pinned is not None:
                with self.stats.lock:
                    self.stats.prefix_routed += 1
                return pinned
        if request.session is not None:
            # affinity hashes over the CURRENT membership (retired
            # replicas excluded; role-split fleets hash within the
            # ticket's pool): a scale event remaps sessions — a cache
            # preference reshuffle, never a correctness issue
            candidates = [r for r in self.replicas
                          if not r.retired and not r.retiring
                          and (phase is None or r.role == phase)]
            key = zlib.crc32(str(request.session).encode())
            pinned = candidates[key % len(candidates)] if candidates \
                else None
            if pinned in healthy:
                return pinned
        return min(healthy, key=lambda r: (r.outstanding, r.index))

    def _prefix_match(self, prompt: list,
                      healthy: list) -> _Replica | None:
        """The affinity probe: ask each candidate's engine for its
        longest cached prefix of ``prompt`` (a lock-protected radix
        walk, no device work, no counters moved) and pin to the
        longest match when it is worth it. Ties break by least
        outstanding work, so two equally-warm replicas still balance.
        Remote stubs answer from the bounded radix summary their
        agent ships on every heartbeat (ISSUE-18) — no per-request
        network probe, staleness bounded by the heartbeat interval,
        and a stale hit costs a suboptimal preference, never
        correctness — so a REMOTE replica holding the prefix can win
        over a cold local one."""
        best, best_len = None, 0
        for r in healthy:
            probe = getattr(r.server, "prefix_match_len", None)
            if probe is None:
                continue
            try:
                n = probe(prompt)
            except Exception:
                log.exception("prefix affinity probe failed on "
                              "replica %d", r.index)
                continue
            if n > best_len or (n == best_len and n > 0
                                and best is not None
                                and r.outstanding < best.outstanding):
                best, best_len = r, n
        if best is None or best_len < min(len(prompt),
                                          self._AFFINITY_MIN_TOKENS):
            return None
        return best

    # ------------------------------------------------------- supervision

    def _beat(self, replica: _Replica) -> None:
        """One heartbeat from a replica's scheduler thread (once per
        iteration, including idle waits)."""
        replica.last_beat = time.monotonic()
        wd = self._watchdog  # snapshot: drain() nulls the attribute
        # concurrently, and an AttributeError here would kill the
        # replica thread mid-drain with tickets still queued
        if wd is not None:
            wd.ping(str(replica.index))

    def _unwatch(self, replica: _Replica) -> None:
        """A replica thread exiting cleanly (drain finished its queue)
        takes itself off the watchdog's list — its silence is not a
        stall."""
        wd = self._watchdog  # snapshot (see _beat)
        if wd is not None:
            wd.unregister(str(replica.index))

    def _fail_remote(self, replica: _Replica, reason: str) -> None:
        """A remote replica's lease expired (or its agent reported a
        terminal condition mid-stream): the network-side analog of the
        watchdog's stall — same funnel, same token-exact failover.
        Runs on the stub's lease-monitor (or stream-reader) thread;
        ``_fail_replica``'s epoch/state fence makes a duplicate report
        (lease expiry racing a reader's dead-agent discovery) a
        no-op."""
        with replica.cv:
            epoch = replica.epoch
        self._fail_replica(replica, epoch,
                           f"replica {replica.index} ({replica.host}): "
                           f"{reason}")

    def _on_stall(self, task_id: str) -> None:
        """Watchdog expiry: the replica's thread stopped beating —
        a WEDGED dispatch (the failure exceptions cannot catch). Runs
        on the monitor thread; the wedged thread finds the bumped epoch
        whenever its dispatch finally returns and discards the stale
        output."""
        replica = self.replicas[int(task_id)]
        with replica.cv:
            epoch = replica.epoch
        self._fail_replica(
            replica, epoch,
            f"replica {replica.index} stalled: no heartbeat for "
            f"{self.stall_timeout_s:.1f}s")

    def _fail_replica(self, replica: _Replica, epoch: int,
                      reason: str) -> None:
        """Declare a replica failed (exception route from its own
        thread, stall route from the watchdog): bump its epoch (the
        fencing token — stale output from the old epoch is discarded),
        steal EVERY ticket it holds, and fail them over. Idempotent
        under the race of both routes firing: the epoch check makes the
        second caller a no-op. ``fail_lock`` is held through the whole
        steal + failover so the replica thread's breaker entry
        (``_recover``'s hard engine reset) cannot wipe the agent-side
        sessions while ``_claim_parked`` is still adopting them."""
        with replica.fail_lock:
            with replica.cv:
                if replica.epoch != epoch or replica.state != HEALTHY:
                    return  # already handled (exception-vs-watchdog
                    #         race)
                replica.epoch += 1
                replica.state = BROKEN
                replica.failures += 1
                replica.consecutive_failures += 1
                admitted = list(replica._tickets.values())
                replica._tickets.clear()
                queued = replica.queue.steal_all()  # WFQ service
                # order; tickets keep their tier, so the survivor's
                # queue re-applies the same fairness
                replica.outstanding = 0
                replica.cv.notify_all()
            wd = self._watchdog  # snapshot (see _beat)
            if wd is not None:
                wd.unregister(str(replica.index))
            with self.stats.lock:
                self.stats.replica_failures += 1
            log.error("%s: failing over %d admitted + %d queued "
                      "ticket(s)", reason, len(admitted), len(queued))
            self._failover(replica, admitted, queued, reason)

    def _failover(self, replica: _Replica, admitted: list,
                  queued: list, reason: str) -> None:
        """The TonY task-retry analog, token-exact: ``admitted``
        tickets ran on the failed engine — charge one attempt, exclude
        the replica, re-run from the prompt (deterministic decode +
        ``_n_emitted`` make the retried stream byte-identical past what
        the client already has). ``queued`` tickets never touched the
        engine: moved untouched, no attempt charged, no exclusion.
        Budget or fleet exhaustion sheds 503 (retriable) — never 500."""
        now = time.monotonic()
        for ticket in admitted:
            ticket.attempts += 1
            ticket.excluded.add(replica.index)
        if admitted:
            with self.stats.lock:
                self.stats.retries += len(admitted)
        for ticket in admitted + queued:
            if ticket.trace is not None:
                # close the failed attempt and mark the epoch fence:
                # a chaos-path trace shows BOTH engine runs, with the
                # failover instant between them (admitted=False means
                # the ticket was still queued — moved, never charged)
                admitted_here = any(ticket is t for t in admitted)
                ticket.trace.end_attempt(
                    now, outcome="failed" if admitted_here else "moved",
                    reason=reason)
                ticket.trace.add("failover", now, attempt=False,
                                 from_replica=replica.index,
                                 new_epoch=replica.epoch,
                                 admitted=admitted_here)
            ticket.state = QUEUED
            ticket.replica = None
            if ticket.attempts >= self.max_attempts:
                self._shed_ticket(
                    replica, ticket, 503,
                    f"retry budget exhausted: {ticket.attempts} failed "
                    f"run(s) on replicas {sorted(ticket.excluded)} "
                    f"({reason})", exc=RetryBudgetExhausted)
                continue
            if any(ticket is t for t in admitted):
                self._claim_snapshot(ticket)
                if ticket.migrate is None:
                    self._claim_parked(replica, ticket)
            self._requeue(replica, ticket, reason)

    def _claim_snapshot(self, ticket: Ticket) -> None:
        """The lease's claim half: if a migrate extract for this
        ticket is in flight (the source died mid-move), wait up to
        ``migrate_lease_s`` for the frozen snapshot and attach it —
        the requeue then resumes the session token-exact with NO
        recompute. Timeout or a failed extract falls through to the
        ordinary crash path (re-run from the prompt, still
        token-exact, just slower); the abandoned flag tells the
        extractor its late snapshot belongs to nobody."""
        with self._lease_lock:
            lease = self._snap_leases.pop(_lease_key(ticket), None)
        if lease is None:
            return
        if not lease.done.wait(self.migrate_lease_s):
            with self._lease_lock:
                if not lease.done.is_set():
                    # expired with the extract still running: the
                    # extractor sees abandoned=True and releases the
                    # snapshot when (if) it completes
                    lease.abandoned = True
                    log.warning("migrate snapshot lease expired after "
                                "%.1fs; re-running from prompt",
                                self.migrate_lease_s)
                    return
        if lease.snap is None:
            return  # the extract failed: nothing to adopt
        ticket.migrate = lease.snap
        with self.stats.lock:
            self.stats.migrate_lease_adoptions += 1
            self.stats.migrations += 1
        if ticket.trace is not None:
            ticket.trace.add("migrate_lease_adopt", time.monotonic(),
                             attempt=False,
                             waited_s=round(
                                 time.monotonic() - lease.t0, 3))
        log.warning("failover adopted an in-flight migrate snapshot "
                    "(token-exact resume, no recompute)")

    def _claim_parked(self, replica: _Replica, ticket: Ticket) -> None:
        """The parked-session check (ISSUE-20, closing the ROADMAP-4
        residue): before a failover re-runs an admitted ticket from
        its prompt, ask the failed replica's AGENT for the session —
        a lease that expired because the gateway-side transport
        flapped (not because the agent died) leaves the agent holding
        a perfectly good live slot or parked snapshot. Adopting it
        pins the invariants the chaos rounds check: ONE attempt
        charged (the failover already did), ZERO re-prefill, and a
        token-exact resumed stream. Any error falls through to the
        ordinary re-run — still token-exact, just slower."""
        server = replica.server
        adopt = getattr(server, "adopt_parked", None) \
            if server is not None else None
        if adopt is None:
            return  # local replica: its engine died with its slots
        try:
            resp = adopt(ticket.request.id)
        except Exception as e:
            log.debug("failover park check for %r on %s failed: %r",
                      ticket.request.id, replica.host, e)
            return
        if resp is None or resp.get("snapshot") is None:
            return  # unknown / reaped / finished-elsewhere: re-run
        ticket.migrate = resp["snapshot"]
        with self.stats.lock:
            self.stats.park_adoptions += 1
            self.stats.migrations += 1
        if ticket.trace is not None:
            ticket.trace.add("park_adopt", time.monotonic(),
                             attempt=False, host=replica.host,
                             offset=resp.get("offset"))
        log.warning("failover adopted the PARKED session for %r off "
                    "agent %s (token-exact resume, no re-prefill)",
                    ticket.request.id, replica.host)

    def _requeue(self, replica: _Replica, ticket: Ticket,
                 reason: str) -> None:
        """Land a stolen ticket on a healthy replica (outside its
        excluded set), or shed it 503. ``force=True`` bypasses the
        drain gate — the zero-loss drain promise covers stolen tickets
        too, as long as a live thread can still run them."""
        tried: set[int] = set()
        while True:
            try:
                target = self._route(ticket, ticket.excluded | tried)
            except NoHealthyReplicas:
                self._shed_ticket(
                    replica, ticket, 503,
                    f"no healthy replica left ({reason})",
                    exc=NoHealthyReplicas)
                return
            try:
                target.enqueue(ticket, force=True)
            except (GatewayClosed, _ReplicaUnhealthy):
                tried.add(target.index)  # raced its own failure/exit
                continue
            with self.stats.lock:
                self.stats.failovers += 1
            return

    def _relay_handoff(self, replica: _Replica, ticket: Ticket, res,
                       now: float) -> None:
        """The disaggregation hinge, run on the PREFILL replica's
        thread out of ``_deliver``: the prefill half finished (pages +
        last-position logits in ``res.handoff``), so move the ticket
        to a decode replica carrying the payload. Not a failover (no
        attempt charged, no exclusion — the prefill engine did its job)
        and not a completion (the client has seen nothing). A fleet
        with no healthy decode replica sheds 503, retriable."""
        with self.stats.lock:
            self.stats.handoffs += 1
        ticket._prefill_meta = {
            "prefill_replica": replica.index,
            "prefix_hit_tokens": res.prefix_hit_tokens,
            "prefill_tokens_saved": res.prefill_tokens_saved,
            "prefill_chunks": getattr(res, "prefill_chunks", 0),
        }
        ticket.handoff = res.handoff
        ticket.phase = "decode"
        ticket.state = QUEUED
        ticket.replica = None
        if ticket.trace is not None:
            ticket.trace.end_attempt(now, outcome="handoff")
            ticket.trace.add("handoff", now, attempt=False,
                             from_replica=replica.index,
                             n_tokens=res.handoff.get("n_tokens"))
        tried: set[int] = set()
        while True:
            try:
                target = self._route(ticket, ticket.excluded | tried)
            except NoHealthyReplicas:
                self._shed_ticket(
                    replica, ticket, 503,
                    "no healthy decode replica to receive the "
                    "prefill handoff", exc=NoHealthyReplicas)
                return
            try:
                # force=True: the drain promise covers a request whose
                # prefill half already ran, same as a stolen ticket
                target.enqueue(ticket, force=True)
            except (GatewayClosed, _ReplicaUnhealthy):
                tried.add(target.index)
                continue
            return

    # ------------------------------------------------ live migration

    def _migrate_ticket(self, replica: _Replica, engine_id: int,
                        ticket: Ticket, epoch: int) -> bool:
        """Freeze one live decode slot off ``replica`` and relay it to
        another replica (ISSUE-18). False means the session did NOT
        move and keeps running where it is — not-live-yet (pending or
        mid-prefill), unpaged engine, no healthy taker, or the extract
        lost a race; every one of those leaves the old behavior (decode
        to completion, or crash-path failover) intact."""
        server = replica.server
        if server is None or not getattr(server, "paged", False):
            return False
        if getattr(server, "extract_session", None) is None:
            return False
        # probe for a taker BEFORE freezing: with nobody to adopt it, a
        # freeze would degrade the session to a re-run from the prompt
        # for nothing
        try:
            self._route(ticket, ticket.excluded | {replica.index})
        except NoHealthyReplicas:
            return False
        # owner-swap extract (page ids, zero bytes moved) whenever the
        # engine's pool is shared — if routing then lands the ticket on
        # a REMOTE replica, the stub gathers the content late
        # (serve/migrate.gather_local); otherwise gather to wire now
        pool = getattr(getattr(server, "slots", None), "pool", None)
        wire = not (pool is not None and getattr(pool, "shared", False))
        # register the lease BEFORE the freeze: if the source replica
        # dies while the extract is in flight (remote migrate_out over
        # a SIGKILLed agent, a wedged local scheduler), _failover finds
        # this lease and waits a bounded time for the snapshot instead
        # of instantly degrading the session to re-run-from-prompt
        key = _lease_key(ticket)
        lease = _SnapLease()
        with self._lease_lock:
            self._snap_leases[key] = lease
        try:
            snap = server.extract_session(engine_id, wire=wire)
        except Exception:
            log.exception("migrate-out extract failed on replica %d",
                          replica.index)
            snap = None
        if snap is None:
            # failed or not in a live slot (pending, prefilling, or it
            # finished under us): wake any waiting claimer with
            # nothing — it proceeds down the crash path immediately
            with self._lease_lock:
                self._snap_leases.pop(key, None)
                lease.done.set()
            return False
        with self._lease_lock:
            lease.snap = snap
            lease.done.set()
            claimed = self._snap_leases.pop(key, None) is None
            abandoned = lease.abandoned
        if abandoned:
            # the claimer's lease expired before the extract finished:
            # the ticket already re-ran from its prompt — the late
            # snapshot is a duplicate of a stream someone else owns
            _release_snapshot(snap)
            return False
        if claimed:
            # _failover took the lease and is adopting the snapshot
            # (it sets ticket.migrate and requeues): the session moves
            # token-exact with no recompute — the move happened, just
            # through the crash funnel instead of the relay below
            return True
        with replica.cv:
            owned = replica.epoch == epoch \
                and replica._tickets.pop(engine_id, None) is not None
            if owned:
                replica.outstanding = max(
                    0, replica.outstanding - ticket.cost)
        if not owned:
            # the watchdog's steal raced the freeze: failover owns the
            # ticket now (re-run from prompt) — drop the frozen copy
            _release_snapshot(snap)
            return False
        self._relay_migration(replica, ticket, snap, time.monotonic())
        return True

    def _relay_migration(self, replica: _Replica, ticket: Ticket,
                         snap, now: float) -> None:
        """The planned-move hinge (ISSUE-18), the migration analog of
        ``_relay_handoff``: a frozen live session leaves ``replica``
        carrying its ``SessionSnapshot`` and resumes mid-stream on
        whichever replica routing picks — prefix affinity included.
        Not a failover (no attempt charged, no exclusion — the source
        did nothing wrong) and not a completion (the stream continues;
        the absolute-offset emit dedup keeps the client gap/dup-free).
        Both attempts land in ONE trace, fenced by the ``migrate``
        span. No taker left — a narrow race, callers probe before
        freezing — falls back to the crash path: drop the snapshot
        (refs released) and requeue an ordinary re-run from the
        prompt, token-exact."""
        with self.stats.lock:
            self.stats.migrations += 1
        ticket.migrate = snap
        ticket.state = QUEUED
        ticket.replica = None
        if ticket.trace is not None:
            local = not isinstance(snap, dict) \
                and bool(getattr(snap, "local", False))
            n_tok = snap.get("n_tokens") if isinstance(snap, dict) \
                else snap.n_tokens
            ticket.trace.end_attempt(now, outcome="migrate")
            ticket.trace.add("migrate", now, attempt=False,
                             from_replica=replica.index,
                             n_tokens=int(n_tok), local=local)
        tried = {replica.index}
        while True:
            try:
                target = self._route(ticket, ticket.excluded | tried)
            except NoHealthyReplicas:
                _release_ticket_payload(ticket)
                self._requeue(
                    replica, ticket,
                    "no replica left to adopt the migrated session")
                return
            try:
                target.enqueue(ticket, force=True)
            except (GatewayClosed, _ReplicaUnhealthy):
                tried.add(target.index)
                continue
            return

    def migrate_session(self, request_id) -> bool:
        """Move one in-flight request to another replica, mid-stream
        and token-exact — the operator/rebalancer entry to the same
        machinery retirement uses. The new placement goes through the
        ordinary routing stack, so with prefix affinity on, a hot
        session migrates TOWARD the replica already holding its
        prefix. Returns False when the request is not currently in a
        live decode slot (queued, mid-prefill, finished, unknown) or
        nothing could adopt it; the request is unharmed either way.

        Safe from any thread: the freeze itself serializes against the
        source's decode loop under the engine dispatch lock (local) or
        happens on the agent's scheduler (remote)."""
        for r in self.replicas:
            if r.retired or r.server is None:
                continue
            with r.cv:
                epoch = r.epoch
                found = [(eid, t) for eid, t in r._tickets.items()
                         if t.request.id == request_id]
            if found:
                return self._migrate_ticket(r, found[0][0],
                                            found[0][1], epoch)
        return False

    # ------------------------------------- restart recovery (ISSUE-20)

    def _register_resume(self, ticket: Ticket) -> None:
        """Every admitted ticket joins the resume registry behind
        ``GET /v1/stream/<id>?offset=`` — reconnects work crash or no
        crash. Terminal tickets stay fetchable for ``park_ttl_s``
        (the client-side twin of the agent's park TTL) and are reaped
        opportunistically here: registrations happen at traffic rate,
        so the registry can never grow past traffic + one TTL."""
        now = time.monotonic()
        with self._resume_lock:
            dead = [rid for rid, t in self._resume.items()
                    if t.t_terminal is not None
                    and now - t.t_terminal > self.park_ttl_s]
            for rid in dead:
                del self._resume[rid]
            self._resume[ticket.request.id] = ticket

    def _abandon_resume(self, ticket: Ticket, status: int) -> None:
        """A submit that sheds AFTER its admit row landed (no healthy
        replica, the drain race): close the WAL entry and drop the
        registration — the client got a synchronous error, there is
        nothing to resume and nothing for ``--recover`` to re-run."""
        with ticket._emit_lock:
            ticket.state = SHED
            ticket.t_terminal = time.monotonic()
            ticket._shed_status = status
        if ticket._journal is not None:
            ticket._journal.shed(ticket.request.id, status)
        with self._resume_lock:
            self._resume.pop(ticket.request.id, None)

    def resume_ticket(self, rid) -> Ticket | None:
        with self._resume_lock:
            return self._resume.get(rid)

    def resume_events(self, rid, offset: int = 0,
                      keepalive_s: float = 15.0):
        """The resumable-stream generator behind
        ``GET /v1/stream/<request_id>?offset=N`` (both edges frame
        it): yield the absolute token windows past the client's own
        cursor, then the terminal line. Reads the ticket's resume
        buffer (``_tokens``) under its emit lock instead of consuming
        the single-consumer ``events`` queue, so a resumed stream
        never races the original consumer — N watchers of one request
        all see the same bytes. First yield is ``{"gone": True}`` for
        an unknown/reaped id (the edge 404s); a client whose request
        finished while it was away gets the buffered suffix plus the
        terminal immediately."""
        ticket = self.resume_ticket(rid)
        if ticket is None:
            yield {"gone": True}
            return
        sent = max(0, int(offset))
        last = time.monotonic()
        while True:
            with ticket._emit_lock:
                total = len(ticket._tokens)
                state = ticket.state
                window = list(ticket._tokens[sent:]) if sent < total \
                    else None
                metrics = ticket.metrics
                shed = (ticket._shed_status, ticket._shed_reason)
            if window:
                yield {"offset": sent, "token_ids": window}
                sent += len(window)
                last = time.monotonic()
                continue
            if state == SHED:
                yield {"shed": True, "status": shed[0] or 503,
                       "reason": shed[1]}
                return
            if state == DONE and metrics is not None:
                yield {"done": True, "metrics": metrics}
                return
            now = time.monotonic()
            if keepalive_s and now - last >= keepalive_s:
                yield {"keepalive": True}
                last = now
            time.sleep(0.02)

    def recover_from_journal(self, entries: dict) -> dict:
        """Boot-time crash recovery (``--recover``): the TonY-AM-
        restart analog for serving. ``entries`` is a replayed journal
        (``journal.replay``); every LIVE entry — admitted, never
        terminal — is re-admitted under its ORIGINAL request id:

        - remote replicas first sync epochs PAST the dead gateway's
          (``sync_recovery_epoch`` — never ``reset()``, which would
          wipe the very sessions we came back for), so the first
          adopt fences out any stale second adopter;
        - a session the journaled host PARKED (or still runs — the
          agent freezes it on the spot) is adopted and resumes
          mid-stream, token-exact, zero re-prefill, no attempt
          charged;
        - a request that FINISHED into the void comes back as its
          buffered result, immediately terminal;
        - everything else re-runs from the prompt, charged one
          attempt — deterministic decode makes the re-run
          byte-identical, and the resume buffer serves whatever
          suffix the client is missing.

        Call after ``start()``. Returns the recovery report (also
        folded into stats/alerts)."""
        t0 = time.monotonic()
        live = sorted((e for e in entries.values() if e.live),
                      key=lambda e: e.t_admit)
        report = {"live": len(live), "adopted": 0, "rerun": 0,
                  "finished": 0, "shed": 0}
        by_host: dict[str, _Replica] = {}
        for r in self.replicas:
            if r.retired or r.server is None:
                continue
            sync = getattr(r.server, "sync_recovery_epoch", None)
            if sync is not None:
                try:
                    sync()
                except Exception as e:
                    log.warning("recovery epoch sync failed for "
                                "replica %d (%s): %r", r.index,
                                r.host, e)
                by_host[r.host] = r
        # adopts can hold an agent's control connection for seconds
        # (freeze-for-adopt waits out the current dispatch), starving
        # the heartbeats queued behind them — mask lease expiries for
        # the duration so recovery can't fail over the very replicas
        # it is adopting from
        for r in by_host.values():
            pause = getattr(r.server, "pause_lease", None)
            if pause is not None:
                pause()
        for e in live:
            doc = e.request or {}
            request = GenRequest(
                prompt=list(doc.get("prompt", [])),
                max_new_tokens=int(doc.get("max_new_tokens", 64)),
                temperature=float(doc.get("temperature", 0.0)),
                top_k=int(doc.get("top_k", 0)),
                seed=int(doc.get("seed", 0)),
                id=e.rid,
                session=doc.get("session"),
                tenant=doc.get("tenant"),
                priority=doc.get("priority"))
            resp = None
            replica = by_host.get(e.host) if e.host else None
            if replica is not None:
                try:
                    resp = replica.server.adopt_parked(e.rid)
                except Exception as exc:
                    log.warning("recovery adopt of %r from %s failed "
                                "(%r); re-running from the prompt",
                                e.rid, e.host, exc)
            if resp is not None and resp.get("finished"):
                self._recover_finished(request, resp, e)
                report["finished"] += 1
                continue
            snap = resp.get("snapshot") if resp is not None else None
            mode = "adopt" if snap is not None else "rerun"
            ticket = Ticket(request, None)
            weights = self.tier_weights if self.tier_weights \
                is not None else _DEFAULT_WEIGHTS
            ticket.tier = request.priority \
                if request.priority in weights else DEFAULT_TIER
            ticket.tenant = request.tenant
            if snap is not None:
                # resume mid-stream: the wire snapshot carries the
                # full generated prefix — seed the resume buffer AND
                # the emit cursor from it, so the engine's re-emission
                # of the absolute window dedups exactly and a client
                # resuming at any offset <= the journaled one finds
                # its suffix in the buffer (the journal may be AHEAD
                # of what the client's socket actually delivered)
                gen = [int(t) for t in snap.get("generated", [])]
                ticket.migrate = snap
                ticket._tokens = list(gen)
                ticket._n_emitted = len(gen)
            else:
                # token-exact re-run from the prompt, charged one
                # attempt — the journaled offset is NOT seeded: the
                # engine regenerates from 0 and the buffer refills
                # byte-identically (deterministic decode)
                ticket.attempts = 1
            if self.traces is not None:
                trace = RequestTrace(request.id, t0=ticket.t_submit)
                trace.root.tags.update(
                    prompt_len=len(request.prompt),
                    max_new_tokens=request.max_new_tokens,
                    priority=ticket.tier, recovered=True)
                trace.add("recover", ticket.t_submit, attempt=False,
                          mode=mode, journal_offset=e.offset,
                          host=e.host)
                ticket.trace = trace
            if self.journal is not None:
                # fresh WAL rows in the NEW journal: a second crash
                # recovers from THIS boot's record (find_latest picks
                # the newest journal; the old one is left stale)
                ticket._journal = self.journal
                self.journal.admit(e.rid, doc, time.time())
            self._register_resume(ticket)
            tried: set[int] = set()
            while True:
                try:
                    target = self._route(ticket, tried)
                except NoHealthyReplicas:
                    self._shed_ticket(
                        self.replicas[0], ticket, 503,
                        "no healthy replica at recovery",
                        exc=NoHealthyReplicas)
                    report["shed"] += 1
                    break
                try:
                    target.enqueue(ticket, force=True)
                except (GatewayClosed, _ReplicaUnhealthy):
                    tried.add(target.index)
                    continue
                report["adopted" if mode == "adopt" else "rerun"] += 1
                break
        for r in by_host.values():
            resume_lease = getattr(r.server, "resume_lease", None)
            if resume_lease is not None:
                resume_lease()
        wall_ms = round((time.monotonic() - t0) * 1e3, 3)
        report["wall_ms"] = wall_ms
        self._t_recovered = time.monotonic()
        with self.stats.lock:
            self.stats.recoveries += 1
            self.stats.accepted += report["adopted"] + report["rerun"]
            self.stats.sessions_adopted += report["adopted"]
            self.stats.sessions_rerun += report["rerun"]
            self.stats.recovered_finished += report["finished"]
            self.stats.recovery_wall_ms += wall_ms
        if live:
            log.warning(
                "recovered %d journaled request(s) in %.0fms: "
                "%d adopted mid-stream, %d re-run from prompt, "
                "%d finished results, %d shed", len(live), wall_ms,
                report["adopted"], report["rerun"],
                report["finished"], report["shed"])
        return report

    def _recover_finished(self, request: GenRequest, resp: dict,
                          entry) -> None:
        """A request that FINISHED while the gateway was dead: the
        agent buffered the undelivered result — materialize it as an
        immediately-terminal ticket so the client's resume fetches the
        whole stream + done line. Bypasses ``_record_done`` on
        purpose: the latency fields a live completion carries
        (queue_wait/ttft/tpot) do not exist for a result that crossed
        a crash, and a fabricated zero would poison the histograms."""
        from tony_tpu.serve.agent import result_from_doc

        res = result_from_doc({**resp["result"], "id": request.id})
        ticket = Ticket(request, None)
        ticket.tier = request.priority if request.priority \
            else DEFAULT_TIER
        ticket.tenant = request.tenant
        metrics = {
            "id": request.id, "recovered": True,
            "tokens_in": len(res.prompt),
            "tokens_out": len(res.tokens),
            "finish_reason": res.finish_reason,
            "attempts": 0,
        }
        with ticket._emit_lock:
            ticket._tokens = list(res.tokens)
            ticket._n_emitted = len(res.tokens)
            ticket.metrics = metrics
            ticket.state = DONE
            ticket.t_terminal = time.monotonic()
            ticket._emit(("done", res, metrics))
        self._register_resume(ticket)
        if self.journal is not None:
            # admit + done into the NEW journal: a second crash must
            # not try to adopt a session this boot already closed
            self.journal.admit(request.id, entry.request or {},
                               time.time())
            self.journal.done(request.id)

    def kill(self) -> None:
        """Die the way SIGKILL would — for chaos harnesses that crash
        an IN-PROCESS gateway (bench extras.recovery): no drain, no
        journal compaction (the WAL must survive exactly as the crash
        left it), and above all NO agent resets or epoch bumps — a
        dead process cannot POST /v1/reset, so neither may this path,
        or it would wipe the very parked sessions recovery exists to
        adopt. Remote transports are closed FIRST so any replica
        thread racing into its breaker sees a dead wire (logged,
        harmless), exactly like the real thing."""
        for loop in (self.scaler, self.rebalancer, self._alert_loop,
                     self._autotune_loop):
            if loop is not None:
                try:
                    loop.stop()
                except Exception:
                    pass
        wd = self._watchdog
        self._watchdog = None
        if wd is not None:
            wd.stop()
        self._closed = True
        for r in self.replicas:
            server = r.server
            if server is not None \
                    and getattr(server, "transport", None) is not None:
                try:
                    server.close(drain_agent=False)
                except Exception:
                    pass
        for r in self.replicas:
            with r.cv:
                r._stop = True
                r._tickets.clear()
                r.queue.steal_all()
                r.outstanding = 0
                r.cv.notify_all()
        for r in self.replicas:
            r.join(2.0)
        if self.journal is not None:
            self.journal.close()  # flush, never compact
        with self._resume_lock:
            self._resume.clear()
        self._drain_done = False

    def _shed_ticket(self, replica: _Replica, ticket: Ticket,
                     status: int, reason: str,
                     exc: type | None = None) -> None:
        """Terminal-event a stolen ticket the gateway gave up on,
        charged to the FAILED replica's shed count so per-replica
        /stats reconciles with ``shed_by_status`` (its ``outstanding``
        was already zeroed wholesale by the steal, so that is NOT
        touched). ``exc`` tells ``Ticket.result()`` which Shed subclass
        to raise when the bare status is ambiguous (the 503 family)."""
        _release_ticket_payload(ticket)  # a dead ticket must not pin
        #                                  shared-pool pages
        if ticket.trace is not None:
            ticket.trace.finish(outcome="shed", status=status,
                                reason=reason)
            self._export_trace(ticket)
        with ticket._emit_lock:
            # state flip + terminal emit under the emit lock: a failed
            # replica's late token delta can't slip in AFTER the shed
            # event the client treats as final
            ticket.state = SHED
            ticket._shed_exc_cls = exc
            ticket.t_terminal = time.monotonic()
            ticket._shed_status = status
            ticket._shed_reason = reason
            replica.shed += 1
            self._record_shed(replica, status, tier=ticket.tier)
            ticket._emit(("shed", status, reason))
        if ticket._journal is not None:
            ticket._journal.shed(ticket.request.id, status)

    def _note_probe(self, replica: _Replica) -> None:
        with self.stats.lock:
            self.stats.probes += 1

    def _note_rejoin(self, replica: _Replica) -> None:
        wd = self._watchdog  # snapshot (see _beat)
        if wd is not None:
            wd.register(str(replica.index))
        with self.stats.lock:
            self.stats.rejoins += 1

    def _note_quarantine(self, replica: _Replica) -> None:
        log.error("replica %d quarantined after %d consecutive "
                  "failures", replica.index, replica.consecutive_failures)
        with self.stats.lock:
            self.stats.quarantines += 1

    @property
    def n_healthy(self) -> int:
        return sum(1 for r in self.replicas if r.state == HEALTHY)

    def health(self) -> dict:
        """The /healthz payload: per-replica breaker state + heartbeat
        age, so a load balancer sees a DEGRADED gateway (one replica
        down, still serving) before anything 503s."""
        now = time.monotonic()
        live = [r for r in self.replicas if not r.retired]
        n = self.n_healthy
        return {
            "status": "ok" if n == len(live)
            else ("degraded" if n else "down"),
            "healthy": n,
            "replicas": [{
                "replica": r.index,
                "state": r.state,
                "retiring": r.retiring,
                "heartbeat_age_s": round(now - r.last_beat, 3),
                "consecutive_failures": r.consecutive_failures,
            } for r in live],
        }

    # ----------------------------------------------------- observability

    def _export_trace(self, ticket: Ticket) -> None:
        """A finished (done or shed) trace goes into the debug ring
        (``GET /debug/trace/<id>``) and — with history on — as one
        Chrome trace-event JSON doc per line in
        ``metrics/traces.jsonl``, next to the requests.jsonl rows the
        same request id keys."""
        if self.traces is None or ticket.trace is None:
            return
        self.traces.put(ticket.trace)
        if self.history is not None:
            try:
                self.history.record_trace(ticket.trace.to_chrome())
            except Exception:
                # same contract as the requests.jsonl write: a dropped
                # trace row must never cost the client its terminal
                # event
                log.exception("history trace write failed")

    def _host_sample(self) -> dict:
        """Host resource gauges: process-tree RSS from /proc, TPU
        HBM/duty-cycle when the runtime exposes them (absent off-TPU).
        TTL-cached so the /proc walk runs per snapshot-second, not per
        request. Replicas are threads of THIS process, so the block is
        process-level truth attached to every replica row (documented
        in docs/OBSERVABILITY.md)."""
        now = time.monotonic()
        if self._host_cache is not None \
                and now - self._host_cache[0] < 1.0:
            return self._host_cache[1]
        from tony_tpu.metrics.sampler import process_tree_rss_bytes

        host: dict = {"rss_bytes": process_tree_rss_bytes(os.getpid())}
        try:
            if self._tpu_discoverer is None:
                from tony_tpu.utils.tpu_info import TpuDiscoverer

                self._tpu_discoverer = TpuDiscoverer()
            tpu = self._tpu_discoverer.device_metrics()
            if "hbm" in tpu:
                host["tpu_hbm_bytes"] = int(tpu["hbm"])
            if "util" in tpu:
                host["tpu_util"] = round(tpu["util"], 3)
        except Exception:  # noqa: BLE001 — discovery trouble degrades
            # to an RSS-only block, never a broken /stats
            log.debug("tpu metrics discovery failed", exc_info=True)
        self._host_cache = (now, host)
        return host

    # -------------------------------------------------------- accounting

    def stats_shed(self, status: int) -> None:
        with self.stats.lock:
            self.stats.shed_by_status[status] = \
                self.stats.shed_by_status.get(status, 0) + 1

    def _record_shed(self, replica: _Replica, status: int,
                     tier: str | None = None) -> None:
        self.stats_shed(status)
        if tier is not None:
            with self.stats.lock:
                self.stats.shed_by_tier[tier] = \
                    self.stats.shed_by_tier.get(tier, 0) + 1
        self._push_replica_metrics(replica)

    def _record_done(self, replica: _Replica, metrics: dict) -> None:
        with self.stats.lock:
            self.stats.completed += 1
            self.stats.tokens_in += metrics["tokens_in"]
            self.stats.tokens_out += metrics["tokens_out"]
            self.stats.prefix_hit_tokens += \
                metrics.get("prefix_hit_tokens", 0)
            self.stats.prefill_tokens_saved += \
                metrics.get("prefill_tokens_saved", 0)
            self.stats.drafted += metrics.get("drafted", 0)
            self.stats.draft_accepted += metrics.get("accepted", 0)
            tier = metrics.get("priority") or DEFAULT_TIER
            self.stats.completed_by_tier[tier] = \
                self.stats.completed_by_tier.get(tier, 0) + 1
            if tier not in self.stats.tier_wait:
                self.stats.tier_wait[tier] = Histogram()
            self.stats.window.append(metrics)
        # per-tier queue-wait histogram: the lifetime surface that
        # proves WFQ's no-starvation promise on /metrics
        self.stats.tier_wait[tier].observe(metrics["queue_wait_ms"] / 1e3)
        for key, ms_key in (("queue_wait", "queue_wait_ms"),
                            ("ttft", "ttft_ms"), ("tpot", "tpot_ms"),
                            ("e2e", "e2e_ms")):
            self.stats.hist[key].observe(metrics[ms_key] / 1e3)
        if self.history is not None:
            try:
                self.history.record(metrics)
            except Exception:
                # ANY failure (disk, or a request id json can't take):
                # a dropped history row must never cost the client its
                # done event — the ticket was already popped from
                # _tickets, so it is invisible to the failover steal
                # and a raise here would strand it terminal-event-less
                log.exception("history metrics write failed")
        self._push_replica_metrics(replica)

    def _push_replica_metrics(self, replica: _Replica) -> None:
        if self.metrics_store is None:
            return
        try:
            self.metrics_store.update_metrics(
                f"gateway:replica-{replica.index}",
                {k: v for k, v in replica.stats().items()
                 if isinstance(v, (int, float))})
        except Exception:
            log.exception("metrics store push failed")

    def snapshot(self) -> dict:
        out = self.stats.snapshot()
        out["ready"] = self.ready
        out["draining"] = self.draining
        # retired replicas drop out of the per-replica rows (and their
        # engine counters out of the fleet rollup — per-replica series
        # end when a replica does, like any scraped pod's); the
        # gateway-level request counters above are lifetime
        now = time.monotonic()
        live = [r for r in self.replicas if not r.retired]
        # one queue_signals per replica per scrape (the O(depth)
        # oldest-wait scan runs here, never on the per-request metrics
        # push), via the same helper scale_signals() uses — the
        # autoscaler and a human reading /stats see the same numbers
        queue = self._queue_block(live, now)
        sig_by_index = {s["replica"]: s for s in queue["per_replica"]}
        rows = []
        host = self._host_sample()
        for r in live:
            row = r.stats(include_dispatch=True)
            sig = sig_by_index[r.index]
            row["oldest_wait_s"] = sig["oldest_wait_s"]
            row["enqueue_rate_per_s"] = sig["enqueue_rate_per_s"]
            row["queued_by_tier"] = sig["by_tier"]
            row["host"] = host
            server = r.server  # single read vs concurrent retirement
            if server is not None:
                g = server.goodput()
                if g is not None:
                    row["goodput"] = g
                elif hasattr(server, "transport_stats"):
                    # a remote replica whose ledger has not been
                    # pulled yet reports an EXPLICIT null — silently
                    # omitting the key made "unobserved" look like a
                    # local engine with the timeline off
                    row["goodput"] = None
            rows.append(row)
        out["replicas"] = rows
        out["queued"] = queue["depth"]
        out["max_queue"] = self.max_queue
        # the ISSUE-9 queue block: fleet + per-replica queue sensors
        # (depth, oldest-wait age, enqueue rate) — the autoscaler's
        # primary input, useful standalone on /stats and /metrics
        out["queue"] = queue
        out["engine"] = self._engine_summary(rows, live)
        with self.stats.lock:
            out["routing"] = {
                "prefix_affinity": self.prefix_affinity,
                "prefix_routed": self.stats.prefix_routed,
                "handoffs": self.stats.handoffs,
                "migrations": self.stats.migrations,
                "migrate_lease_adoptions":
                    self.stats.migrate_lease_adoptions,
                "park_adoptions": self.stats.park_adoptions,
                "roles": {r.index: r.role for r in live}
                if self.roles else None,
            }
            # crash recovery (ISSUE-20): journaling state + what the
            # last --recover boot did — always present so a dashboard
            # can pin "journal on, 0 recoveries" as the healthy shape
            with self._resume_lock:
                n_resume = len(self._resume)
            out["recovery"] = {
                "journal": self.journal is not None,
                "resumable": n_resume,
                "recoveries": self.stats.recoveries,
                "sessions_adopted": self.stats.sessions_adopted,
                "sessions_rerun": self.stats.sessions_rerun,
                "recovered_finished": self.stats.recovered_finished,
                "recovery_wall_ms": round(
                    self.stats.recovery_wall_ms, 3),
            }
        with self.stats.lock:
            tiers = sorted(set(self.stats.completed_by_tier)
                           | set(self.stats.shed_by_tier)
                           | set(queue["by_tier"]))
            tier_rows = {}
            for tier in tiers:
                waits = sorted(
                    r["queue_wait_ms"] for r in self.stats.window
                    if (r.get("priority") or DEFAULT_TIER) == tier)
                tier_rows[tier] = {
                    "queued": queue["by_tier"].get(tier, 0),
                    "completed": self.stats.completed_by_tier.get(tier, 0),
                    "shed": self.stats.shed_by_tier.get(tier, 0),
                    "queue_wait_ms": {
                        "p50": _percentile(waits, 0.50),
                        "p99": _percentile(waits, 0.99)},
                }
            out["admission"] = {
                "tiers": dict(self.tier_weights if self.tier_weights
                              is not None else _DEFAULT_WEIGHTS),
                "by_tier": tier_rows,
                "quota": {**self.quotas.stats(),
                          "rejections": self.stats.quota_rejections},
            }
            out["supervision"] = {
                "healthy_replicas": self.n_healthy,
                "replicas": len(live),
                "retired": len(self.replicas) - len(live),
                "replicas_added": self.stats.replicas_added,
                "replicas_removed": self.stats.replicas_removed,
                "max_attempts": self.max_attempts,
                "stall_timeout_s": self.stall_timeout_s,
                "replica_failures": self.stats.replica_failures,
                "failovers": self.stats.failovers,
                "retries": self.stats.retries,
                "probes": self.stats.probes,
                "rejoins": self.stats.rejoins,
                "quarantines": self.stats.quarantines,
            }
            # the flight recorder's own trail: how many alert-triggered
            # bundles landed, and where the latest one is
            out["bundles"] = {
                "on_alert": self.bundle_on_alert
                and self.history is not None,
                "written": self.stats.bundles_written,
                "last_path": self.stats.last_bundle,
            }
        # fleet goodput ledger, merged from the per-replica ledgers
        # the rows above already computed (wall-clock weighted)
        out["engine"]["goodput"] = merge_ledgers(
            [row.get("goodput") for row in rows])
        # the adaptive shape controller (serve/autotune.py): status +
        # the live knob values it steers, per replica
        if self.autotune is not None:
            auto = self.autotune.snapshot()
            auto["replicas"] = self.autotune.knob_values(
                [(r.index, r.server) for r in live])
            out["engine"]["autotune"] = auto
        else:
            out["engine"]["autotune"] = {"enabled": False}
        if self.alerts is not None:
            out["alerts"] = {"enabled": True, **self.alerts.snapshot()}
        else:
            out["alerts"] = {"enabled": False}
        scaler = self.scaler
        if scaler is not None:
            out["scaler"] = scaler.status()
        rebalancer = self.rebalancer
        out["rebalance"] = rebalancer.status() \
            if rebalancer is not None else {"enabled": False}
        edge = self._edge_stats
        if edge is not None:
            try:
                out["edge"] = edge()
            except Exception:  # a dying edge must not break /stats
                log.exception("edge stats provider failed")
        return out

    def register_edge(self, stats_fn: Callable | None) -> None:
        """Attach the serving edge's connection-plane stats callable
        (-> dict); its block appears as snapshot()["edge"] and the
        ``tony_edge_*`` /metrics families. None detaches."""
        self._edge_stats = stats_fn

    def _engine_summary(self, replica_rows: list | None = None,
                        live: list | None = None) -> dict:
        """Fleet-level engine counters: the device work behind the
        request percentiles (prefills run, decode rounds, occupancy,
        overshoot waste) plus the speculative-decoding and prefix-cache
        effectiveness blocks, summed across replicas — so /stats shows
        savings NEXT TO the work they avoided. ``replica_rows`` (the
        per-replica stats rows snapshot() just built) donates its
        ``dispatch`` blocks so one scrape takes each timeline's lock
        once, not twice."""
        replicas = live if live is not None \
            else [r for r in self.replicas if not r.retired]
        servers = [r.server for r in replicas if r.server is not None]
        counts = [s.counters() for s in servers]
        total = lambda key: sum(c.get(key, 0) for c in counts)  # noqa: E731
        # migration totals include the retired replicas' carry — see
        # _Stats.migrate_carry
        carry = dict(self.stats.migrate_carry)
        mtotal = lambda key: total(key) + carry.get(key, 0)  # noqa: E731
        lookups = total("prefix_lookups")
        drafted = total("spec_drafted")
        if replica_rows is not None:
            dispatch_blocks = [row["dispatch"] for row in replica_rows
                               if "dispatch" in row]
        else:
            dispatch_blocks = [s.timeline.summary() for s in servers
                               if s.timeline is not None]
        return {
            # fleet dispatch timeline: per-kind count / host-wall ms /
            # compile split / tokens, merged across replicas — the
            # /stats block ROADMAP 4's dispatch-overhead work reads
            "dispatch": DispatchTimeline.merge(dispatch_blocks),
            "prefills": total("prefills"),
            "decode_steps": total("decode_steps"),
            "dispatches": total("dispatches"),
            "wasted_steps": total("wasted_steps"),
            "active_slots": sum(s.slots.n_active for s in servers),
            "slots": sum(s.slots.batch_size for s in servers),
            "spec": {
                "enabled": any(s.speculate_k > 0 for s in servers),
                "rounds": total("spec_rounds"),
                "drafted": drafted,
                "accepted": total("spec_accepted"),
                "acceptance_rate": round(
                    total("spec_accepted") / drafted, 4)
                if drafted else 0.0,
            },
            "prefix": {
                "enabled": any(s.prefix is not None for s in servers),
                "lookups": lookups,
                "hits": total("prefix_hits"),
                "hit_rate": round(total("prefix_hits") / lookups, 4)
                if lookups else 0.0,
                "hit_tokens": total("prefix_hit_tokens"),
                "prefill_tokens_saved": total("prefill_tokens_saved"),
                "entries": total("prefix_entries"),
                "bytes": total("prefix_bytes"),
                "budget_bytes": total("prefix_budget_bytes"),
                "evictions": total("prefix_evictions"),
            },
            # disaggregation (ISSUE-12): chunked-prefill volume and
            # prefill->decode handoffs, fleet-wide
            "prefill_chunks": {
                "enabled": any(getattr(s, "prefill_chunk", 0) > 0
                               for s in servers),
                "dispatches": total("prefill_chunk_dispatches"),
                "requests": total("prefill_chunked_requests"),
            },
            "handoffs": {
                "out": total("handoffs_out"),
                "in": total("handoffs_in"),
            },
            # live migration (ISSUE-18): sessions frozen out / adopted
            # in, split by HOW the pages moved — owner swap (shared
            # pool, ids only) vs gathered content — plus the bytes the
            # swaps did NOT copy and the freeze->resume stall the
            # moved streams actually saw
            "migrations": {
                "out": mtotal("migrations_out"),
                "in": mtotal("migrations_in"),
                "local": mtotal("migrations_local"),
                "remote": mtotal("migrations_remote"),
                "pages_moved": mtotal("migrate_pages_moved"),
                "bytes_avoided": mtotal("migrate_bytes_avoided"),
                "bytes_wire": mtotal("migrate_bytes_wire"),
                "delta_in": mtotal("migrate_delta_in"),
                "freeze_resume_ms": round(
                    mtotal("migrate_freeze_resume_ms"), 3),
            },
            # sharded replicas (ISSUE-14): mesh topology rollup —
            # device/shard counts ride the flat counters (so remote
            # agents report too); the axis layout comes from the first
            # local sharded engine
            "mesh": {
                "enabled": any("mesh_devices" in c for c in counts),
                "devices": max((c.get("mesh_devices", 1)
                                for c in counts), default=1),
                "kv_shards": max((c.get("mesh_kv_shards", 1)
                                  for c in counts), default=1),
                "param_bytes_per_chip": max(
                    (c.get("mesh_param_bytes_per_chip", 0)
                     for c in counts), default=0),
                "topology": next(
                    (s.mesh_info()["axes"] for s in servers
                     if callable(getattr(s, "mesh_info", None))
                     and getattr(s, "mesh", None) is not None), {}),
            },
            # the host-RAM page tier (serve/tier.py): spill/restore
            # volume and residency — page_ins > 0 under prefix traffic
            # is the tier paying for itself, page_ins high while
            # kv_pages is pressured is the kv_host_thrash alert
            "kv_host": {
                "enabled": any(getattr(s, "host_tier", None) is not None
                               for s in servers),
                "entries": total("kv_host_entries"),
                "bytes": total("kv_host_bytes"),
                "budget_bytes": total("kv_host_budget_bytes"),
                "tokens": total("kv_host_tokens"),
                "spills": total("kv_host_spills"),
                "page_ins": total("kv_host_page_ins"),
                "spill_bytes": total("kv_host_spill_bytes"),
                "page_in_bytes": total("kv_host_page_in_bytes"),
                "evictions": total("kv_host_evictions"),
            },
            # the paged-KV utilization block (ROADMAP 4's fixed-shape-
            # waste sensor): how many pages exist / hold tokens / are
            # shared copy-on-write, and how many bytes that keeps
            # resident vs the tokens actually living in them
            "kv_pages": {
                "enabled": any(s.paged for s in servers),
                "total": total("kv_pages_total"),
                "used": total("kv_pages_used"),
                "free": total("kv_pages_free"),
                "reserved": total("kv_pages_reserved"),
                "cow_shared": total("kv_cow_shared"),
                "cow_forks": total("kv_cow_forks"),
                "page_size": max((c.get("kv_page_size", 0)
                                  for c in counts), default=0),
                "bytes_resident": total("kv_bytes_resident"),
                "tokens_resident": total("kv_tokens_resident"),
            },
        }
