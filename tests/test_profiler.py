"""Profiler subsystem tests (greenfield vs reference; SURVEY.md §5.1)."""

import glob
import json
import os

import jax.numpy as jnp

from tony_tpu.profiler import StepProfiler, trigger_path, write_trigger


def test_trigger_roundtrip(tmp_path):
    path = write_trigger(str(tmp_path), num_steps=3, task_id="worker:1")
    assert path == trigger_path(str(tmp_path), "worker:1")
    with open(path) as f:
        assert json.load(f)["num_steps"] == 3
    # per-task isolation: a different task's poller must not see it
    assert not os.path.exists(trigger_path(str(tmp_path), "worker:0"))


def test_step_profiler_captures_trace(tmp_path):
    prof = StepProfiler(workdir=str(tmp_path), task_id="worker:0")
    assert prof.poll() is False  # idle poll is cheap + false
    write_trigger(str(tmp_path), num_steps=2, task_id="worker:0",
                  logdir=str(tmp_path / "prof"))
    for _ in range(4):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        prof.poll()
    assert prof.captures == 1
    assert prof.active_steps_left == 0
    # trigger consumed; xplane artifacts written
    assert not os.path.exists(trigger_path(str(tmp_path), "worker:0"))
    artifacts = glob.glob(str(tmp_path / "prof" / "**" / "*"), recursive=True)
    assert any(os.path.isfile(a) for a in artifacts), artifacts


def test_step_profiler_ignores_foreign_trigger(tmp_path):
    prof = StepProfiler(workdir=str(tmp_path), task_id="worker:0")
    write_trigger(str(tmp_path), num_steps=1, task_id="worker:1")
    assert prof.poll() is False
    assert prof.captures == 0


def test_coordinator_command_queue():
    """request_profile -> queued -> drained exactly once on heartbeat."""
    import tempfile

    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import ClientRpcHandler, Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.application.security.enabled", False)
    with tempfile.TemporaryDirectory() as tmp:
        conf.set("tony.staging-dir", tmp)
        conf.set("tony.history.location", os.path.join(tmp, "hist"))
        coord = Coordinator(conf, "application_cmdq", os.path.join(tmp, "job"))
        try:
            handler = ClientRpcHandler(coord)
            assert handler.request_profile("worker:0", 7) is True
            assert handler.request_profile("ghost:9", 1) is False
            resp = handler.task_executor_heartbeat("worker:0")
            assert resp["commands"] == [{"type": "profile", "num_steps": 7}]
            # drained: second heartbeat is empty
            assert handler.task_executor_heartbeat("worker:0")["commands"] == []
        finally:
            coord.rpc.stop()
            coord.metrics_rpc.stop()


# ------------------------------------------------------- xplane parsing


def test_xplane_parse_cpu_trace(tmp_path):
    """On the CPU backend the trace has host planes but no /device: plane
    — the parser must say 'no device data' (None), not crash, so bench
    callers can fall back to wall-clock."""
    import jax

    from tony_tpu.profiler import device_busy_ms, op_totals_ms, xplane

    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    f(x).block_until_ready()
    jax.profiler.stop_trace()

    files = xplane.xplane_files(logdir)
    assert files, "trace wrote no xplane dump"
    space = xplane.load_xspace(files[-1])
    if space is None:  # proto stubs unavailable in this env: degraded mode
        assert op_totals_ms(logdir) is None
        assert device_busy_ms(logdir) is None
        return
    assert [p.name for p in space.planes]  # parsed something real
    # CPU backend -> no TPU device plane -> None (graceful degradation)
    if not xplane.device_planes(space):
        assert device_busy_ms(logdir) is None


def test_trace_device_ms_cpu_returns_none_or_positive():
    import jax

    from tony_tpu.profiler import trace_device_ms

    f = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    out = trace_device_ms(f, (x,), steps=2)
    assert out is None or out > 0


def test_hbm_estimate_bytes():
    import jax

    from tony_tpu.profiler import hbm_estimate_bytes

    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((64, 64), jnp.float32)
    est = hbm_estimate_bytes(f, x)
    # args (16 KB) + out (16 KB); CPU backends may report nothing (0)
    assert est == 0 or est >= 2 * 64 * 64 * 4


def test_hbm_estimate_bytes_bad_input_is_zero():
    from tony_tpu.profiler import hbm_estimate_bytes

    assert hbm_estimate_bytes(object()) == 0
