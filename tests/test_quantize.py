"""int8 weight-only serving (models/quantize.py + QuantDense).

Correctness anchor: the quantized model must match a full-precision
forward over the SAME dequantized weights (the kernel adds no error
beyond quantization itself), across architecture families and the
KV-cache decode path.
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import (
    Transformer,
    TransformerConfig,
    generate,
    quantize_for_serving,
)
from tony_tpu.ops.quant import dequantize_q8


def _dequant_params(params, reference):
    """Quantized tree -> fp tree shaped like ``reference``."""

    def walk(node, ref):
        if isinstance(node, dict) and "kernel_q8" in node:
            w = np.asarray(dequantize_q8(node["kernel_q8"], node["scale"]))
            out = {"kernel": jnp.asarray(
                w.reshape(np.asarray(ref["kernel"]).shape), jnp.float32)}
            if "bias" in node:
                out["bias"] = node["bias"]
            return out
        if isinstance(node, dict):
            return {k: walk(v, ref[k]) for k, v in node.items()}
        return node

    return walk(params, reference)


CONFIGS = {
    "llama_gqa": dict(norm="rms", positional="rope", use_bias=False,
                      gated_mlp=True, n_kv_heads=2),
    "gpt2": dict(norm="layer", positional="learned", use_bias=True,
                 activation="gelu_tanh"),
    "neox": dict(norm="layer", positional="rope", use_bias=True,
                 parallel_residual=True, rotary_dims=4),
    "phi": dict(norm="layer", positional="rope", use_bias=True,
                parallel_residual=True, rotary_dims=4,
                tied_embeddings=False, lm_head_bias=True),
}


@pytest.mark.parametrize("family", [
    # gpt2 (learned positions + biases) is the heavyweight variant
    # (~15 s of compiles); tier-1 keeps the others, -m slow runs it
    pytest.param(f, marks=pytest.mark.slow) if f == "gpt2" else f
    for f in sorted(CONFIGS)])
def test_quantized_forward_matches_dequant_reference(family):
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            **CONFIGS[family])
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    qmodel, qparams = quantize_for_serving(model, params)
    assert qmodel.cfg.quantized
    got = np.asarray(qmodel.apply(qparams, tokens))
    ref = np.asarray(model.apply(_dequant_params(qparams, params), tokens))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # and close to the ORIGINAL fp model (int8 error only)
    fp = np.asarray(model.apply(params, tokens))
    assert np.abs(got - fp).mean() / (np.abs(fp).mean() + 1e-9) < 0.05


def test_quantized_decode_matches_quantized_forward():
    """KV-cache decode through QuantDense == the quantized full forward
    (the serving path generate() drives)."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference", gated_mlp=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    qmodel, qparams = quantize_for_serving(model, params)
    full = np.asarray(qmodel.apply(qparams, tokens))
    cache = qmodel.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
    steps = []
    variables = {"params": qparams["params"], "cache": cache}
    for i in range(tokens.shape[1]):
        logits, mut = qmodel.apply(variables, tokens[:, i:i + 1],
                                   decode=True, mutable=["cache"])
        variables = {"params": qparams["params"], "cache": mut["cache"]}
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               rtol=2e-4, atol=2e-4)


def test_quantized_generate_runs_greedy():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    qmodel, qparams = quantize_for_serving(model, params)
    out = generate(qmodel, qparams["params"], prompt, max_new_tokens=4)
    assert out.shape == (1, 4)
    assert bool(jnp.all((out >= 0) & (out < 64)))


def test_quantize_rejects_unsupported_configs():
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=16, dtype=jnp.float32)
    scan = Transformer(TransformerConfig(**base, scan_layers=True))
    with pytest.raises(ValueError, match="scan_layers"):
        quantize_for_serving(scan, {})


def test_quantized_params_are_half_the_bytes():
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference", gated_mlp=True,
                            tied_embeddings=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    _, qparams = quantize_for_serving(model, params)

    def nbytes(tree):
        return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))

    # dense kernels went fp32 -> int8 (+ tiny scales); embeddings/norms
    # stay fp32, so the total shrinks by well over 2x for kernel-heavy
    # trees and the kernels themselves by ~4x
    assert nbytes(qparams) < 0.5 * nbytes(params)


def test_q8_matmul_prime_rows_pads_not_degenerates():
    """A prime activation row count (batch*prompt_len) must pad to block
    multiples, not collapse to 1-row blocks."""
    from tony_tpu.ops import dequantize_q8, q8_matmul, quantize_q8

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((257, 64)), jnp.float32)  # prime m
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    w_q, scale = quantize_q8(w)
    got = np.asarray(q8_matmul(x, w_q, scale, block_m=128))
    want = np.asarray(x) @ np.asarray(dequantize_q8(w_q, scale))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_quantized_params_tp_logical_axes():
    """int8 leaves shard on the same logical axes as their bf16 kernels
    (VERDICT r3 next #5): column-parallel q/wi out dims, row-parallel
    o/wo in dims, GQA k/v on the always-replicated kv_heads."""
    from tony_tpu.models.transformer import logical_axis_rules_tree

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=1, d_ff=64,
                            max_seq_len=16, dtype=jnp.float32,
                            gated_mlp=True,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    _, qparams = quantize_for_serving(model, params)
    axes = logical_axis_rules_tree(qparams)
    attn = axes["params"]["block_0"]["attn"]
    assert attn["q"]["kernel_q8"] == ("embed", "heads")
    assert attn["q"]["scale"] == ("heads",)
    assert attn["k"]["kernel_q8"] == ("embed", "kv_heads")  # GQA guard
    assert attn["v"]["scale"] == ("kv_heads",)
    assert attn["o"]["kernel_q8"] == ("heads", "embed")  # row-parallel
    assert attn["o"]["scale"] == ("embed",)
    mlp = axes["params"]["block_0"]["mlp"]
    assert mlp["wi"]["kernel_q8"] == ("embed", "mlp")
    assert mlp["wo"]["kernel_q8"] == ("mlp", "embed")
    # fp leaves (embedding) keep their rules
    assert axes["params"]["embedding"] == ("vocab", "embed")
    # norm scales stay replicated (same leaf NAME as QuantDense's scale)
    norm_scale = axes["params"]["block_0"]["ln1"]["scale"]
    assert norm_scale == (None,)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_quantized_forward_under_tensor_parallel_mesh():
    """generate --int8 under a tp mesh (custom-partitioned pallas q8
    matmul): sharded logits and greedy tokens match the replicated run."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.mesh import DATA
    from tony_tpu.parallel.sharding import tree_shardings

    mesh = make_mesh(MeshSpec(data=2, tensor=4))
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=2, d_ff=64,
                            max_seq_len=24, dtype=jnp.float32,
                            gated_mlp=True, mesh=mesh,
                            attention_backend="reference")
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 12), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    qmodel, qparams = quantize_for_serving(model, params)
    logits_rep = qmodel.apply(qparams, tokens)

    sh = tree_shardings(mesh, logical_axis_rules_tree(qparams), "tp")
    placed = jax.device_put(qparams, sh)
    # q kernels really are tensor-sharded on the device mesh
    q_leaf = placed["params"]["block_0"]["attn"]["q"]["kernel_q8"]
    assert q_leaf.sharding.spec[1] == "tensor", q_leaf.sharding.spec
    tok_sh = jax.device_put(tokens, NamedSharding(mesh, P(DATA)))
    logits_tp = jax.jit(qmodel.apply)(placed, tok_sh)
    np.testing.assert_allclose(np.asarray(logits_tp),
                               np.asarray(logits_rep),
                               atol=2e-4, rtol=2e-4)


def test_lora_adapter_logical_axes():
    """LoRA A/B shard like their host kernel: A carries the input axis,
    B the output axes; rank stays replicated."""
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.train.lora import lora_init

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=1, d_ff=64,
                            max_seq_len=16, dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    lora = lora_init(jax.random.PRNGKey(1), params, rank=4,
                     targets=("q", "v", "wi"))
    axes = logical_axis_rules_tree(lora)
    qk = axes["params"]["block_0"]["attn"]["q"]["kernel"]
    assert qk["a"] == ("embed", None)
    assert qk["b"] == (None, "heads", "kv")
    vk = axes["params"]["block_0"]["attn"]["v"]["kernel"]
    assert vk["b"] == (None, "kv_heads", "kv")  # GQA: fewer v heads
    wik = axes["params"]["block_0"]["mlp"]["wi"]["kernel"]
    assert wik["a"] == ("embed", None)
    assert wik["b"] == (None, "mlp")


def test_quantized_moe_matches_dequant_reference():
    """Mixtral-style int8 MoE serving (VERDICT r3 next #5): the quantized
    expert path (vmapped pallas dequant matmul) matches a full-precision
    forward over the dequantized expert weights, routed AND dropless."""
    for dropless in (True, False):
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq_len=16,
                                dtype=jnp.float32, moe_every=2,
                                moe_num_experts=4, moe_top_k=2,
                                moe_gated=True, moe_renormalize=True,
                                moe_dropless=dropless,
                                attention_backend="reference")
        model = Transformer(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0, 64)
        params = model.init(jax.random.PRNGKey(5), tokens)
        qmodel, qparams = quantize_for_serving(model, params)

        # dequantize every int8 leaf back into the fp tree and compare
        def dq(node, ref):
            if isinstance(node, dict) and "wi_q8" in node:
                out = {"router": node["router"]}
                for nm in ("wi", "wg", "wo"):
                    if nm + "_q8" in node:
                        out[nm] = jnp.asarray(
                            np.asarray(node[nm + "_q8"], np.float32)
                            * np.asarray(node[nm + "_scale"])[:, None, :])
                return out
            if isinstance(node, dict) and "kernel_q8" in node:
                w = np.asarray(dequantize_q8(node["kernel_q8"],
                                             node["scale"]))
                out = {"kernel": jnp.asarray(
                    w.reshape(np.asarray(ref["kernel"]).shape),
                    jnp.float32)}
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            if isinstance(node, dict):
                return {k: dq(v, ref[k]) for k, v in node.items()}
            return node

        fp_params = dq(qparams, params)
        logits_q = qmodel.apply(qparams, tokens)
        logits_fp = model.apply(fp_params, tokens)
        np.testing.assert_allclose(np.asarray(logits_q),
                                   np.asarray(logits_fp),
                                   atol=2e-4, rtol=2e-4)


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8-dev mesh")
def test_quantized_moe_expert_sharded_matches_unsharded():
    """int8 MoE under expert parallelism (VERDICT r4 weak #6): with
    cfg.mesh carrying an expert axis, the q8 expert FFN runs shard-mapped
    over it — quantized expert weights SHARD instead of replicating —
    and the result must equal the unsharded q8 forward, routed AND
    dropless, with the weights actually placed expert-sharded."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "expert"))
    for dropless in (True, False):
        base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                    d_ff=64, max_seq_len=16, dtype=jnp.float32,
                    moe_every=2, moe_num_experts=4, moe_top_k=2,
                    moe_gated=True, moe_renormalize=True,
                    moe_dropless=dropless,
                    attention_backend="reference")
        model = Transformer(TransformerConfig(**base))
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0, 64)
        params = model.init(jax.random.PRNGKey(5), tokens)
        qmodel, qparams = quantize_for_serving(model, params)
        logits_ref = qmodel.apply(qparams, tokens)

        sh_model = Transformer(TransformerConfig(**base, mesh=mesh))
        sh_qmodel, _ = quantize_for_serving(sh_model, params)

        from tony_tpu.models import shard_expert_qparams

        placed = shard_expert_qparams(mesh, qparams)
        moe = placed["params"]["block_1"]["moe"]
        assert not moe["wi_q8"].sharding.is_fully_replicated, \
            "expert weights should be sharded over the expert axis"
        logits_sh = jax.jit(sh_qmodel.apply)(placed, tokens)
        np.testing.assert_allclose(np.asarray(logits_sh),
                                   np.asarray(logits_ref),
                                   atol=2e-5, rtol=2e-5)
