"""Shared agent→user-process control-file protocol.

Both on-demand channels (profiler triggers, elastic save_and_exit) drop a
small JSON file in the task's workdir, suffixed with the task id because
tasks can share a job dir on one host. Atomic tmp-write + rename so a
poller never reads a partial file.
"""

from __future__ import annotations

import json
import os


def task_suffix(task_id: str) -> str:
    return f".{task_id.replace(':', '-')}" if task_id else ""


def current_task_id() -> str:
    """This process's task id from the injected env, or '' standalone."""
    role = os.environ.get("TONY_JOB_NAME", "")
    return f"{role}:{os.environ.get('TONY_TASK_INDEX', '0')}" if role else ""


def control_file_path(workdir: str, name: str, task_id: str = "") -> str:
    return os.path.join(workdir, name + task_suffix(task_id))


def write_control_file(path: str, payload: dict) -> str:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path
