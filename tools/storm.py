#!/usr/bin/env python
"""Connection-storm load harness for the event-driven edge (ISSUE-16).

A selectors-based client: one thread holds every socket, so the
HARNESS can't be the concurrency bottleneck it is measuring. Two
phases against a live gateway:

  idle  - open N keep-alive connections that never send a byte and
          hold them; the gateway's RSS delta prices the edge's memory
          per idle connection (the edge parks them in the loop at
          zero thread cost).
  storm - drive S concurrent NDJSON token streams (POST /v1/generate,
          stream=true) with a bursty arrival schedule over a synthetic
          tenant population; measure TTFT percentiles, shed rate
          (429/503 with Retry-After), completion count, and peak
          concurrent open streams. A spot-check re-runs the first K
          prompts unary at zero concurrency and asserts the streamed
          token_ids reassemble to the exact same sequence.

Usage (the gateway must already be running):

  python tools/storm.py --base http://127.0.0.1:8000 \
      --idle 10000 --streams 10000 --tokens 4 --server-pid $GW_PID \
      --json /tmp/storm.json

Pure stdlib, no jax — runs as a light sidecar process so the client's
fd budget doesn't share the server's.
"""

import argparse
import json
import selectors
import socket
import sys
import time
import urllib.request


def proc_status(pid: int) -> dict:
    """VmRSS (KiB) and Threads for a pid, from /proc."""
    out = {}
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss_kb"] = int(line.split()[1])
                elif line.startswith("Threads:"):
                    out["threads"] = int(line.split()[1])
    except OSError:
        pass
    return out


def http_get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def http_post_json(base: str, path: str, doc: dict,
                   timeout: float = 60.0):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def parse_base(base: str) -> tuple[str, int]:
    rest = base.split("//", 1)[-1].rstrip("/")
    host, _, port = rest.partition(":")
    return host, int(port or 80)


# --------------------------------------------------------------- idle

def idle_phase(host: str, port: int, n: int, server_pid: int,
               base: str, hold_s: float, deadline: float) -> dict:
    """Open n idle keep-alive connections, hold them, price the RSS."""
    before = proc_status(server_pid)
    sel = selectors.DefaultSelector()
    socks: list[socket.socket] = []
    pending = 0
    opened = 0
    errors = 0
    i = 0
    while (opened + errors) < n and time.monotonic() < deadline:
        # ramp in bounded batches so connect() backlog overflow turns
        # into retries, not a thundering failure
        while i < n and pending < 512:
            s = socket.socket()
            s.setblocking(False)
            try:
                s.connect((host, port))
            except BlockingIOError:
                pass
            except OSError:
                s.close()
                errors += 1
                i += 1
                continue
            sel.register(s, selectors.EVENT_WRITE)
            pending += 1
            i += 1
        for key, _ in sel.select(timeout=1.0):
            s = key.fileobj
            sel.unregister(s)
            pending -= 1
            err = s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                s.close()
                errors += 1
            else:
                socks.append(s)
                opened += 1
    time.sleep(hold_s)  # let the server's accept loop fully settle
    after = proc_status(server_pid)
    stats = {}
    try:
        stats = http_get_json(base, "/stats").get("edge", {})
    except OSError:
        pass
    out = {
        "target": n,
        "opened": opened,
        "connect_errors": errors,
        "server_rss_before_kb": before.get("rss_kb", 0),
        "server_rss_after_kb": after.get("rss_kb", 0),
        "server_threads": after.get("threads", 0),
        "edge_open_connections": stats.get("open_connections", -1),
    }
    if opened:
        delta = out["server_rss_after_kb"] - out["server_rss_before_kb"]
        out["rss_kb_per_idle_conn"] = round(max(0, delta) / opened, 3)
    for s in socks:
        try:
            s.close()
        except OSError:
            pass
    sel.close()
    return out


# -------------------------------------------------------------- storm

class _Stream:
    """One in-flight streaming request's client-side state machine."""

    __slots__ = ("sock", "buf", "state", "status", "t_sent", "t_first",
                 "t_done", "tokens", "chunk_need", "body", "idx",
                 "keepalives")

    def __init__(self, sock, idx):
        self.sock = sock
        self.idx = idx
        self.buf = b""
        self.state = "connect"   # connect -> sent -> headers -> body
        self.status = 0
        self.t_sent = 0.0
        self.t_first = 0.0
        self.t_done = 0.0
        self.tokens: list[int] = []
        self.chunk_need = -1     # -1: expecting a chunk-size line
        self.body = b""
        self.keepalives = 0

    def feed(self, data: bytes) -> bool:
        """Consume response bytes; True when the response is complete."""
        self.buf += data
        if self.state == "headers":
            end = self.buf.find(b"\r\n\r\n")
            if end < 0:
                return False
            head = self.buf[:end].decode("latin-1")
            self.buf = self.buf[end + 4:]
            self.status = int(head.split(None, 2)[1])
            self.state = "body"
        if self.state != "body":
            return False
        # de-chunk: every complete chunk's payload joins self.body;
        # a zero chunk ends the response
        while True:
            if self.chunk_need < 0:
                nl = self.buf.find(b"\r\n")
                if nl < 0:
                    return False
                try:
                    self.chunk_need = int(self.buf[:nl], 16)
                except ValueError:
                    # not chunked (an error doc with Content-Length):
                    # callers treat EOF as the end instead
                    self.body += self.buf
                    self.buf = b""
                    return False
                self.buf = self.buf[nl + 2:]
                if self.chunk_need == 0:
                    return True
            if len(self.buf) < self.chunk_need + 2:
                return False
            self.body += self.buf[:self.chunk_need]
            self.buf = self.buf[self.chunk_need + 2:]
            self.chunk_need = -1
            self._drain_lines()

    def _drain_lines(self) -> None:
        while b"\n" in self.body:
            line, _, self.body = self.body.partition(b"\n")
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if doc.get("keepalive"):
                self.keepalives += 1
                continue
            if "finish_reason" in doc:
                # the terminal doc repeats the FULL token_ids
                # (prompt + generation) — the delta frames already
                # delivered them
                continue
            ids = doc.get("token_ids")
            if ids:
                if not self.t_first:
                    self.t_first = time.monotonic()
                self.tokens.extend(int(x) for x in ids)


def storm_prompt(i: int) -> list[int]:
    return [1 + (i % 50), 2, 3]


def storm_phase(host: str, port: int, base: str, n: int, tokens: int,
                tenants: int, bursts: int, burst_gap_s: float,
                server_pid: int, deadline: float, check: int) -> dict:
    """Drive n concurrent streams with a bursty arrival schedule."""
    sel = selectors.DefaultSelector()
    streams: list[_Stream] = []
    live = 0
    peak_live = 0
    done: list[_Stream] = []
    failed = 0
    burst_size = max(1, n // max(1, bursts))
    launched = 0
    next_burst_t = time.monotonic()
    peak_threads = proc_status(server_pid).get("threads", 0)

    def launch_one(i: int) -> None:
        nonlocal live, failed
        s = socket.socket()
        s.setblocking(False)
        try:
            s.connect((host, port))
        except BlockingIOError:
            pass
        except OSError:
            failed += 1
            s.close()
            return
        st = _Stream(s, i)
        streams.append(st)
        sel.register(s, selectors.EVENT_WRITE, st)
        live += 1

    def finish(st: _Stream, ok: bool) -> None:
        nonlocal live, failed
        st.t_done = time.monotonic()
        sel.unregister(st.sock)
        try:
            st.sock.close()
        except OSError:
            pass
        live -= 1
        if ok:
            done.append(st)
        else:
            failed += 1

    while (launched < n or live > 0) and time.monotonic() < deadline:
        now = time.monotonic()
        if launched < n and now >= next_burst_t:
            for _ in range(min(burst_size, n - launched)):
                launch_one(launched)
                launched += 1
            next_burst_t = now + burst_gap_s
        peak_live = max(peak_live, live)
        for key, mask in sel.select(timeout=0.2):
            st = key.data
            if mask & selectors.EVENT_WRITE:
                err = st.sock.getsockopt(socket.SOL_SOCKET,
                                         socket.SO_ERROR)
                if err:
                    finish(st, ok=False)
                    continue
                body = json.dumps({
                    "token_ids": storm_prompt(st.idx),
                    "max_new_tokens": tokens, "stream": True,
                    "id": f"storm-{st.idx}",
                    "tenant": f"t{st.idx % max(1, tenants)}",
                }).encode()
                req = (b"POST /v1/generate HTTP/1.1\r\n"
                       b"Host: storm\r\n"
                       b"Content-Type: application/json\r\n"
                       b"Content-Length: " + str(len(body)).encode()
                       + b"\r\nConnection: close\r\n\r\n" + body)
                try:
                    st.sock.sendall(req)
                except OSError:
                    finish(st, ok=False)
                    continue
                st.t_sent = time.monotonic()
                st.state = "headers"
                sel.modify(st.sock, selectors.EVENT_READ, st)
                continue
            try:
                data = st.sock.recv(65536)
            except BlockingIOError:
                continue
            except OSError:
                finish(st, ok=False)
                continue
            if not data:
                finish(st, ok=bool(st.status))
                continue
            if st.feed(data):
                finish(st, ok=True)
        t = proc_status(server_pid).get("threads", 0)
        peak_threads = max(peak_threads, t)

    # anything still live at the deadline counts as failed
    for st in list(streams):
        if st.t_done == 0.0 and st.sock.fileno() >= 0:
            finish(st, ok=False)

    ok = [st for st in done if st.status == 200]
    shed = [st for st in done if st.status in (429, 503)]
    other = [st for st in done
             if st.status not in (200, 429, 503)]
    ttfts = sorted((st.t_first - st.t_sent) * 1e3
                   for st in ok if st.t_first)

    def pct(q: float) -> float:
        if not ttfts:
            return 0.0
        return round(ttfts[min(len(ttfts) - 1,
                               int(q * (len(ttfts) - 1)))], 1)

    out = {
        "streams": n,
        "launched": launched,
        "completed_200": len(ok),
        "shed": len(shed),
        "shed_rate": round(len(shed) / max(1, launched), 4),
        "errors": failed + len(other),
        "peak_concurrent_streams": peak_live,
        "peak_server_threads": peak_threads,
        "keepalives_seen": sum(st.keepalives for st in ok),
        "ttft_p50_ms": pct(0.50),
        "ttft_p95_ms": pct(0.95),
        "ttft_p99_ms": pct(0.99),
    }
    # token-exact spot check: re-run the first K prompts unary at zero
    # concurrency; the streamed reassembly must match exactly
    checked = exact = 0
    by_idx = {st.idx: st for st in ok}
    for i in sorted(by_idx):
        if checked >= check:
            break
        st = by_idx[i]
        try:
            ref = http_post_json(base, "/v1/generate", {
                "token_ids": storm_prompt(st.idx),
                "max_new_tokens": tokens, "id": f"check-{st.idx}"})
        except OSError:
            continue
        prompt = storm_prompt(st.idx)
        ref_new = ref.get("token_ids", [])[len(prompt):]
        checked += 1
        if st.tokens == ref_new:
            exact += 1
    out["tokens_checked"] = checked
    out["tokens_exact"] = exact
    try:
        out["edge"] = http_get_json(base, "/stats").get("edge", {})
    except OSError:
        pass
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", required=True,
                    help="gateway base URL, e.g. http://127.0.0.1:8000")
    ap.add_argument("--idle", type=int, default=0,
                    help="idle keep-alive connections to hold")
    ap.add_argument("--streams", type=int, default=0,
                    help="concurrent NDJSON streams to drive")
    ap.add_argument("--tokens", type=int, default=4,
                    help="max_new_tokens per stream")
    ap.add_argument("--tenants", type=int, default=16,
                    help="synthetic tenant population size")
    ap.add_argument("--bursts", type=int, default=10,
                    help="arrival schedule: launch in this many bursts")
    ap.add_argument("--burst-gap", type=float, default=0.5,
                    help="seconds between bursts")
    ap.add_argument("--hold", type=float, default=2.0,
                    help="idle phase: seconds to hold before measuring")
    ap.add_argument("--check", type=int, default=8,
                    help="streams to spot-check token-exact vs unary")
    ap.add_argument("--server-pid", type=int, default=0,
                    help="gateway pid for /proc RSS+thread readings")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="whole-run ceiling in seconds")
    ap.add_argument("--json", default="",
                    help="write the report JSON here (stdout always)")
    args = ap.parse_args(argv)

    host, port = parse_base(args.base)
    deadline = time.monotonic() + args.timeout
    report = {"base": args.base}
    if args.idle > 0:
        report["idle"] = idle_phase(host, port, args.idle,
                                    args.server_pid, args.base,
                                    args.hold, deadline)
    if args.streams > 0:
        report["storm"] = storm_phase(
            host, port, args.base, args.streams, args.tokens,
            args.tenants, args.bursts, args.burst_gap,
            args.server_pid, deadline, args.check)
    text = json.dumps(report, indent=2)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
