from tony_tpu.profiler.profiler import (
    ServeProfiler,
    StepProfiler,
    maybe_start_server,
    trace,
    trigger_path,
    write_trigger,
)
from tony_tpu.profiler.xplane import (
    device_busy_ms,
    hbm_estimate_bytes,
    op_totals_ms,
    per_plane_op_totals_ms,
    trace_device_ms,
)

__all__ = [
    "ServeProfiler",
    "StepProfiler",
    "device_busy_ms",
    "hbm_estimate_bytes",
    "maybe_start_server",
    "op_totals_ms",
    "per_plane_op_totals_ms",
    "trace",
    "trace_device_ms",
    "trigger_path",
    "write_trigger",
]
