"""Pipeline parallelism over the ``pipe`` mesh axis.

Absent from the reference (its TaskScheduler DAG sequences *jobs*, not
micro-batches — SURVEY.md section 2.4). Two schedules:

- GPipe (default): each pipe-axis device holds one stage's parameters
  (stacked along a leading "layers" dim sharded on ``pipe``); activations
  flow stage-to-stage via ``lax.ppermute`` inside a ``lax.scan`` bubble
  schedule. Bubble: (n_stages - 1) ticks of one stage's work per tick.
- Interleaved/circular (``circular_repeats=R > 1``, the Megatron-style
  schedule): n_stages * R virtual stages round-robin over the same ring
  (device d holds virtual stages {r*n + d}), microbatches injected in
  groups of n. Same per-device parameter count as stacking R layers into
  one GPipe stage, but the bubble stays (n - 1) ticks of ONE virtual
  stage's work — R times smaller.

Both are differentiable and jit-compatible (static schedule lengths).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from tony_tpu.utils.compat import shard_map

from tony_tpu.parallel.mesh import PIPE


def _pipeline_local(stage_params, x_micro, *, stage_fn, axis_name):
    """Body under shard_map.

    stage_params: this stage's param tree (leading stacked dim stripped
      to size 1 by sharding; squeezed before use).
    x_micro: [n_micro, mb, ...] full microbatched input (replicated).
    Returns [n_micro, mb, ...] outputs (valid on every device after psum).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], stage_params)  # strip stacked dim
    n_micro = x_micro.shape[0]
    total = n_micro + n_stages - 1
    out_buf = jnp.zeros_like(x_micro)
    carry_act = jnp.zeros_like(x_micro[0])

    def step(state, t):
        carry_act, out_buf = state
        # stage 0 ingests microbatch t (clamped; masked later)
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(stage == 0, x_micro[mb_idx], carry_act)
        y = stage_fn(params, inp)
        # last stage writes finished microbatch t-(n_stages-1)
        out_idx = t - (n_stages - 1)
        valid_out = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        out_buf = lax.cond(
            valid_out,
            lambda b: lax.dynamic_update_index_in_dim(b, y, jnp.maximum(out_idx, 0), 0),
            lambda b: b,
            out_buf,
        )
        # shift activations to the next stage
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        carry_act = lax.ppermute(y, axis_name, perm)
        return (carry_act, out_buf), None

    (carry_act, out_buf), _ = lax.scan(step, (carry_act, out_buf),
                                       jnp.arange(total))
    # outputs only live on the last stage; broadcast over the ring
    mask = (stage == n_stages - 1).astype(out_buf.dtype)
    return lax.psum(out_buf * mask, axis_name)


def _circular_local(stage_params, x_micro, *, stage_fn, axis_name,
                    n_stages: int, repeats: int, n_micro: int):
    """Interleaved schedule body under shard_map.

    stage_params: this device's [R, ...] virtual-stage params (device-major
      interleaving done by the caller: local rep r = virtual stage r*n + d).
    x_micro: [n_micro, mb, ...] microbatched input (replicated).

    Schedule: microbatch m enters virtual stage v at tick
      t(m, v) = (m // n) * n * R + (m % n) + v
    (conflict-free: each device runs at most one stage_fn per tick), so a
    microbatch advances one virtual stage — one ring hop — every tick, and
    injections pause between groups while earlier microbatches loop around
    the ring. Total ticks: t(n_micro-1, V-1) + 1.
    """
    d = lax.axis_index(axis_name)
    V = n_stages * repeats
    total = ((n_micro - 1) // n_stages) * n_stages * repeats \
        + ((n_micro - 1) % n_stages) + V
    out_buf = jnp.zeros_like(x_micro)
    # carry slot per device: activation + its virtual stage v + microbatch m
    act0 = jnp.zeros_like(x_micro[0])
    state0 = (act0, jnp.int32(-1), jnp.int32(0), out_buf)

    def step(state, t):
        act, v, m, out_buf = state
        # device 0 injection: tick t carries microbatch m_cand iff the
        # in-group offset (t mod n*R) is < n
        tmod = t % (n_stages * repeats)
        m_cand = (t // (n_stages * repeats)) * n_stages + tmod
        inject = (d == 0) & (tmod < n_stages) & (m_cand < n_micro)
        act = jnp.where(inject, x_micro[jnp.clip(m_cand, 0, n_micro - 1)],
                        act)
        v = jnp.where(inject, 0, v)
        m = jnp.where(inject, m_cand, m)

        active = (v >= 0) & (v < V)
        rep = jnp.clip(v // n_stages, 0, repeats - 1)
        params_r = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, rep, 0, keepdims=False),
            stage_params)

        def run(operand):
            p, a = operand
            return stage_fn(p, a)

        y = lax.cond(active, run, lambda operand: operand[1],
                     (params_r, act))
        # last virtual stage (necessarily device n-1) emits the microbatch
        done = active & (v == V - 1)
        out_buf = lax.cond(
            done,
            lambda b: lax.dynamic_update_index_in_dim(
                b, y, jnp.clip(m, 0, n_micro - 1), 0),
            lambda b: b,
            out_buf,
        )
        v_next = jnp.where(active & ~done, v + 1, -1)
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        act = lax.ppermute(y, axis_name, perm)
        v_next = lax.ppermute(v_next, axis_name, perm)
        m = lax.ppermute(m, axis_name, perm)
        return (act, v_next, m, out_buf), None

    (_, _, _, out_buf), _ = lax.scan(step, state0, jnp.arange(total))
    # each finished microbatch was written on device n-1 only
    mask = (d == n_stages - 1).astype(out_buf.dtype)
    return lax.psum(out_buf * mask, axis_name)


def interleave_stage_params(stacked_params, n_stages: int, repeats: int):
    """Pipeline-order [V, ...] stack -> device-major order for the
    interleaved schedule (device d's contiguous rows become its virtual
    stages [r*n + d]). Do this ONCE at setup and pass
    ``interleaved=True``: the permutation is a cross-device reshuffle of
    every parameter when the stack is pipe-sharded, not something to pay
    per training step."""
    perm = jnp.asarray([r * n_stages + d for d in range(n_stages)
                        for r in range(repeats)])
    return jax.tree.map(lambda p: p[perm], stacked_params)


def pipeline_apply(stage_fn: Callable, stacked_params, x, *, mesh: Mesh,
                   n_microbatches: int, axis_name: str = PIPE,
                   remat: bool = False, circular_repeats: int = 1,
                   interleaved: bool = False, batch_axis: str | None = None,
                   param_specs=None):
    """Run ``x`` through ``n_stages`` pipeline stages.

    stage_fn(params, x_mb) -> y_mb with y_mb.shape == x_mb.shape (uniform
      inter-stage activation shape, standard for decoder stacks).
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
      along ``axis_name``).
    x: [batch, ...]; batch must divide by n_microbatches.
    remat: rematerialize each stage call in the backward pass — activation
      memory per device drops from O(schedule_len x stage_activations) to
      O(schedule_len x microbatch) at the cost of one extra forward, the
      standard trade for deep pipelines on HBM-bound TPUs.
    circular_repeats: R > 1 selects the interleaved (Megatron-style)
      schedule: stacked_params' leading dim must be n_stages * R virtual
      stages in PIPELINE ORDER (stage v runs on device v % n_stages);
      bubble shrinks from (n-1) R-deep ticks to (n-1) 1-deep ticks.
    interleaved: the circular stacked_params are ALREADY device-major
      (pre-permuted once at setup by ``interleave_stage_params``). Without
      it, pipeline_apply permutes per call — a full cross-device reshuffle
      of the parameters every step when the stack lives pipe-sharded, so
      training loops should pre-interleave.
    batch_axis: mesh axis to shard the per-microbatch batch dim over
      (data parallelism composed with the pipeline: each data shard runs
      the same schedule on its slice; grad reduction over the axis is the
      shard_map transpose of the params' replication — automatic).
    param_specs: pytree of PartitionSpecs for stacked_params composing
      OTHER mesh axes into the stage weights (tensor parallelism: e.g.
      ``P(PIPE, None, TENSOR)``; stage_fn is then responsible for the
      matching ``lax.psum`` over the tensor axis, Megatron-style). Every
      leaf spec must lead with ``axis_name``. Default: ``P(axis_name)``.
    """
    n_stages = mesh.shape[axis_name]
    if circular_repeats < 1:
        raise ValueError(f"circular_repeats must be >= 1, "
                         f"got {circular_repeats}")
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead != n_stages * circular_repeats:
        raise ValueError(
            f"{n_stages} pipe devices x circular_repeats={circular_repeats} "
            f"needs {n_stages * circular_repeats} stacked virtual stages, "
            f"got leading dim {lead}")
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} % n_microbatches {n_microbatches} != 0")
    x_micro = x.reshape(n_microbatches, batch // n_microbatches, *x.shape[1:])

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    if circular_repeats > 1:
        if not interleaved:
            stacked_params = interleave_stage_params(
                stacked_params, n_stages, circular_repeats)
        local = functools.partial(
            _circular_local, stage_fn=stage_fn, axis_name=axis_name,
            n_stages=n_stages, repeats=circular_repeats,
            n_micro=n_microbatches)
    else:
        local = functools.partial(_pipeline_local, stage_fn=stage_fn,
                                  axis_name=axis_name)

    if param_specs is None:
        params_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    else:
        for leaf in jax.tree.leaves(param_specs,
                                    is_leaf=lambda s: isinstance(s, P)):
            if not leaf or leaf[0] != axis_name:
                raise ValueError(
                    f"param_specs leaves must lead with the pipe axis "
                    f"{axis_name!r}, got {leaf}")
        params_specs = param_specs
    x_spec = P(None, batch_axis) if batch_axis else P()
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(params_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
    out = fn(stacked_params, x_micro)
    return out.reshape(batch, *x.shape[1:])


def stack_stage_params(per_stage_params: list) -> dict:
    """Stack per-stage param trees along a new leading dim for pipe sharding."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
