"""Session: the coordinator's in-memory cluster-state model.

Reference: tensorflow/TonySession.java (633 LoC) — role->task arrays,
registration set, cluster-spec construction, chief semantics, and the
per-task exit-status -> final-application-status policy
(TonySession.java:262-398). Pure logic, no I/O: fully unit-testable.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field

from tony_tpu import constants as C
from tony_tpu.config import TonyConf
from tony_tpu.session.task import Task, TaskInfo, TaskStatus

log = logging.getLogger(__name__)


class SessionStatus(enum.Enum):
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"


@dataclass
class RoleRequest:
    """Resources for one role (ref: tensorflow/JobContainerRequest.java)."""

    role: str
    instances: int
    chips: int = 0
    memory: str = "2g"
    vcores: int = 1
    node_label: str = ""
    depends_on: list[str] = field(default_factory=list)
    command: str = ""

    @classmethod
    def from_conf(cls, conf: TonyConf, role: str) -> "RoleRequest":
        return cls(
            role=role,
            instances=int(conf.role_get(role, "instances")),
            chips=int(conf.role_get(role, "chips")),
            memory=str(conf.role_get(role, "memory")),
            vcores=int(conf.role_get(role, "vcores")),
            node_label=str(conf.role_get(role, "node-label"))
            or str(conf.get("tony.application.node-label", "")),
            depends_on=[
                s.strip()
                for s in str(conf.role_get(role, "depends-on")).split(",")
                if s.strip()
            ],
            command=str(conf.role_get(role, "command")),
        )


class Session:
    """Cluster state for one coordinator attempt (session epoch)."""

    def __init__(self, conf: TonyConf, session_id: int = 0):
        self.conf = conf
        self.session_id = session_id
        self.status = SessionStatus.RUNNING
        self.failure_reason: str | None = None
        # role -> list[Task | None], allocated lazily like the reference's
        # getAndInitMatchingTaskByPriority (TonySession.java:219)
        self.tasks: dict[str, list[Task | None]] = {}
        self.requests: dict[str, RoleRequest] = {}
        # expected tasks = instances of *scheduled* roles; the GANG gate
        # compares registrations against this, not the full config, so DAG
        # stages each form their own gang (ref: TonySession.numExpectedTasks
        # :69,204-210 incremented as the scheduler requests containers)
        self.num_expected = 0
        self.untracked = set(conf.get_list("tony.application.untracked.jobtypes"))
        self.sidecars = set(conf.get_list("tony.application.sidecar.jobtypes"))
        self.stop_on_failure = set(
            conf.get_list("tony.application.stop-on-failure.jobtypes")
        )
        self.fail_on_any_worker = conf.get_bool(
            "tony.application.fail-on-worker-failure-enabled"
        )
        for role in conf.roles():
            req = RoleRequest.from_conf(conf, role)
            self.requests[role] = req
            self.tasks[role] = [None] * req.instances

    # -- allocation ---------------------------------------------------------
    def init_task(self, role: str, index: int | None = None) -> Task | None:
        """Bind the next free slot of ``role`` (ref: TonySession.java:219)."""
        slots = self.tasks.get(role)
        if slots is None:
            return None
        if index is None:
            for i, t in enumerate(slots):
                if t is None:
                    index = i
                    break
            else:
                return None
        if index < 0 or index >= len(slots):
            return None
        if slots[index] is not None:
            return slots[index]
        task = Task(role=role, index=index, session_id=self.session_id)
        slots[index] = task
        return task

    def get_task(self, role: str, index: int) -> Task | None:
        slots = self.tasks.get(role)
        if slots is None or index < 0 or index >= len(slots):
            return None
        return slots[index]

    def has_slot(self, task_id: str) -> bool:
        """Whether ``task_id`` names a configured slot (allocated or not)."""
        role, _, idx = task_id.rpartition(":")
        slots = self.tasks.get(role)
        return slots is not None and idx.isdigit() and int(idx) < len(slots)

    def get_task_by_id(self, task_id: str) -> Task | None:
        role, _, idx = task_id.rpartition(":")
        if not role or not idx.isdigit():
            return None
        return self.get_task(role, int(idx))

    def all_tasks(self) -> list[Task]:
        return [t for slots in self.tasks.values() for t in slots if t is not None]

    # -- registration / spec (ref: getClusterSpec TonySession.java:237) -----
    def register(self, task_id: str, host_port: str) -> Task | None:
        task = self.get_task_by_id(task_id)
        if task is None:
            return None
        if task.completed:
            # late/duplicate registration must not erase a terminal status
            log.warning("ignoring registration for completed task %s", task_id)
            return None
        try:
            task.set_host_port(host_port)
        except ValueError:
            log.warning("rejecting malformed host:port %r from %s", host_port, task_id)
            return None
        task.registered = True
        task.status = TaskStatus.READY
        return task

    @property
    def total_expected(self) -> int:
        return sum(len(s) for s in self.tasks.values())

    @property
    def num_registered(self) -> int:
        return sum(1 for t in self.all_tasks() if t.registered)

    def add_expected(self, n: int) -> None:
        """Ref: TonySession.addNumExpectedTask :208."""
        self.num_expected += n

    def all_registered(self) -> bool:
        """All *scheduled* tasks registered (ref: MLGenericRuntime GANG gate
        compares getNumRegisteredTasks to getNumExpectedTasks :83-87)."""
        return self.num_expected > 0 and self.num_registered >= self.num_expected

    def cluster_spec(self) -> dict[str, list[str]]:
        """{role: ["host:port" per index]} — the rendezvous contract."""
        spec: dict[str, list[str]] = {}
        for role, slots in self.tasks.items():
            spec[role] = [
                t.host_port if t is not None and t.registered else "" for t in slots
            ]
        return spec

    # -- chief semantics (ref: TonySession.isChief :383) --------------------
    def is_chief(self, role: str, index: int) -> bool:
        """chief:0 if a chief role exists, else worker:0 (else master:0)."""
        if C.CHIEF_JOB_NAME in self.tasks:
            return role == C.CHIEF_JOB_NAME and index == 0
        if C.WORKER_JOB_NAME in self.tasks:
            return role == C.WORKER_JOB_NAME and index == 0
        if "master" in self.tasks:
            return role == "master" and index == 0
        # single-role jobs: index 0 of the first role is chief
        roles = list(self.tasks)
        return bool(roles) and role == roles[0] and index == 0

    def is_untracked(self, role: str) -> bool:
        return role in self.untracked or role in self.sidecars

    def is_sidecar(self, role: str) -> bool:
        return role in self.sidecars

    # -- completion policy (ref: TonySession.onTaskCompleted :262-349) ------
    def on_task_completed(self, role: str, index: int, exit_code: int) -> None:
        task = self.get_task(role, index)
        if task is None:
            log.warning("completion for unknown task %s:%s", role, index)
            return
        task.set_exit_status(exit_code)
        if exit_code == 0:
            return
        # failure policy short-circuits (ref: :276-285)
        if self.is_sidecar(role):
            log.info("sidecar %s:%d failed (exit %d); tolerated", role, index, exit_code)
            return
        if self.is_chief(role, index):
            self._fail(f"chief task {role}:{index} failed with exit code {exit_code}")
        elif role in self.stop_on_failure:
            self._fail(f"stop-on-failure role task {role}:{index} failed ({exit_code})")
        elif self.fail_on_any_worker and not self.is_untracked(role):
            self._fail(f"tracked task {role}:{index} failed ({exit_code})")
        elif self.is_untracked(role):
            # untracked non-sidecar failure fails the app fast
            # (ref: ApplicationMaster.java:1260-1264)
            self._fail(f"untracked task {role}:{index} failed ({exit_code})")

    def fail(self, reason: str) -> None:
        """External failure injection point: liveness expiry, registration
        timeout, startup failure (ref: onTaskDeemedDead / registrationTimeout
        / startupFailed in ApplicationMaster.java)."""
        self._fail(reason)

    def _fail(self, reason: str) -> None:
        if self.status == SessionStatus.RUNNING:
            self.status = SessionStatus.FAILED
            self.failure_reason = reason
            log.error("session failed: %s", reason)

    def tracked_tasks(self) -> list[Task]:
        return [t for t in self.all_tasks() if not self.is_untracked(t.role)]

    def training_finished(self) -> bool:
        """All tracked tasks reached a terminal state (ref: updateSessionStatus)."""
        tracked = [
            t
            for role, slots in self.tasks.items()
            if not self.is_untracked(role)
            for t in slots
        ]
        if not tracked:
            return False
        return all(t is not None and t.completed for t in tracked)

    def update_session_status(self) -> SessionStatus:
        """Final reducer (ref: TonySession.updateSessionStatus :295): succeed
        iff not already failed and at least one tracked task succeeded and no
        tracked task failed the policy above."""
        if self.status != SessionStatus.RUNNING:
            return self.status
        tracked = self.tracked_tasks()
        failed = [t for t in tracked if t.status == TaskStatus.FAILED]
        succeeded = [t for t in tracked if t.status == TaskStatus.FINISHED]
        if failed and not succeeded:
            self._fail(f"all tracked completions failed (e.g. {failed[0].id})")
        elif failed and self.fail_on_any_worker:
            self._fail(f"tracked task {failed[0].id} failed")
        elif succeeded:
            self.status = SessionStatus.SUCCEEDED
        else:
            self._fail("no tracked task succeeded")
        return self.status

    # -- views --------------------------------------------------------------
    def task_infos(self) -> list[TaskInfo]:
        infos = [t.to_info() for t in self.all_tasks()]
        infos.sort(key=lambda i: (i.attention, i.name, i.index))
        return infos
