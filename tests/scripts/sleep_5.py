"""Long-running payload for kill/liveness tests (ref: sleep_30.py, shortened
for a 1-cpu test box)."""
import time

time.sleep(5)
