"""``tony-tpu submit`` — ClusterSubmitter equivalent.

Reference: tony-cli ClusterSubmitter.java:49-95 + the common CLI options
(util/Utils.getCommonOptions :277, TonyClient extras :425-436): --src_dir,
--executes, --task_params, --conf_file, repeated --conf k=v, --python_venv.
A shutdown hook force-kills the running app on Ctrl-C (ref: :92-94).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from tony_tpu import constants as C
from tony_tpu.client import TonyClient
from tony_tpu.config import build_conf


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu submit",
        description="Submit a distributed training job to tony-tpu",
    )
    p.add_argument("--src_dir", help="user source directory shipped to tasks")
    p.add_argument("--executes", help="training entrypoint (script or command)")
    p.add_argument("--task_params", help="args appended to the entrypoint")
    p.add_argument("--conf_file", help="job conf (.toml or .json)")
    p.add_argument("--conf", action="append", default=[],
                   help="override, k=v (repeatable)")
    p.add_argument("--python_venv", help="venv dir or zip shipped to tasks")
    p.add_argument("--shell_env", help="comma K=V pairs exported to tasks")
    p.add_argument("--framework",
                   help="runtime: jax|tensorflow|pytorch|mxnet|standalone|ray")
    p.add_argument("--app_name", help="display name")
    p.add_argument("--instances", type=int,
                   help="shortcut for --conf tony.worker.instances=N")
    return p


def conf_from_args(args: argparse.Namespace):
    conf = build_conf(args.conf_file, args.conf)
    if args.src_dir:
        conf.set("tony.application.src-dir", args.src_dir)
    if args.executes:
        conf.set("tony.application.executes", args.executes)
    if args.task_params:
        conf.set("tony.application.task-params", args.task_params)
    if args.python_venv:
        conf.set("tony.application.python-venv", args.python_venv)
    if args.shell_env:
        conf.set("tony.application.shell-env", args.shell_env)
    if args.framework:
        conf.set("tony.application.framework", args.framework)
    if args.app_name:
        conf.set("tony.application.name", args.app_name)
    if args.instances is not None:
        conf.set("tony.worker.instances", args.instances)
    return conf


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    client = TonyClient(conf_from_args(args))

    def on_interrupt(signum, frame):
        client.force_kill()
        sys.exit(C.EXIT_FAIL)

    signal.signal(signal.SIGINT, on_interrupt)
    signal.signal(signal.SIGTERM, on_interrupt)
    ok = client.run()
    return C.EXIT_SUCCESS if ok else C.EXIT_FAIL


if __name__ == "__main__":
    raise SystemExit(main())
