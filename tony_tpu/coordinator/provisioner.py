"""TPU slice provisioning — the resource-acquisition half of the RM role.

Reference: the TonY client ASKS a resource manager for capacity —
``TonyClient.submitApplication`` (TonyClient.java:314-349) submits the AM
container request to YARN, and every role's task becomes a container
request carrying its GPU count and node label (TaskScheduler.java:93-105,
util/Utils.java:420-430 ``setupContainerRequestForRM``); YARN then grants
containers incrementally, within a 15-minute allocation timeout
(TonyConfigurationKeys.java:261-262).

On TPU there is no incremental container negotiation: capacity arrives as
a SLICE whose hosts are created together. The Provisioner is therefore the
whole-slice analog of that RM conversation:

- ``StaticProvisioner``: hosts pre-exist (``tony.application.hosts`` /
  local devices) — no acquisition, the pre-round-2 behavior and still the
  default (``tony.provisioner.mode = none``).
- ``TpuVmProvisioner``: drives ``gcloud compute tpus tpu-vm
  create/describe/delete`` (mode ``tpu-vm``) or the queued-resources API
  (mode ``queued``) through a mockable subprocess layer; waits for READY
  within ``tony.provisioner.timeout-ms`` (the container-allocation-timeout
  analog), derives the host list from the node's ``networkEndpoints``, and
  deletes the slice when the job stops (unless ``tony.provisioner.keep``).

Sizing comes from the session's aggregate chip demand
(sum over roles of instances x tony.<role>.chips — the GPU-count analog)
checked against the accelerator type's chip count; ``preflight_chips``
applies the same demand to LOCAL launches by comparing against discovered
chips (utils/tpu_info.py), failing at submit rather than mid-gang.
"""

from __future__ import annotations

import json
import logging
import re
import subprocess
import time

from tony_tpu.config import ConfError, TonyConf

log = logging.getLogger(__name__)

# provisioning states surfaced in the client's status line
STATE_NONE = "NONE"
STATE_CREATING = "CREATING"
STATE_WAITING = "WAITING"
STATE_READY = "READY"
STATE_DELETING = "DELETING"
STATE_FAILED = "FAILED"

_READY_NODE_STATES = frozenset({"READY"})
_DOOMED_NODE_STATES = frozenset({"PREEMPTED", "TERMINATED", "FAILED"})
_DOOMED_QR_STATES = frozenset({"FAILED", "SUSPENDED", "SUSPENDING"})


class ProvisioningError(RuntimeError):
    """Slice acquisition failed (create error, timeout, doomed state)."""


def required_chips(conf: TonyConf) -> int:
    """Aggregate chip demand: sum over roles of instances x chips
    (ref: per-container GPU counts, util/Utils.java:420-430)."""
    total = 0
    for role in conf.roles():
        inst = _conf_int(conf, f"tony.{role}.instances", 0)
        chips = _conf_int(conf, f"tony.{role}.chips", 0)
        if inst > 0 and chips > 0:
            total += inst * chips
    return total


def chips_in_accelerator_type(accel: str) -> int:
    """Chip count encoded in an accelerator type string.

    TPU naming: ``v5p-32`` counts TensorCores for v2-v5p (2 cores/chip:
    v5p-32 = 16 chips) and chips for v5e/v6e+ (``v5litepod-16``/``v6e-16``
    = 16 chips). Unknown shapes return 0 (caller skips the check)."""
    m = re.fullmatch(r"(v\d+[a-z]*(?:pod)?)-(\d+)", accel.strip())
    if not m:
        return 0
    gen, n = m.group(1), int(m.group(2))
    cores_per_chip = 1 if gen in ("v5litepod", "v5e", "v6e", "v7e") else 2
    return max(n // cores_per_chip, 1)


class Provisioner:
    """Base: acquire capacity before the gang, release it after."""

    state = STATE_NONE

    def provision(self) -> list[str]:
        """Acquire (or adopt) the slice; returns its host list. Raises
        ProvisioningError on failure/timeout."""
        raise NotImplementedError

    def deprovision(self) -> None:
        raise NotImplementedError


class StaticProvisioner(Provisioner):
    """Hosts pre-exist; provisioning is a no-op (the default)."""

    def __init__(self, hosts: list[str] | None = None):
        self.hosts = hosts or []
        self.state = STATE_READY

    def provision(self) -> list[str]:
        return self.hosts

    def deprovision(self) -> None:
        pass


class GcloudRunner:
    """One exec point for gcloud so tests swap in a fake binary
    (ref pattern: GpuDiscoverer's configurable nvidia-smi path)."""

    def __init__(self, gcloud_bin: str, project: str, zone: str,
                 timeout_s: float = 120.0):
        self.gcloud_bin = gcloud_bin
        self.project = project
        self.zone = zone
        self.timeout_s = timeout_s

    def run(self, *args: str, parse_json: bool = False):
        argv = [self.gcloud_bin, *args]
        if self.zone:
            argv += ["--zone", self.zone]
        if self.project:
            argv += ["--project", self.project]
        if parse_json:
            argv += ["--format", "json"]
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=self.timeout_s)
        except (OSError, subprocess.SubprocessError) as e:
            # missing/typo'd binary or a hung gcloud must FAIL the job,
            # not crash the coordinator past _stop()
            raise ProvisioningError(f"gcloud invocation failed: {e}") from e
        if proc.returncode != 0:
            raise ProvisioningError(
                f"{' '.join(argv[:5])}... exited {proc.returncode}: "
                f"{(proc.stderr or proc.stdout).strip()[-500:]}")
        if parse_json:
            try:
                return json.loads(proc.stdout or "{}")
            except json.JSONDecodeError as e:
                raise ProvisioningError(
                    f"unparseable gcloud JSON from {argv[1:4]}: {e}") from e
        return proc.stdout


class TpuVmProvisioner(Provisioner):
    """Create/await/teardown a TPU-VM slice via gcloud.

    ``queued=True`` goes through queued-resources (the capacity queue —
    the YARN queue analog of ``tony.yarn.queue``); otherwise a direct
    ``tpu-vm create``. Either way the node must reach READY within
    ``timeout_s`` and its networkEndpoints become the launcher's hosts.
    """

    def __init__(self, name: str, accelerator_type: str,
                 runtime_version: str, runner: GcloudRunner, *,
                 queued: bool = False, spot: bool = False,
                 reuse: bool = True, keep: bool = False,
                 timeout_s: float = 900.0, poll_interval_s: float = 10.0,
                 network: str = "", labels: str = "", node_count: int = 1):
        if not name:
            raise ConfError("provisioner needs tony.provisioner.name")
        if not accelerator_type:
            raise ConfError(
                "tony.provisioner.accelerator-type (or tony.tpu.topology) "
                "is required for provisioner mode tpu-vm/queued")
        if node_count > 1 and not queued:
            # only the queued-resources API creates multiple nodes under
            # one resource (the multislice shape, VERDICT r2 #4)
            raise ConfError(
                f"tony.tpu.num-slices={node_count} requires "
                "tony.provisioner.mode=queued (multi-node queued-resources)")
        self.name = name
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.runner = runner
        self.queued = queued
        self.spot = spot
        self.reuse = reuse
        self.keep = keep
        self.timeout_s = timeout_s
        self.poll_interval_s = poll_interval_s
        self.network = network
        self.labels = labels
        self.node_count = max(1, node_count)
        self.state = STATE_NONE
        self._created = False  # only delete what we created (unless adopt)

    def node_names(self) -> list[str]:
        """Single-node: the resource name itself. Multi-node queued
        resources: gcloud derives ``<prefix>-0..N-1`` from --node-prefix."""
        if self.node_count <= 1:
            return [self.name]
        return [f"{self.name}-{i}" for i in range(self.node_count)]

    # ------------------------------------------------------------- describe
    def _describe_node(self, node_name: str | None = None) -> dict | None:
        try:
            return self.runner.run("compute", "tpus", "tpu-vm", "describe",
                                   node_name or self.name, parse_json=True)
        except ProvisioningError:
            return None

    def _describe_queued(self) -> dict | None:
        try:
            return self.runner.run("compute", "tpus", "queued-resources",
                                   "describe", self.name, parse_json=True)
        except ProvisioningError:
            return None

    @staticmethod
    def hosts_from_node(node: dict) -> list[str]:
        hosts = []
        for ep in node.get("networkEndpoints") or []:
            addr = ep.get("ipAddress") or \
                (ep.get("accessConfig") or {}).get("externalIp", "")
            if addr:
                hosts.append(addr)
        return hosts

    # --------------------------------------------------------------- create
    def _create(self) -> None:
        args = ["--accelerator-type", self.accelerator_type,
                "--version" if not self.queued else "--runtime-version",
                self.runtime_version, "--quiet"]
        if self.spot:
            args.append("--spot")
        if self.network:
            args += ["--network", self.network]
        if self.labels:
            args += ["--labels", self.labels]
        if self.queued and self.node_count > 1:
            # one queued resource, N nodes = N slices (DCN-connected);
            # gcloud names them <prefix>-0..N-1
            self.runner.run("compute", "tpus", "queued-resources", "create",
                            self.name, "--node-count", str(self.node_count),
                            "--node-prefix", self.name, *args)
        elif self.queued:
            self.runner.run("compute", "tpus", "queued-resources", "create",
                            self.name, "--node-id", self.name, *args)
        else:
            # --async: gcloud's synchronous create can outlive any sane RPC
            # timeout; we poll describe ourselves either way
            self.runner.run("compute", "tpus", "tpu-vm", "create", self.name,
                            "--async", *args)
        self._created = True

    def provision(self) -> list[str]:
        existing = self._describe_node(self.node_names()[0])
        if existing is not None:
            state = str(existing.get("state", ""))
            if not self.reuse:
                raise ProvisioningError(
                    f"TPU {self.name} already exists (state {state}) and "
                    "tony.provisioner.reuse is off")
            log.info("adopting existing TPU %s (state %s)", self.name, state)
        else:
            self.state = STATE_CREATING
            log.info("creating TPU slice %s (%s, %s%s)", self.name,
                     self.accelerator_type, self.runtime_version,
                     ", queued" if self.queued else "")
            self._create()
        self.state = STATE_WAITING
        hosts = self._await_ready()
        self.state = STATE_READY
        log.info("TPU slice %s READY with %d host(s): %s", self.name,
                 len(hosts), ",".join(hosts))
        return hosts

    def _await_ready(self) -> list[str]:
        """Poll until the node is READY + has endpoints (ref: the AM's
        container-allocation wait with its 15-min timeout)."""
        deadline = time.monotonic() + self.timeout_s
        last = "(no describe yet)"
        while time.monotonic() < deadline:
            if self.queued:
                qr = self._describe_queued()
                if qr is not None:
                    qstate = str((qr.get("state") or {}).get("state", ""))
                    last = f"queued-resource {qstate}"
                    if qstate in _DOOMED_QR_STATES:
                        raise ProvisioningError(
                            f"queued resource {self.name} is {qstate}: "
                            f"{json.dumps(qr.get('state', {}))[:300]}")
            # every node (1 for single-slice, N for multislice) must be
            # READY with endpoints; hosts concatenate in node order so
            # contiguous flat-index ranges land on one slice — the same
            # grouping multislice_env assumes
            all_hosts: list[str] = []
            ready = 0
            for node_name in self.node_names():
                node = self._describe_node(node_name)
                if node is None:
                    last = f"node {node_name} not yet describable"
                    break
                nstate = str(node.get("state", ""))
                last = f"node {node_name} {nstate}"
                if nstate in _DOOMED_NODE_STATES:
                    raise ProvisioningError(
                        f"TPU {node_name} entered {nstate} while waiting")
                if nstate not in _READY_NODE_STATES:
                    break
                hosts = self.hosts_from_node(node)
                if not hosts:
                    last = f"node {node_name} READY but no networkEndpoints"
                    break
                ready += 1
                all_hosts.extend(hosts)
            if ready == len(self.node_names()):
                return all_hosts
            time.sleep(self.poll_interval_s)
        raise ProvisioningError(
            f"TPU {self.name} not READY within {self.timeout_s:.0f}s "
            f"(last: {last})")

    # ------------------------------------------------------------- teardown
    def deprovision(self) -> None:
        if self.keep:
            log.info("tony.provisioner.keep: leaving TPU %s up", self.name)
            return
        if not self._created and self.state != STATE_READY:
            return  # nothing acquired
        self.state = STATE_DELETING
        try:
            if self.queued:
                self.runner.run("compute", "tpus", "queued-resources",
                                "delete", self.name, "--force", "--quiet")
            else:
                self.runner.run("compute", "tpus", "tpu-vm", "delete",
                                self.name, "--quiet")
            log.info("deleted TPU slice %s", self.name)
        except (ProvisioningError, subprocess.SubprocessError, OSError):
            # teardown is best-effort: the job outcome must not flip over
            # a delete hiccup, but operators need the trail
            log.exception("failed to delete TPU slice %s", self.name)
        self.state = STATE_NONE


def _conf_int(conf: TonyConf, key: str, default: int) -> int:
    """``get_int`` with a TYPED failure: a garbage value in a numeric
    provisioner key must fail the submission with a ConfError naming
    the key, not escape as a bare ValueError stack trace — the
    autoscaler's ProvisionerBackend (gateway/autoscale.py) turns any
    provisioning exception into a logged decision, and 'invalid
    literal for int()' tells an operator nothing."""
    try:
        return conf.get_int(key, default)
    except (TypeError, ValueError) as e:
        raise ConfError(f"{key} must be an integer "
                        f"(got {conf.get(key)!r}): {e}") from None


def provisioner_from_conf(conf: TonyConf, app_id: str) -> Provisioner:
    """Build the configured provisioner (cheap: no subprocess here).
    Raises ``ConfError`` (typed, operator-readable) for unknown modes,
    undersized slices, and malformed numeric values — never a bare
    ``ValueError`` stack trace."""
    mode = str(conf.get("tony.provisioner.mode", "none"))
    if mode == "none":
        hosts = [h.strip() for h in
                 str(conf.get("tony.application.hosts", "")).split(",")
                 if h.strip()]
        return StaticProvisioner(hosts)
    if mode not in ("tpu-vm", "queued"):
        raise ConfError(f"unknown tony.provisioner.mode: {mode}")
    accel = str(conf.get("tony.provisioner.accelerator-type", "")) or \
        str(conf.get("tony.tpu.topology", ""))
    need = required_chips(conf)
    n_nodes = max(1, _conf_int(conf, "tony.tpu.num-slices", 1))
    have = chips_in_accelerator_type(accel) * n_nodes
    if need > 0 and have > 0 and have < need:
        raise ConfError(
            f"accelerator type {accel} x {n_nodes} node(s) has {have} chips "
            f"but roles request {need} (sum of instances x "
            f"tony.<role>.chips)")
    runner = GcloudRunner(
        str(conf.get("tony.provisioner.gcloud-bin", "gcloud")),
        str(conf.get("tony.provisioner.project", "")),
        str(conf.get("tony.provisioner.zone", "")))
    return TpuVmProvisioner(
        str(conf.get("tony.provisioner.name", "")) or
        f"tony-{app_id.replace('_', '-')}",
        accel,
        str(conf.get("tony.provisioner.runtime-version",
                     "tpu-ubuntu2204-base")),
        runner,
        queued=(mode == "queued"),
        spot=conf.get_bool("tony.provisioner.spot"),
        reuse=conf.get_bool("tony.provisioner.reuse", True),
        keep=conf.get_bool("tony.provisioner.keep"),
        timeout_s=_conf_int(conf, "tony.provisioner.timeout-ms",
                            900_000) / 1000,
        poll_interval_s=_conf_int(
            conf, "tony.provisioner.poll-interval-ms", 10_000) / 1000,
        network=str(conf.get("tony.provisioner.network", "")),
        labels=str(conf.get("tony.provisioner.labels", "")),
        node_count=_conf_int(conf, "tony.tpu.num-slices", 1))


def preflight_chips(conf: TonyConf) -> str | None:
    """LOCAL-launch preflight: discovered chips must cover the aggregate
    demand. Returns an error string (caller fails the submission) or None.

    Only enforced when roles actually request chips AND discovery finds
    any (a CPU CI host discovers none — chip requests there are advisory,
    like the reference on clusters without the GPU resource plugin)."""
    need = required_chips(conf)
    if need <= 0:
        return None
    from tony_tpu.utils.tpu_info import TpuDiscoverer

    info = TpuDiscoverer(
        str(conf.get("tony.tpu.info-exec-path", ""))).get_device_information()
    have = len(info.chips)
    if have and have < need:
        return (f"roles request {need} chips but this host has {have} "
                f"(source: {info.source}); lower tony.<role>.chips/"
                "instances or provision a slice (tony.provisioner.mode)")
    return None
