"""Raw text -> packed token corpus (the PackedTokenSource input format).

No reference analog (TonY ships no data tooling; its examples read
pre-prepared MNIST). This closes the last gap between "I have text files"
and the packed-pretraining path: stream documents through any tokenizer,
append an EOS separator per document, and write one flat binary of token
ids that ``PackedTokenSource`` memmaps.

Tokenizer-agnostic by design: ``encode`` is any ``str -> sequence[int]``
callable, so a HF fast tokenizer (``tok.encode``), sentencepiece, or the
in-tree ``ByteTokenizer`` all plug in without this module importing any of
them.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Sequence

import numpy as np


class ByteTokenizer:
    """Zero-dependency fallback tokenizer: UTF-8 bytes as token ids.

    vocab: 256 byte values + 1 EOS (id 256) -> vocab_size 257. Lossless
    round-trip for any text; the standard baseline when no trained
    tokenizer is at hand (and what makes examples/tests runnable offline).
    """

    vocab_size = 257
    eos_id = 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode(
            "utf-8", errors="replace")


class _BinWriter:
    """Buffered token-id sink with dtype range checking per flush."""

    def __init__(self, f, dtype, buffer_tokens: int):
        self.f = f
        self.dtype = np.dtype(dtype)
        self.limit = np.iinfo(self.dtype).max
        self.buffer_tokens = buffer_tokens
        self.buf: list[int] = []
        self.total = 0

    def append(self, ids: Iterable[int]) -> None:
        self.buf.extend(int(i) for i in ids)
        if len(self.buf) >= self.buffer_tokens:
            self.flush()

    def flush(self) -> None:
        if not self.buf:
            return
        arr = np.asarray(self.buf, dtype=np.int64)
        if arr.min() < 0 or arr.max() > self.limit:
            raise ValueError(
                f"token id out of range for {self.dtype} "
                f"(min {arr.min()}, max {arr.max()}, limit {self.limit})")
        arr.astype(self.dtype).tofile(self.f)
        self.total += len(self.buf)
        self.buf.clear()


def encode_corpus_to_bin(
    texts: Iterable[str],
    out_path: str,
    encode: Callable[[str], Sequence[int]],
    *,
    eos_id: int | None = None,
    dtype=np.uint16,
    buffer_tokens: int = 1 << 20,
) -> int:
    """Stream ``texts`` through ``encode`` into a flat token .bin.

    Each document's ids are appended, followed by ``eos_id`` (when given)
    as the document separator — the packed format PackedTokenSource
    expects. Writing is buffered (``buffer_tokens`` ids per flush) so a
    corpus never has to fit in memory. Returns the total token count.

    dtype must hold every id (uint16 for vocab < 65536; uint32 above) —
    overflow is checked per flush, not silently wrapped.
    """
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "wb") as f:
        w = _BinWriter(f, dtype, buffer_tokens)
        for text in texts:
            w.append(encode(text))
            if eos_id is not None:
                w.append([eos_id])
        w.flush()
    return w.total


def encode_files_to_bin(paths: Sequence[str], out_path: str,
                        encode: Callable[[str], Sequence[int]], *,
                        eos_id: int | None = None, dtype=np.uint16,
                        block_bytes: int | None = None) -> int:
    """Stream files into one packed .bin, EOS separator once per FILE.

    By default each file is encoded in ONE ``encode`` call — lossless for
    every tokenizer (BPE merges and per-call special tokens see the whole
    document), at the cost of holding one file's text + ids in memory.

    ``block_bytes`` opts into streaming for files too large for that:
    ~block_bytes chunks split at LINE boundaries. Only use it with a
    split-invariant ``encode`` (bytes/chars, or a subword tokenizer called
    with special tokens off AND whose merges never span a newline) —
    otherwise every block boundary perturbs the token stream.
    """
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "wb") as f:
        w = _BinWriter(f, dtype, 1 << 20)
        for path in paths:
            with open(path, encoding="utf-8") as src:
                if block_bytes is None:
                    w.append(encode(src.read()))
                else:
                    block: list[str] = []
                    size = 0
                    for line in src:
                        block.append(line)
                        size += len(line)
                        if size >= block_bytes:
                            w.append(encode("".join(block)))
                            block, size = [], 0
                    if block:
                        w.append(encode("".join(block)))
            if eos_id is not None:
                w.append([eos_id])
        w.flush()
    return w.total
