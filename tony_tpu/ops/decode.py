"""Flash-decode: single-query KV-cache attention as a pallas TPU kernel.

The decode hot loop is HBM-bound (docs/PERF.md "Decode roofline"): every
generated token re-reads the whole KV cache once. This kernel is the
cache-side counterpart of the int8 weight path (ops/quant.py):

- one grid step per (batch x kv_head, kv block): K/V tiles are DMA'd
  HBM->VMEM once and consumed by an online-softmax accumulation held in
  VMEM scratch — no [S] score tensor round-trips to HBM, and the
  softmax/weighted-sum fuse into the tile pass (XLA's decode attention
  materializes scores + probabilities in HBM at small batch);
- the cache may be stored **int8 with per-(position, head) scales**
  (quantize-on-write in models/transformer._decode_attention): tiles
  cross HBM as int8 — HALF the cache traffic of bf16, the dominant
  decode bytes at long context — and dequantize in VMEM right before
  the MXU, exactly the ops/quant.py recipe for weights;
- GQA: the q-head group [G, D] of each kv head rides one kernel
  instance, so cache tiles are read ONCE per kv head (never repeated to
  n_heads), preserving the GQA bandwidth saving end-to-end;
- cache positions at/after ``length`` (and behind the sliding window)
  are masked; blocks entirely outside [start, length) skip their FLOPs
  via ``@pl.when`` predication.

No reference analog (TonY ships no kernels; SURVEY.md section 2.5 —
the data plane is delegated). Falls back to the pallas interpreter
off-TPU so CPU tests pin exactness against the jax reference path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tony_tpu.ops.platform import interpret_mode

NEG_INF = -1e30


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, L, H, D] float -> (int8 values, fp32 scales [B, L, H]).
    Symmetric absmax per (batch, position, head) — the KV analog of
    ops/quant.quantize_q8's per-output-channel recipe; dequant is
    ``q * scale[..., None]``."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale[..., None]


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, *rest,
                   block_k: int, scale: float, window: int,
                   quant: bool, kvh: int):
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # lengths live whole in SMEM (scalars don't tile: a (1, 1) VMEM
    # block of an [B, 1] array fails Mosaic's sublane rule on-chip);
    # indexed dynamically per grid row instead of via BlockSpec
    length = len_ref[pl.program_id(0) // kvh, 0]
    start = jnp.maximum(length - window, 0) if window > 0 else 0

    def _body():
        q = q_ref[0]  # [Gp, D]
        k = k_ref[0]  # [block_k, D] (int8 when quant)
        v = v_ref[0]
        if quant:
            kf = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
            vf = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
        else:
            kf, vf = k, v
        s = jax.lax.dot_general(
            q, kf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Gp, block_k]
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        visible = pos < length
        if window > 0:
            visible = visible & (pos >= start)
        s = jnp.where(visible, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(vf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # skip FLOPs for blocks wholly past `length` or behind the window
    # (their DMA is already issued by BlockSpec — static grid — so this
    # saves compute, not traffic; the traffic win comes from int8 tiles)
    in_range = ki * block_k < length
    if window > 0:
        in_range = in_range & (ki * block_k + block_k > start)

    @pl.when(in_range)
    def _run():
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_kernel_mha(q_ref, k_ref, v_ref, len_ref, *rest,
                       block_k: int, scale: float, window: int,
                       quant: bool, kvh: int, bh_blk: int):
    """Batched-rows variant for MHA decode (group == 1).

    The GQA kernel pads each kv head's single query row to 8 sublanes
    and runs one grid instance per (batch x head) — at short cache that
    is b*h tiny instances whose fixed cost (DMA setup, grid step) beats
    the useful work, exactly where the XLA einsum used to win
    (VERDICT r4 #1/#4: 0.89x at cache 512). Here ``bh_blk`` (batch x
    head) rows ride ONE instance: 8 real query rows fill the sublanes
    that padding wasted, DMA tiles are 8x larger, and the instance count
    drops 8x. The score/value contractions become VPU
    multiply-reductions (each row has its own K/V — there is no shared
    matmul), which decode can afford: it is bandwidth-bound, and the VPU
    work is microseconds against the cache-read time.
    """
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    ki = pl.program_id(1)
    nk = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # per-row cache lengths: rows of this block may span batches; SMEM
    # scalar reads (unrolled: bh_blk is static) assemble the column
    row0 = pl.program_id(0) * bh_blk
    lens = jnp.stack([len_ref[(row0 + i) // kvh, 0]
                      for i in range(bh_blk)]).reshape(bh_blk, 1)
    maxlen = jnp.max(lens)

    def _body():
        q = q_ref[:].astype(jnp.float32)          # [bh, D]
        k = k_ref[:]                              # [bh, block_k, D]
        v = v_ref[:]
        if quant:
            kf = k.astype(jnp.float32) * ks_ref[:, 0, :][:, :, None]
            vf = v.astype(jnp.float32) * vs_ref[:, 0, :][:, :, None]
        else:
            kf = k.astype(jnp.float32)
            vf = v.astype(jnp.float32)
        # each row contracts against its own K tile: VPU mul-reduce over
        # D (lane dim), not a matmul
        s = jnp.sum(q[:, None, :] * kf, axis=2) * scale  # [bh, block_k]
        pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        visible = pos < lens
        if window > 0:
            visible = visible & (pos >= jnp.maximum(lens - window, 0))
        s = jnp.where(visible, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_new
        acc_scr[:] = acc_scr[:] * corr + jnp.sum(
            p[:, :, None] * vf, axis=1)  # [bh, D]

    in_range = ki * block_k < maxlen
    if window > 0:
        # conservative: any row's window may reach into this block
        in_range = in_range & (ki * block_k + block_k
                               > jnp.min(jnp.maximum(lens - window, 0)))

    @pl.when(in_range)
    def _run():
        _body()

    @pl.when(ki == nk - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        # rows with NO visible position ever (length 0, or a window
        # past every block — e.g. an empty continuous-batching slot
        # sharing this 8-row block with live rows) never raise m above
        # NEG_INF: their p = exp(s - m) degenerated to 1 and acc holds
        # a sum of V tiles — mask them to the 0 the GQA kernel (whose
        # per-row gate never runs such rows) and the reference emit.
        # Rows whose first visible block comes late self-heal: the
        # correction factor exp(NEG_INF - m_new) wipes the pollution.
        valid = m_scr[:] > NEG_INF * 0.5
        o_ref[:] = jnp.where(valid, acc_scr[:] / l_safe,
                             0.0).astype(o_ref.dtype)


def _pick_block_k(limit: int, s: int) -> int:
    """Largest multiple-of-8 divisor of ``s`` within ``limit``; a whole-
    length single block is legal too (mosaic pads a full-dim block). Any
    other non-8-multiple would be a sublane-misaligned TPU tile that only
    the CPU interpreter accepts, so it is an error, not a fallback."""
    if s <= limit:
        return s
    b = limit
    for cand in range(b - b % 8, 7, -8):
        if s % cand == 0:
            return cand
    raise ValueError(
        f"no usable flash-decode block for cache length {s} (need a "
        f"divisor <= {limit} that is a multiple of 8, or the whole "
        f"length; pad max_seq_len to a multiple of 8)")


@functools.partial(jax.jit, static_argnames=("window", "block_k",
                                             "interpret"))
def flash_decode(q, k, v, length, *, window: int = 0, block_k: int = 512,
                 k_scale=None, v_scale=None, interpret: bool | None = None):
    """Single-step decode attention over a static KV cache.

    q: [B, H, D] — the one new query per sequence (head-grouped GQA ok).
    k/v: [B, S, KVH, D] cache buffers — float, or int8 with
      ``k_scale``/``v_scale`` [B, S, KVH] fp32 per-(position, head)
      scales (quantize-on-write; see models/quantize.quantize_kv).
    length: [B] int32 — valid cache length per sequence (query sits at
      position ``length - 1``); positions >= length are masked. Lengths
      are PER-SLOT state: a serving batch may mix any lengths, and a
      length of 0 marks an EMPTY continuous-batching slot — its output
      row is exact zeros (both kernels; see _finalize), never NaN, so
      empty slots ride a live batch for free.
    window: sliding window (key visible iff 0 <= q_pos - k_pos < window).
    Returns [B, H, D] in q's dtype.
    """
    b, h, d = q.shape
    bs, s, kvh, dk = k.shape
    if bs != b or dk != d or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q{q.shape} k{k.shape} v{v.shape}")
    if h % kvh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kvh}")
    quant = k.dtype == jnp.int8
    if quant != (v.dtype == jnp.int8):
        raise ValueError("k and v must both be int8 or both float")
    if quant and (k_scale is None or v_scale is None):
        raise ValueError("int8 cache needs k_scale and v_scale")
    group = h // kvh
    gp = -(-group // 8) * 8  # pad query rows to a legal sublane multiple
    scale = d ** -0.5
    if interpret is None:
        interpret = interpret_mode()
    bk = _pick_block_k(block_k, s)

    from jax.experimental.pallas import tpu as pltpu

    # [B, S, KVH, D] -> [B*KVH, S, D]
    kr = k.transpose(0, 2, 1, 3).reshape(b * kvh, s, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * kvh, s, d)
    len2 = jnp.broadcast_to(jnp.asarray(length, jnp.int32).reshape(-1, 1),
                            (b, 1))  # scalar length broadcasts per batch
    if quant:
        # [B, S, KVH] -> [B*KVH, 1, S]: lane-dim S keeps (1, bk) legal
        ksr = k_scale.transpose(0, 2, 1).reshape(b * kvh, 1, s)
        vsr = v_scale.transpose(0, 2, 1).reshape(b * kvh, 1, s)

    bh_blk = 8
    if group == 1 and (b * kvh) % bh_blk == 0:
        # MHA: 8 (batch x head) rows per instance — fills the sublanes
        # the GQA kernel padded, 8x fewer instances, 8x larger DMA tiles
        # (the short-cache regime where per-instance cost dominated)
        qr = q.reshape(b * kvh, d)
        kernel = functools.partial(
            _decode_kernel_mha, block_k=bk, scale=scale, window=window,
            quant=quant, kvh=kvh, bh_blk=bh_blk)
        in_specs = [
            pl.BlockSpec((bh_blk, d), lambda bh, ki: (bh, 0)),
            pl.BlockSpec((bh_blk, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((bh_blk, bk, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ]
        operands = [qr, kr, vr, len2]
        if quant:
            in_specs += [
                pl.BlockSpec((bh_blk, 1, bk), lambda bh, ki: (bh, 0, ki)),
                pl.BlockSpec((bh_blk, 1, bk), lambda bh, ki: (bh, 0, ki)),
            ]
            operands += [ksr, vsr]
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((b * kvh, d), q.dtype),
            grid=(b * kvh // bh_blk, s // bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((bh_blk, d), lambda bh, ki: (bh, 0)),
            scratch_shapes=[_vmem((bh_blk, 1)), _vmem((bh_blk, 1)),
                            _vmem((bh_blk, d))],
            interpret=interpret,
        )(*operands)
        return out.reshape(b, h, d)

    # [B, H, D] -> [B*KVH, Gp, D] (group-major per kv head)
    qr = q.reshape(b, kvh, group, d)
    if gp != group:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    qr = qr.reshape(b * kvh, gp, d)

    kernel = functools.partial(_decode_kernel, block_k=bk, scale=scale,
                               window=window, quant=quant, kvh=kvh)
    in_specs = [
        pl.BlockSpec((1, gp, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, bk, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qr, kr, vr, len2]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bk), lambda bh, ki: (bh, 0, ki)),
            pl.BlockSpec((1, 1, bk), lambda bh, ki: (bh, 0, ki)),
        ]
        operands += [ksr, vsr]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gp, d), q.dtype),
        grid=(b * kvh, s // bk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gp, d), lambda bh, ki: (bh, 0, 0)),
        scratch_shapes=[_vmem((gp, 1)), _vmem((gp, 1)), _vmem((gp, d))],
        interpret=interpret,
    )(*operands)
    out = out.reshape(b, kvh, gp, d)[:, :, :group]
    return out.reshape(b, h, d)
