"""High-level fit() loop tests: loader integration, checkpoint resume,
eval, metric sinks."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu.data import ArraySource, DataLoader
from tony_tpu.parallel import data_parallel_mesh
from tony_tpu.parallel.sharding import batch_sharding
from tony_tpu.train import JsonlMetricsLogger, Trainer, cross_entropy_loss, fit


def _setup(seed=0):
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (x @ w_true)[:, 0] + 0.01 * rng.standard_normal(64).astype(np.float32)
    src = ArraySource({"x": x, "y": y})

    def apply_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(0.05), donate=False)
    params = {"w": jnp.zeros((4, 1), jnp.float32)}
    loader = lambda epochs: DataLoader(  # noqa: E731
        src, global_batch_size=16, seed=1, num_epochs=epochs,
        sharding=batch_sharding(mesh), process_index=0, process_count=1)
    return trainer, params, loader


def test_fit_trains_and_logs(tmp_path):
    trainer, params, loader = _setup()
    sink_path = tmp_path / "metrics.jsonl"
    result = fit(trainer, params, loader(10), log_every=5,
                 metric_sinks=[JsonlMetricsLogger(str(sink_path))])
    assert result.steps_run == 40  # 4 batches x 10 epochs
    assert result.resumed_from is None
    assert result.history, "log_every should have recorded metrics"
    assert result.history[-1]["loss"] < result.history[0]["loss"]
    lines = [json.loads(l) for l in sink_path.read_text().splitlines()]
    assert lines[0]["step"] == 5 and "loss" in lines[0]
    assert "steps_per_sec" in lines[0]


def test_fit_num_steps_cap():
    trainer, params, loader = _setup()
    result = fit(trainer, params, loader(None), num_steps=7, log_every=0)
    assert result.steps_run == 7
    assert int(result.state.step) == 7


def test_fit_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    trainer, params, loader = _setup()
    first = fit(trainer, params, loader(None), num_steps=6,
                checkpoint_dir=ckpt, checkpoint_every=4, log_every=0)
    assert first.steps_run == 6

    # second run resumes at 6 and trains 4 more
    second = fit(trainer, params, loader(None), num_steps=4,
                 checkpoint_dir=ckpt, log_every=0)
    assert second.resumed_from == 6
    assert second.steps_run == 4
    assert int(second.state.step) == 10
    # restored params actually carried over (loss keeps improving, not reset)
    w2 = np.asarray(second.state.params["w"])
    assert not np.allclose(w2, 0.0)


def test_fit_total_steps_resume_completes_budget(tmp_path):
    """total_steps is absolute: a resumed attempt trains only the remainder
    (the retry-resume contract), and the data order fast-forwards via
    DataLoader.from_step instead of replaying consumed batches."""
    ckpt = str(tmp_path / "ckpts")
    trainer, params, loader = _setup()
    first = fit(trainer, params, loader(None), total_steps=6,
                checkpoint_dir=ckpt, log_every=0)
    assert first.steps_run == 6
    second = fit(trainer, params, loader(None), total_steps=10,
                 checkpoint_dir=ckpt, log_every=0)
    assert second.resumed_from == 6
    assert second.steps_run == 4  # completes the budget, not 10 more
    third = fit(trainer, params, loader(None), total_steps=10,
                checkpoint_dir=ckpt, log_every=0)
    assert third.steps_run == 0  # budget already met


def test_fit_closes_prefetch_thread_on_early_exit():
    """Exiting at the step target on an infinite prefetching loader must
    stop the prefetch worker (no leaked thread / pinned staged batches)."""
    import threading
    import time

    trainer, params, loader = _setup()
    before = threading.active_count()
    result = fit(trainer, params, loader(None), num_steps=3, log_every=0)
    assert result.steps_run == 3
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_loader_from_step_matches_continuous_run():
    src = ArraySource({"x": np.arange(32, dtype=np.float32),
                       "y": np.arange(32, dtype=np.float32)})
    mk = lambda: DataLoader(  # noqa: E731
        src, global_batch_size=8, seed=9, num_epochs=2,
        process_index=0, process_count=1, prefetch=0)
    full = [b["x"].tolist() for b in mk()]
    tail = [b["x"].tolist() for b in mk().from_step(5)]
    assert tail == full[5:]  # epoch boundary (4/epoch) crossed correctly


def test_grad_accumulation_matches_full_batch():
    """accum_steps=4 must produce the same update as one full-batch step
    (mean-of-microbatch grads == full-batch grad for mean losses)."""
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(3)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    y = rng.standard_normal(16).astype(np.float32)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}

    def apply_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"][:, None]) ** 2)

    params = {"w": jnp.ones((4, 1), jnp.float32)}
    outs = {}
    for accum in (1, 4):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optax.sgd(0.1), donate=False,
                          accum_steps=accum)
        step_fn, placed = trainer.build_step(trainer.init_state(params))
        placed, metrics = step_fn(placed, batch)
        outs[accum] = (np.asarray(placed.params["w"]),
                       float(metrics["loss"]))
    np.testing.assert_allclose(outs[1][0], outs[4][0], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[1][1], outs[4][1], rtol=1e-5)


def test_grad_accumulation_rejects_indivisible():
    mesh = data_parallel_mesh()
    trainer = Trainer(mesh=mesh,
                      apply_fn=lambda p, b: jnp.sum(p["w"] * b["x"]),
                      optimizer=optax.sgd(0.1), donate=False, accum_steps=3)
    step_fn, placed = trainer.build_step(
        trainer.init_state({"w": jnp.ones((2,))}))
    with pytest.raises(ValueError, match="not divisible"):
        step_fn(placed, {"x": jnp.ones((8, 2))})


def test_fit_eval_loop():
    trainer, params, loader = _setup()

    def eval_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    result = fit(trainer, params, loader(4), log_every=0,
                 eval_data=list(loader(1)), eval_fn=eval_fn, eval_every=8)
    evals = [h for h in result.history if "eval/loss" in h]
    assert len(evals) == 2  # 16 steps / eval_every=8
    assert evals[-1]["eval/loss"] < evals[0]["eval/loss"]


def test_mixed_precision_bf16_compute_keeps_fp32_master():
    mesh = data_parallel_mesh()

    def apply_fn(p, batch):
        pred = jnp.tanh(batch["x"] @ p["w1"]) @ p["w2"]
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 4)).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32))[:, 0]
    batch = {
        "x": jax.device_put(jnp.asarray(x), batch_sharding(mesh)),
        "y": jax.device_put(jnp.asarray(y), batch_sharding(mesh)),
    }
    params = {"w1": jnp.ones((4, 8), jnp.float32) * 0.1,
              "w2": jnp.ones((8, 1), jnp.float32) * 0.1}
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(0.05), donate=False,
                      compute_dtype=jnp.bfloat16)
    step_fn, state = trainer.build_step(trainer.init_state(params))
    losses = []
    for _ in range(20):
        state, metrics = step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    # master params and adam moments stay fp32
    assert state.params["w1"].dtype == jnp.float32
    for leaf in jax.tree.leaves(state.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    assert losses[-1] < losses[0] * 0.5


def test_mixed_precision_with_grad_accum():
    mesh = data_parallel_mesh()

    def apply_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"])[:, 0] ** 2)

    batch = {"x": jax.device_put(jnp.ones((16, 4)), batch_sharding(mesh))}
    params = {"w": jnp.ones((4, 1), jnp.float32)}
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.sgd(0.1), donate=False,
                      accum_steps=4, compute_dtype=jnp.bfloat16)
    step_fn, state = trainer.build_step(trainer.init_state(params))
    state, metrics = step_fn(state, batch)
    assert state.params["w"].dtype == jnp.float32
    assert np.isfinite(float(metrics["loss"]))


def test_fit_ema_params():
    # default Trainer (donate=True): the EMA copy must survive donation
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = (x @ w_true)[:, 0]
    src = ArraySource({"x": x, "y": y})

    def apply_fn(p, batch):
        return jnp.mean(((batch["x"] @ p["w"])[:, 0] - batch["y"]) ** 2)

    def make(donate):
        return Trainer(mesh=mesh, apply_fn=apply_fn,
                       optimizer=optax.adam(0.05), donate=donate)

    loader = lambda: DataLoader(  # noqa: E731
        src, global_batch_size=16, seed=1, num_epochs=2,
        sharding=batch_sharding(mesh), process_index=0, process_count=1)
    params = {"w": jnp.zeros((4, 1), jnp.float32)}
    decay = 0.8
    result = fit(make(True), params, loader(), log_every=0, ema_decay=decay)
    assert result.ema_params is not None

    # pin the exact math: replay the identical deterministic run manually
    trainer2 = make(False)
    step_fn, state = trainer2.build_step(trainer2.init_state(params))
    ema = np.asarray(params["w"])
    for batch in loader():
        state, _ = step_fn(state, batch)
        ema = decay * ema + (1 - decay) * np.asarray(state.params["w"])
    np.testing.assert_allclose(np.asarray(result.ema_params["w"]), ema,
                               atol=1e-6, rtol=1e-6)
    # EMA lags strictly behind the final params on a monotone trajectory
    assert 0 < np.abs(ema).sum() < np.abs(
        np.asarray(result.state.params["w"])).sum()
    # and without ema_decay the field stays None
    assert fit(make(True), params, loader(),
               log_every=0).ema_params is None


def test_cross_entropy_mask():
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((2, 4, 8)),
                         jnp.float32)
    labels = jnp.zeros((2, 4), jnp.int32)
    full = float(cross_entropy_loss(logits, labels))
    ones = float(cross_entropy_loss(logits, labels, jnp.ones((2, 4))))
    np.testing.assert_allclose(full, ones, rtol=1e-6)
    # masking half the positions equals the mean over the kept half
    mask = jnp.asarray([[1, 1, 0, 0], [1, 0, 0, 0]], jnp.float32)
    got = float(cross_entropy_loss(logits, labels, mask))
    logp = jax.nn.log_softmax(logits, axis=-1)[..., 0]
    want = -float((logp * mask).sum() / mask.sum())
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # all-masked: defined (0), not NaN
    assert float(cross_entropy_loss(logits, labels, jnp.zeros((2, 4)))) == 0.0


def test_trainer_batch_shardings_override():
    """Per-leaf batch input shardings (sequence-parallel inputs land
    seq-sharded): step accepts mixed shardings, with and without accum."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.mesh import DATA, SEQ

    mesh = make_mesh(MeshSpec(data=2, seq=4))
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 16, 4)).astype(np.float32)
    seg = np.repeat(np.arange(2, dtype=np.int32)[None, :], 8,
                    axis=0).repeat(8, axis=1)

    def apply_fn(p, batch):
        # segment-gated mean: touches both differently-sharded inputs
        gate = (batch["segments"] == 0).astype(jnp.float32)[..., None]
        return jnp.mean((batch["x"] * gate) @ p["w"])

    shardings = {
        "x": NamedSharding(mesh, P(DATA)),
        "segments": NamedSharding(mesh, P(DATA, SEQ)),
    }
    params = {"w": jnp.ones((4, 1), jnp.float32)}
    for accum in (1, 2):
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optax.sgd(0.1), donate=False,
                          batch_shardings=shardings, accum_steps=accum)
        step, placed = trainer.build_step(trainer.init_state(params))
        batch = {"x": jax.device_put(jnp.asarray(x), shardings["x"]),
                 "segments": jax.device_put(jnp.asarray(seg),
                                            shardings["segments"])}
        _, metrics = step(placed, batch)
        assert np.isfinite(float(metrics["loss"]))
