"""Deterministic fault injection for the serving stack.

TonY's defining robustness story — heartbeat the workers, fail the
silent ones, retry their tasks elsewhere — is only real if the failure
paths actually run. This module is the switch that runs them: a
``FaultPlan`` is a list of pre-declared faults hooked into the two
places a replica does device work (``Server.step()`` and request
admission), so a test or a smoke script can say "the 3rd dispatch on
replica 0 dies" or "this request wedges for two seconds" and get the
SAME failure on every run — the gateway's supervision, failover, and
circuit-breaker paths are pinned by tests instead of being dead code
waiting for real hardware to misbehave.

Two delivery routes:

- **constructor**: ``Server(..., fault_plan=FaultPlan.fail_at(3))`` —
  what the unit tests use.
- **environment**: ``TONY_SERVE_FAULTS`` holds a JSON fault list; the
  gateway CLI arms each replica's engine with the faults addressed to
  it (``FaultPlan.from_env(replica=i)``), so a shell script can chaos-
  test a real subprocess gateway (``make chaos-smoke``) without any
  code hook.

Fault spec fields (JSON object or ``Fault`` kwargs):

  op        engine side: "fail" (raise ``InjectedFault``) or "wedge"
            (sleep — simulates a stalled, not crashed, dispatch; the
            watchdog's case). Transport side (the remote-replica
            stub's HTTP layer, gateway/remote.py): "refuse" (instant
            ``ConnectionRefusedError`` — a dead port), "blackhole"
            (the connection goes nowhere: optional ``seconds`` delay,
            then ``TimeoutError`` — a network partition), "delay"
            (sleep ``seconds``, then proceed — a slow link),
            "disconnect" (``ConnectionResetError`` mid-stream — the
            resume-by-offset case), "half_open" (the connection opened
            but the response body never arrives: fires on stream
            reads, ``seconds`` delay then ``TimeoutError``)
  dispatch  fire on ``step()`` calls numbered >= this (1-based count
            per engine, probes included)
  call      fire on gateway->agent transport calls numbered >= this
            (1-based count per stub; heartbeats, submits and stream
            connects all count)
  request   fire when this ENGINE request id is admitted (through the
            gateway, engine ids are the replica's own deterministic
            0,1,2... sequence; the breaker probe admits id
            ``"__probe__"``, so a plan can keep probes failing) — or,
            transport side, when the stub submits/streams this id
  seconds   wedge/delay/black-hole duration
  times     firings before the fault is spent (default 1; -1 = every
            match — a permanently broken replica / partitioned host)
  replica   restrict an env fault to one replica index (None = all)

A fired fault is logged loudly; ``InjectedFault`` subclasses
``RuntimeError`` so nothing upstream special-cases it — it takes the
exact path a real dispatch failure would. Transport faults raise the
REAL network exception types (``ConnectionRefusedError``,
``ConnectionResetError``, ``TimeoutError``) for the same reason: the
stub's retry/backoff/lease machinery must not be able to tell an
injected partition from a real one.

One ``TONY_SERVE_FAULTS`` value can mix both kinds: the engine arms
``FaultPlan.from_env`` (engine ops only) and the gateway-side stub
arms ``FaultPlan.transport_from_env`` (transport ops only), so a
chaos round can kill replica 0's dispatches AND black-hole replica
1's network from a single env var.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any

log = logging.getLogger(__name__)

ENV_VAR = "TONY_SERVE_FAULTS"

# engine-side ops (hooked at Server.step()/admission) vs transport-side
# ops (hooked at the remote stub's HTTP layer) — one env var carries
# both, each consumer arms only its own kind
ENGINE_OPS = frozenset({"fail", "wedge"})
TRANSPORT_OPS = frozenset({"refuse", "blackhole", "delay", "disconnect",
                           "half_open"})
# transport ops that fire on the per-call hook vs the per-stream-read
# hook (half_open = the connection opened, the body never arrives).
# blackhole fires on BOTH: a partitioned host's already-open streams
# stop delivering exactly like its new connections do.
_CALL_OPS = frozenset({"refuse", "blackhole", "delay"})
_STREAM_OPS = frozenset({"disconnect", "half_open", "delay", "blackhole"})


class InjectedFault(RuntimeError):
    """The deterministic stand-in for a dead dispatch. Deliberately a
    plain ``RuntimeError`` subclass: supervision must treat it exactly
    like a real failure, or the tests prove nothing."""


@dataclass
class Fault:
    """One pre-declared failure. See the module docstring for field
    semantics; a fault needs at least one trigger (``dispatch`` or
    ``request``)."""

    op: str = "fail"
    dispatch: int | None = None
    call: int | None = None
    request: Any = None
    seconds: float = 0.0
    times: int = 1
    replica: int | None = None

    def __post_init__(self):
        if self.op not in ENGINE_OPS | TRANSPORT_OPS:
            raise ValueError(
                f"fault op must be one of "
                f"{sorted(ENGINE_OPS | TRANSPORT_OPS)}, got {self.op!r}")
        if self.dispatch is None and self.call is None \
                and self.request is None:
            raise ValueError(
                "fault needs a trigger: dispatch, call or request")
        if self.op in ENGINE_OPS and self.call is not None:
            raise ValueError(
                f"engine fault {self.op!r} cannot use the transport "
                f"'call' trigger (use 'dispatch' or 'request')")
        if self.op in TRANSPORT_OPS and self.dispatch is not None:
            raise ValueError(
                f"transport fault {self.op!r} cannot use the engine "
                f"'dispatch' trigger (use 'call' or 'request')")
        if self.op in ("wedge", "delay") and self.seconds <= 0:
            raise ValueError(f"{self.op} fault needs seconds > 0")


class FaultPlan:
    """The engine-side hook object: owns its faults plus a dispatch
    counter (one per engine — probes advance it too, so a spent fault
    lets the breaker probe succeed while ``times=-1`` keeps a replica
    down through every probe)."""

    def __init__(self, faults):
        self.faults = list(faults)
        self.n_dispatches = 0
        self.n_calls = 0
        self.fired = 0

    # --------------------------------------------------- construction

    @classmethod
    def from_env(cls, replica: int | None = None, env=None,
                 ops: frozenset = ENGINE_OPS) -> "FaultPlan | None":
        """Parse ``TONY_SERVE_FAULTS`` (a JSON fault object or list)
        into the plan addressed to ``replica`` — None when the variable
        is unset/empty or no fault targets this replica. ``ops``
        selects the consumer's kind (engine ops by default — the
        gateway-side stub arms ``transport_from_env``); entries of the
        other kind are validated but not armed here. Invalid specs
        raise loudly: a chaos run with a silently ignored typo'd fault
        would assert against a fault-free gateway."""
        spec = (os.environ if env is None else env).get(ENV_VAR, "").strip()
        if not spec:
            return None
        try:
            docs = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"{ENV_VAR} is not valid JSON: {e}") from None
        if isinstance(docs, dict):
            docs = [docs]
        faults = []
        for d in docs:
            if not isinstance(d, dict):
                raise ValueError(f"{ENV_VAR} entries must be objects: {d!r}")
            f = Fault(**d)
            if f.op not in ops:
                continue
            if f.replica is None or replica is None or f.replica == replica:
                faults.append(f)
        return cls(faults) if faults else None

    @classmethod
    def transport_from_env(cls, replica: int | None = None,
                           env=None) -> "FaultPlan | None":
        """The gateway-side arming point: transport faults addressed
        to ``replica``'s stub (``gateway/remote.RemoteServer``)."""
        return cls.from_env(replica, env=env, ops=TRANSPORT_OPS)

    @classmethod
    def fail_at(cls, dispatch: int, times: int = 1) -> "FaultPlan":
        return cls([Fault("fail", dispatch=dispatch, times=times)])

    @classmethod
    def wedge_at(cls, dispatch: int, seconds: float,
                 times: int = 1) -> "FaultPlan":
        return cls([Fault("wedge", dispatch=dispatch, seconds=seconds,
                          times=times)])

    @classmethod
    def fail_request(cls, request, times: int = 1) -> "FaultPlan":
        return cls([Fault("fail", request=request, times=times)])

    # --------------------------------------------------------- firing

    def _fire(self, fault: Fault, what: str) -> None:
        if fault.times > 0:
            fault.times -= 1
        self.fired += 1
        if fault.op in ("wedge", "delay"):
            log.warning("fault injection: %s %.2fs at %s",
                        "wedging" if fault.op == "wedge" else "delaying",
                        fault.seconds, what)
            time.sleep(fault.seconds)
            return
        log.warning("fault injection: %s at %s", fault.op, what)
        if fault.op == "refuse":
            raise ConnectionRefusedError(
                f"injected connection refusal at {what}")
        if fault.op == "disconnect":
            raise ConnectionResetError(f"injected disconnect at {what}")
        if fault.op in ("blackhole", "half_open"):
            # the realistic shape: nothing arrives until the caller's
            # read timeout — the optional seconds model that wait
            # without making tests pay a real socket timeout
            if fault.seconds > 0:
                time.sleep(fault.seconds)
            raise TimeoutError(f"injected {fault.op} at {what}")
        raise InjectedFault(f"injected failure at {what}")

    def on_dispatch(self) -> None:
        """Hook at the top of ``Server.step()``; counts scheduler
        dispatches and fires any armed dispatch-triggered fault."""
        self.n_dispatches += 1
        for f in self.faults:
            if f.times == 0 or f.dispatch is None:
                continue
            if self.n_dispatches >= f.dispatch:
                self._fire(f, f"dispatch {self.n_dispatches}")

    def on_admit(self, request_id) -> None:
        """Hook before a request's prefill admission dispatch."""
        for f in self.faults:
            if f.times == 0 or f.request is None:
                continue
            if f.request == request_id:
                self._fire(f, f"admit of request {request_id!r}")

    # ------------------------------------------------------- transport

    def on_call(self, what: str, request=None) -> None:
        """Hook before the remote stub issues one HTTP call (submit /
        stream connect / heartbeat / reset / drain — all count).
        Fires call-count-triggered refuse/blackhole/delay faults, and
        request-triggered ones when ``request`` names the engine id
        the call is about."""
        self.n_calls += 1
        for f in self.faults:
            if f.times == 0 or f.op not in _CALL_OPS:
                continue
            if f.call is not None and self.n_calls >= f.call:
                self._fire(f, f"transport call {self.n_calls} ({what})")
            elif f.request is not None and request is not None \
                    and f.request == request:
                self._fire(f, f"transport call for request {request!r} "
                              f"({what})")

    def on_stream(self, what: str, request=None) -> None:
        """Hook per stream READ (one NDJSON line) on the remote stub:
        disconnect-mid-stream and half-open land here — after the
        connection succeeded, while the body flows. Shares the call
        counter's trigger numbering (``call`` = the connect's number,
        so "disconnect the stream call 3 opened" composes)."""
        for f in self.faults:
            if f.times == 0 or f.op not in _STREAM_OPS:
                continue
            if f.call is not None and self.n_calls >= f.call:
                self._fire(f, f"stream read ({what})")
            elif f.request is not None and request is not None \
                    and f.request == request:
                self._fire(f, f"stream read for request {request!r} "
                              f"({what})")
