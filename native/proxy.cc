// tony_proxy: threaded TCP byte-pump proxy (native implementation).
//
// Reference behavior: tony-proxy ProxyServer.java:21-91 — accept on a local
// gateway port, dial the cluster host, pump bytes both ways, one thread per
// direction. Used by the notebook submitter to tunnel Jupyter/TensorBoard
// from outside the TPU-VM network. Prints "LISTENING <port>" on stdout once
// bound so the Python wrapper (tony_tpu/proxy/proxy.py) can pick up an
// ephemeral port.
//
// Usage: tony_proxy <local_port|0> <remote_host> <remote_port>

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>

namespace {

void pump(int src, int dst) {
  char buf[65536];
  for (;;) {
    ssize_t n = ::recv(src, buf, sizeof(buf), 0);
    if (n <= 0) break;
    ssize_t off = 0;
    while (off < n) {
      ssize_t w = ::send(dst, buf + off, n - off, 0);
      if (w <= 0) { ::shutdown(src, SHUT_RDWR); goto done; }
      off += w;
    }
  }
done:
  ::shutdown(dst, SHUT_RDWR);
  ::close(src);
}

int dial(const char* host, int port) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (::getaddrinfo(host, port_s.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  return fd;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr, "usage: %s <local_port|0> <remote_host> <remote_port>\n",
                 argv[0]);
    return 2;
  }
  int local_port = std::atoi(argv[1]);
  const char* remote_host = argv[2];
  int remote_port = std::atoi(argv[3]);

  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(local_port));
  if (::bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(srv, 16) != 0) {
    std::perror("bind/listen");
    return 1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(srv, reinterpret_cast<sockaddr*>(&addr), &len);
  std::printf("LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  for (;;) {
    int client = ::accept(srv, nullptr, nullptr);
    if (client < 0) continue;
    ::setsockopt(client, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int upstream = dial(remote_host, remote_port);
    if (upstream < 0) {
      ::close(client);
      continue;
    }
    ::setsockopt(upstream, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(pump, client, upstream).detach();
    std::thread(pump, upstream, client).detach();
  }
}
