"""Per-request trace model: spans, attempts, Chrome trace-event export.

The serving analog of TonY's per-task history record (PAPER.md L4/L6:
every job leaves an inspectable trail), at request granularity: a
``RequestTrace`` is a tree of timed spans accumulated while a request
moves through the gateway — http-receive, route, then one ATTEMPT span
per engine run (a failover produces a second attempt on a different
replica, fenced by its epoch), each holding queue-wait, admit
(prefix-lookup / prefill with its bucket / hit-admit), and one span per
decode dispatch the request rode (chunk vs spec-verify). The trace
answers the question counters cannot: *where did this request's time
go* — and for a failed-over request, *both* attempts live in ONE trace.

Design constraints, in order:

- **Always-on-cheap**: span append is a lock + a dataclass. No string
  formatting, no export work, nothing proportional to trace size on
  the hot path; export cost is paid only when somebody asks
  (``/debug/trace/<id>``).
- **Failover-safe**: the replica thread appending decode spans and the
  supervisor ending an attempt (steal) race; all structural mutation
  runs under the trace's own lock, and a span appended to an attempt
  that was already ended is DROPPED — the tracing analog of the epoch
  fence discarding a dead epoch's output. A dropped span can only come
  from a stale owner, and its tokens were re-run (and re-traced) on
  the failover attempt.
- **One clock**: spans record ``time.monotonic()`` (the clock every
  gateway timestamp already uses); the trace stores a wall-clock
  anchor at creation so export converts to epoch microseconds — the
  Chrome/Perfetto ``ts`` convention — without ever mixing clocks
  inside the invariants.

Export is standard Chrome trace-event JSON (``{"traceEvents": [...]}``,
"X" complete events): ``chrome://tracing`` and https://ui.perfetto.dev
load it directly. ``pid`` is the replica that ran the span's attempt,
``tid`` the attempt ordinal — a failover renders as the request
hopping rows mid-flight.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Span:
    """One timed region. ``t0``/``t1`` are ``time.monotonic()`` seconds
    (``t1`` None while open); ``tags`` is a small flat dict of
    JSON-able values; children nest strictly inside the parent."""

    name: str
    t0: float
    t1: float | None = None
    tags: dict = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)

    @property
    def dur_s(self) -> float:
        return (self.t1 - self.t0) if self.t1 is not None else 0.0


class RequestTrace:
    """Span accumulator for one request's whole life, failovers
    included. All mutation is serialized by an internal lock (the
    replica thread, the supervisor's steal path, and the delivery path
    all write). See the module docstring for the drop rule."""

    def __init__(self, request_id: Any, t0: float | None = None,
                 max_spans: int = 4096):
        self._lock = threading.Lock()
        self.request_id = request_id
        t0 = time.monotonic() if t0 is None else t0
        # wall anchor: export maps monotonic -> epoch microseconds
        self._wall0 = time.time() - (time.monotonic() - t0)
        self.root = Span("request", t0,
                         tags={"request_id": str(request_id)})
        self._attempt: Span | None = None  # the open attempt, if any
        self.n_attempts = 0
        self.dropped = 0  # spans discarded as stale (see module doc)
        # memory bound: a 2048-token generation at chunk_steps=1 rides
        # ~2048 decode dispatches; past the cap further spans are
        # counted, not stored, so a trace ring of marathon requests
        # cannot grow without bound
        self.max_spans = max(1, max_spans)
        self._n_spans = 0
        self.truncated = 0  # spans past max_spans (counted, not kept)
        self.done = False

    # ------------------------------------------------------- recording

    def add(self, name: str, t0: float, t1: float | None = None,
            *, attempt: bool | None = None,
            attempt_key: tuple | None = None, clamp: bool = False,
            **tags) -> None:
        """Append a span. ``attempt=True`` targets the OPEN attempt
        (dropped when none is open — a stale owner's late record);
        default targets the open attempt when one exists, else the
        root. ``t1`` defaults to ``t0`` (instant event).

        ``attempt_key=(replica, epoch)`` is the airtight form of the
        drop rule: the span lands only if the open attempt carries
        exactly those tags, checked ATOMICALLY under the trace lock —
        a stale owner whose snapshot raced a steal + re-placement
        (attempt already re-opened on the survivor) is dropped instead
        of mis-attributed to the new attempt.

        ``clamp=True`` clamps the span into its parent's window and
        behind the previous sibling's start, preserving the structural
        invariants (children nest, siblings monotonic) for timestamps
        that arrive from ANOTHER CLOCK: remote dispatch records are
        offset-corrected by an RTT-midpoint estimate whose error can
        legitimately place a span a few ms outside the attempt — the
        correction is honest-but-uncertain, and a debug surface must
        stay well-formed under that uncertainty."""
        span = Span(name, t0, t0 if t1 is None else t1, tags)
        with self._lock:
            if self.done:
                self.dropped += 1
                return
            if attempt_key is not None:
                parent = self._attempt
                if parent is None or attempt_key != (
                        parent.tags.get("replica"),
                        parent.tags.get("epoch")):
                    self.dropped += 1
                    return
            elif attempt is False:
                parent = self.root
            else:
                parent = self._attempt
                if parent is None:
                    if attempt:  # attempt-only span with no open attempt
                        self.dropped += 1
                        return
                    parent = self.root
            if self._n_spans >= self.max_spans:
                self.truncated += 1
                return
            if clamp:
                lo = parent.t0
                if parent.children:
                    lo = max(lo, parent.children[-1].t0)
                span.t0 = max(span.t0, lo)
                span.t1 = max(span.t1, span.t0)
                if parent.t1 is not None:
                    span.t0 = min(span.t0, parent.t1)
                    span.t1 = min(span.t1, parent.t1)
            self._n_spans += 1
            parent.children.append(span)

    def begin_attempt(self, replica: int, epoch: int,
                      t0: float | None = None, **tags) -> None:
        """Open attempt N on ``replica`` (its epoch is the fencing tag
        the failover story revolves around). Extra ``tags`` (the
        placement's ``host``, say) ride on the attempt span. An
        attempt already open is ended first — belt and braces; the
        supervisor normally ends it at the steal."""
        t0 = time.monotonic() if t0 is None else t0
        with self._lock:
            if self.done:
                self.dropped += 1
                return
            if self._attempt is not None and self._attempt.t1 is None:
                self._attempt.t1 = self._cover(self._attempt, t0)
            self.n_attempts += 1
            span = Span(f"attempt-{self.n_attempts}", t0,
                        tags={"replica": replica, "epoch": epoch,
                              **tags})
            self.root.children.append(span)
            self._attempt = span

    @staticmethod
    def _cover(span: Span, t1: float) -> float:
        """A close time that COVERS the span's children: remote spans
        carry offset-corrected timestamps whose estimation error can
        place a dispatch's end a fraction of a ms past the gateway's
        own delivery instant — the attempt genuinely covered that
        dispatch, so the close extends rather than orphaning it."""
        for c in span.children:
            t1 = max(t1, c.t0 if c.t1 is None else c.t1)
        return t1

    def end_attempt(self, t1: float | None = None, **tags) -> None:
        """Close the open attempt (delivery, shed, or the supervisor's
        steal). No-op when none is open."""
        t1 = time.monotonic() if t1 is None else t1
        with self._lock:
            if self._attempt is not None and self._attempt.t1 is None:
                self._attempt.t1 = self._cover(self._attempt, t1)
                self._attempt.tags.update(tags)
            self._attempt = None

    def finish(self, t1: float | None = None, **tags) -> None:
        """Terminal: close the open attempt and the root. After this
        every further append is dropped — a late span must never mutate
        an exported trace."""
        t1 = time.monotonic() if t1 is None else t1
        with self._lock:
            if self.done:
                return
            if self._attempt is not None and self._attempt.t1 is None:
                self._attempt.t1 = self._cover(self._attempt, t1)
            self._attempt = None
            self.root.t1 = self._cover(self.root, t1)
            self.root.tags.update(tags)
            self.done = True

    # --------------------------------------------------------- export

    def _us(self, t: float) -> float:
        return (self._wall0 + t) * 1e6

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (the dict; ``json.dumps`` it). Every
        span becomes an "X" complete event; open spans (an in-flight
        request inspected early) are clamped to the latest timestamp
        seen so the export is always well-formed."""
        with self._lock:
            events: list[dict] = []
            threads: dict[int, int] = {}  # tid -> replica (pid)

            def clamp(span: Span) -> float:
                end = span.t0 if span.t1 is None else span.t1
                for c in span.children:
                    end = max(end, clamp(c))
                return end

            def walk(span: Span, pid: int, tid: int) -> None:
                t1 = clamp(span)
                events.append({
                    "name": span.name, "ph": "X", "cat": "serving",
                    "ts": self._us(span.t0),
                    "dur": max(0.0, (t1 - span.t0) * 1e6),
                    "pid": pid, "tid": tid,
                    "args": dict(span.tags),
                })
                for c in span.children:
                    walk(c, pid, tid)

            tid = 0
            threads[0] = -1
            walk_children = list(self.root.children)
            # the root + non-attempt children render on tid 0; each
            # attempt gets its own tid and its replica as pid
            root_end = clamp(self.root)
            events.append({
                "name": self.root.name, "ph": "X", "cat": "serving",
                "ts": self._us(self.root.t0),
                "dur": max(0.0, (root_end - self.root.t0) * 1e6),
                "pid": -1, "tid": 0, "args": dict(self.root.tags),
            })
            hosts: dict[int, str] = {-1: "gateway"}
            for child in walk_children:
                if child.name.startswith("attempt-"):
                    tid += 1
                    pid = int(child.tags.get("replica", -1))
                    threads[tid] = pid
                    # the placement's host (agent address | "local")
                    # names the pid row: a fleet trace must say WHICH
                    # MACHINE each attempt ran on, not just the index
                    host = child.tags.get("host")
                    if host is not None:
                        hosts[pid] = f"replica {pid} ({host})"
                    walk(child, pid, tid)
                else:
                    walk(child, -1, 0)
            meta = [{"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": t, "args": {"name": "request" if t == 0
                                        else f"attempt-{t}"}}
                    for t, pid in threads.items()]
            meta.extend({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": name}}
                        for pid, name in sorted(hosts.items()))
            return {
                "displayTimeUnit": "ms",
                "otherData": {"request_id": str(self.request_id),
                              "attempts": self.n_attempts,
                              "dropped_spans": self.dropped,
                              "truncated_spans": self.truncated},
                "traceEvents": meta + events,
            }

    def to_json(self) -> str:
        return json.dumps(self.to_chrome())


def check_invariants(trace: RequestTrace) -> list[str]:
    """Structural validation, used by tests and debug tooling. Returns
    a list of problems (empty = healthy):

    - every span is closed with ``t1 >= t0``;
    - children lie inside their parent's window;
    - siblings appear in monotonic ``t0`` order (spans are appended in
      wall order by construction — a violation means a clock or
      locking bug).
    """
    problems: list[str] = []

    def walk(span: Span, path: str) -> None:
        here = f"{path}/{span.name}"
        if span.t1 is None:
            problems.append(f"{here}: span never closed")
            return
        if span.t1 < span.t0:
            problems.append(f"{here}: t1 {span.t1} < t0 {span.t0}")
        prev = None
        for c in span.children:
            if c.t0 < span.t0 - 1e-9 or (
                    c.t1 is not None and span.t1 is not None
                    and c.t1 > span.t1 + 1e-9):
                problems.append(
                    f"{here}/{c.name}: child [{c.t0}, {c.t1}] outside "
                    f"parent [{span.t0}, {span.t1}]")
            if prev is not None and c.t0 < prev - 1e-9:
                problems.append(
                    f"{here}/{c.name}: sibling t0 {c.t0} before "
                    f"previous sibling t0 {prev}")
            prev = c.t0
            walk(c, here)

    walk(trace.root, "")
    return problems


class TraceBuffer:
    """Bounded ring of recently finished traces, keyed by request id
    (stringified — the id a client passes or the UUID the front door
    minted). ``put`` evicts the oldest past ``capacity``; a re-used id
    replaces its old trace (last-writer-wins, matching /stats rows)."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._traces: OrderedDict[str, RequestTrace] = OrderedDict()

    def put(self, trace: RequestTrace) -> None:
        key = str(trace.request_id)
        with self._lock:
            self._traces.pop(key, None)
            self._traces[key] = trace
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)

    def get(self, request_id: Any) -> RequestTrace | None:
        with self._lock:
            return self._traces.get(str(request_id))

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._traces)

    def summaries(self) -> list[dict]:
        """The ``GET /debug/traces`` listing: one row per buffered
        trace — request id, attempt count, and the TERMINAL tags the
        root span carries (outcome, finish_reason/status, token
        counts, latency) — so an operator can find the trace worth
        opening without already knowing its request_id."""
        with self._lock:
            traces = list(self._traces.values())
        out = []
        for t in traces:
            tags = {k: v for k, v in t.root.tags.items()
                    if k != "request_id"}
            # "host": which machine(s) the request's placements ran on
            # (agent address | "local"), matching the ``host`` field
            # requests.jsonl rows carry — without it a listing cannot
            # tell two hosts' requests apart
            hosts = [c.tags.get("host") for c in t.root.children
                     if c.name.startswith("attempt-")
                     and c.tags.get("host") is not None]
            # "placements": replica placements (attempt spans) — the
            # root's own "attempts" terminal tag keeps its metrics
            # meaning (FAILED engine runs) and must not be clobbered
            out.append({"request_id": str(t.request_id),
                        "placements": t.n_attempts,
                        "host": hosts[-1] if hosts else None,
                        "done": t.done, **tags})
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)
